"""Deep profiling layer (ISSUE 6): roofline accounting from XLA cost
analysis, on-demand profiler capture, device-memory telemetry, and SLO
health.

Acceptance scenarios covered here:
- cost-analysis FLOPs agree with the analytic count within 10% on a
  matmul-dominated trainer (the MFU-agreement criterion with the
  denominator held fixed);
- the HBM-utilization gauge equals XLA bytes / measured seconds over
  the installed session roofline — the live %-of-achievable number;
- `POST /profile` returns a loadable trace artifact; overlapping
  captures get 409; artifact rotation is bounded; an idle capture adds
  zero steady-state machinery (and the predict path measures within
  noise of a capture-free run);
- `/healthz` flips ready → not-ready → ready through a SUPERVISOR
  quarantine/revival round trip;
- a raising gauge callback degrades to NaN + an error counter, never a
  dead scrape.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from analytics_zoo_tpu.common import faults
from analytics_zoo_tpu.observability import (CaptureActiveError,
                                             DeviceMemoryLeak,
                                             DeviceMemoryWatcher,
                                             MetricsReporter,
                                             ProfileCapture,
                                             RooflineAccountant,
                                             SLOObjectives, SLOTracker,
                                             StackSampler, cost_of,
                                             get_accountant, get_registry,
                                             leak_check, load_trace_events,
                                             render_prometheus,
                                             set_session_roofline)
from analytics_zoo_tpu.observability import roofline as roofline_mod
from analytics_zoo_tpu.observability.registry import MetricsRegistry
from analytics_zoo_tpu.serving import (ClusterServing, InferenceModel,
                                       InputQueue, MemoryBroker, OutputQueue)
from analytics_zoo_tpu.serving.http_frontend import FrontEnd


@pytest.fixture(autouse=True)
def _clean_session_roofline():
    """Session roofline is process-global state like the registry —
    never leak one test's calibration into the next."""
    yield
    with roofline_mod._session_lock:
        roofline_mod._session["hbm_gbps"] = None
        roofline_mod._session["tflops"] = None
    faults.clear()


def _wait_until(cond, timeout_s=15.0, interval_s=0.01, msg="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval_s)
    raise AssertionError(f"timed out waiting for {msg}")


def _get(url, timeout=10):
    return urllib.request.urlopen(url, timeout=timeout)


def _post(url, data=b"", timeout=30):
    return urllib.request.urlopen(
        urllib.request.Request(url, data=data), timeout=timeout)


# ---------------------------------------------------------------------------
# Roofline accounting
# ---------------------------------------------------------------------------
class TestCostOf:
    def test_matmul_flops_exact(self):
        m, k, n = 32, 64, 16
        f = jax.jit(lambda p, x: x @ p)
        p = np.zeros((k, n), np.float32)
        x = np.zeros((m, k), np.float32)
        c = cost_of(f.lower(p, x))
        assert c is not None
        assert c.flops == pytest.approx(2 * m * k * n, rel=0.01)
        # inputs + output must move at least once
        assert c.bytes >= 4 * (m * k + k * n + m * n)

    def test_lowered_and_compiled_agree(self):
        f = jax.jit(lambda p, x: jax.numpy.tanh(x @ p))
        p = np.zeros((16, 16), np.float32)
        x = np.zeros((4, 16), np.float32)
        low = f.lower(p, x)
        c_low = cost_of(low)
        c_comp = cost_of(low.compile())
        assert c_low.flops == c_comp.flops
        assert c_low.bytes == c_comp.bytes

    def test_garbage_degrades_to_none(self):
        assert cost_of(None) is None

        class Broken:
            def cost_analysis(self):
                raise RuntimeError("no cost model on this backend")
        assert cost_of(Broken()) is None


@pytest.fixture()
def isolated_registry():
    """A fresh MetricsRegistry per test: the accountant math tests
    assert EXACT counter values, and the process-global registry
    accumulates roofline series from any training that ran earlier in
    the same pytest process (e.g. test_fault_tolerance's auto-resume
    fits) — cross-file contamination that made these flake depending on
    collection order."""
    return MetricsRegistry()


class TestAccountant:
    def test_account_math_and_session_roofline(self, isolated_registry):
        reg = isolated_registry
        acct = RooflineAccountant(registry=reg)
        # a deterministic denominator: achieved GB/s and TFLOP/s known
        set_session_roofline(hbm_gbps=100.0, tflops=10.0, registry=reg)
        acct.account("train", flops=2e12, bytes_=20e9, seconds=2.0)
        assert reg.get("roofline_flops_total").value(
            kind="train") == 2e12
        assert reg.get("roofline_achieved_tflops").value(
            kind="train") == pytest.approx(1.0)
        assert reg.get("roofline_achieved_hbm_gbps").value(
            kind="train") == pytest.approx(10.0)
        # 1 TFLOP/s of a 10 TFLOP/s roofline; 10 GB/s of 100 GB/s
        assert reg.get("roofline_mfu").value(
            kind="train") == pytest.approx(0.1)
        assert reg.get("roofline_hbm_utilization").value(
            kind="train") == pytest.approx(0.1)
        assert reg.get("roofline_session_hbm_gbps").value() == 100.0

    def test_reset_starts_gauges_clean_but_counters_accumulate(
            self, isolated_registry):
        reg = isolated_registry
        acct = RooflineAccountant(registry=reg)
        acct.account("serving", 100.0, 100.0, 1.0)
        before = reg.get("roofline_flops_total").value(kind="serving")
        acct.reset("serving")
        acct.account("serving", 300.0, 300.0, 1.0)
        assert acct.snapshot("serving")["flops"] == 300.0   # clean rate
        assert reg.get("roofline_flops_total").value(
            kind="serving") == before + 300.0               # monotonic

    def test_account_never_raises(self):
        acct = RooflineAccountant()
        acct.account("serving", -1.0, 0.0, 0.0)     # degenerate inputs
        acct.account("serving", 1.0, 1.0, -5.0)
        assert acct.snapshot("serving")["seconds"] == 0.0


class TestServingRoofline:
    def test_warmup_harvests_and_predict_accounts(self):
        W = np.random.RandomState(0).randn(16, 8).astype(np.float32)
        im = InferenceModel().load_fn(lambda p, x: x @ p, W)
        im.warmup(np.zeros((16,), np.float32), buckets=[1, 2, 4])
        assert len(im._exec_cost) == 3          # one cost per bucket
        acct = get_accountant()
        before = acct.snapshot("serving")["flops"]
        im.predict(np.ones((2, 16), np.float32))
        after = acct.snapshot("serving")
        bucket_cost = im._exec_cost[im._cost_key(
            np.zeros((2, 16), np.float32))]
        assert after["flops"] == pytest.approx(
            before + bucket_cost.flops)
        assert after["seconds"] > 0

    def test_replicated_pool_accounts_per_batch(self, devices8):
        W = np.random.RandomState(0).randn(8, 4).astype(np.float32)
        im = InferenceModel(num_replicas=2).load_fn(lambda p, x: x @ p, W)
        try:
            im.warmup(np.zeros((8,), np.float32), buckets=[4])
            acct = get_accountant()
            base = acct.snapshot("serving")["flops"]
            pends = [im.predict_async(np.ones((4, 8), np.float32))
                     for _ in range(4)]
            for p in pends:
                p.result()
            cost = next(iter(im._exec_cost.values()))
            assert acct.snapshot("serving")["flops"] == pytest.approx(
                base + 4 * cost.flops)
        finally:
            im.close()

    def test_unwarmed_model_pays_and_publishes_nothing(self):
        W = np.zeros((4, 2), np.float32)
        im = InferenceModel().load_fn(lambda p, x: x @ p, W)
        im.predict(np.ones((2, 4), np.float32))
        assert im._exec_cost == {}
        assert get_accountant().snapshot("serving")["seconds"] == 0.0


class TestTrainerRoofline:
    def _fit_mlp(self, n_layers, d=64, batch=32, n=128, **fit_kw):
        from analytics_zoo_tpu.keras import Sequential
        from analytics_zoo_tpu.keras import layers as L
        from analytics_zoo_tpu.learn.estimator import Estimator
        layers = [L.Dense(d, input_shape=(d,))]
        layers += [L.Dense(d) for _ in range(n_layers - 1)]
        model = Sequential(layers)
        est = Estimator.from_keras(model, optimizer="sgd", loss="mse")
        rs = np.random.RandomState(0)
        x = rs.rand(n, d).astype(np.float32)
        y = rs.rand(n, d).astype(np.float32)
        est.fit((x, y), epochs=1, batch_size=batch, **fit_kw)
        return d, batch

    def test_cost_flops_agree_with_analytic_within_10pct(self):
        """The MFU-agreement acceptance with the denominator held
        fixed: MFU = flops / (dt * peak), and dt/peak are shared, so
        agreement of the FLOP counts IS agreement of the MFUs. A deep
        matmul-dominated MLP is where the analytic 6-flops/param/token
        model is exact (the first layer skips its dx pass, hence deep)."""
        n_layers = 6
        d, batch = self._fit_mlp(n_layers)
        snap = get_accountant().snapshot("train")
        assert snap["flops"] > 0
        calls = 128 // 32
        cost_per_step = snap["flops"] / calls
        analytic = 6.0 * (n_layers * d * d) * batch
        assert cost_per_step == pytest.approx(analytic, rel=0.10)

    def test_hbm_utilization_is_live_fraction_of_session_roofline(self):
        """The BENCH-r05-style number with zero manual math: install a
        session roofline, fit, and the gauge must equal XLA bytes /
        measured seconds / the participating slice's roofline (per-chip
        bound × the step program's device span — the fit runs data-
        parallel on the conftest 8-device mesh, ISSUE 7)."""
        set_session_roofline(hbm_gbps=50.0, tflops=5.0)
        self._fit_mlp(2)
        snap = get_accountant().snapshot("train")
        g = get_registry().get("roofline_hbm_utilization")
        expected = snap["bytes"] / snap["seconds"] \
            / (50.0 * 1e9 * snap["devices"])
        assert snap["devices"] == jax.device_count()
        assert g.value(kind="train") == pytest.approx(expected, rel=1e-6)
        assert expected > 0

    def test_multi_step_run_scales_to_per_step_cost(self):
        """XLA cost analysis counts a scan body once, so a
        steps_per_run=k fit must account the SAME epoch totals as the
        single-step fit of the same workload — the iteration-count
        scaling, not the call count, owns the multiplier."""
        self._fit_mlp(2)
        single = get_accountant().snapshot("train")["flops"]
        self._fit_mlp(2, steps_per_run=4)       # resets "train" first
        multi = get_accountant().snapshot("train")["flops"]
        assert single > 0
        assert multi == pytest.approx(single, rel=0.10)

    def test_aot_cached_step_harvests_from_executable(self, tmp_path):
        """With the persistent compile cache active the step is an
        AOTFunctionCache: the tracker's post-call harvest reads
        cost_analysis straight off the built executable (the
        executables() accessor), and the roofline accounts normally."""
        get_accountant().reset("train")
        self._fit_mlp(2, compile_cache_dir=str(tmp_path))
        snap = get_accountant().snapshot("train")
        assert snap["flops"] > 0 and snap["seconds"] > 0

    def test_env_gate_disables(self, monkeypatch):
        monkeypatch.setenv("ZOO_ROOFLINE", "0")
        get_accountant().reset("train")
        self._fit_mlp(1)
        assert get_accountant().snapshot("train")["seconds"] == 0.0


# ---------------------------------------------------------------------------
# On-demand capture
# ---------------------------------------------------------------------------
class TestProfileCapture:
    def test_capture_produces_loadable_artifact(self, tmp_path):
        cap = ProfileCapture(str(tmp_path), max_artifacts=4)
        f = jax.jit(lambda x: x * 2)
        art = cap.start(tag="unit")
        assert cap.active
        np.asarray(f(np.ones(8, np.float32)))
        manifest = cap.stop()
        assert not cap.active
        assert manifest["dir"] == art
        assert manifest["files"]
        events = load_trace_events(art)
        assert isinstance(events, list) and events

    def test_overlap_raises_and_lock_releases(self, tmp_path):
        cap = ProfileCapture(str(tmp_path))
        cap.start()
        with pytest.raises(CaptureActiveError):
            cap.start()
        cap.stop()
        cap.start()                       # single-flight lock released
        cap.stop()

    def test_single_flight_is_process_wide(self, tmp_path):
        """jax.profiler's session is process-global, so two ProfileCapture
        INSTANCES (the frontend's and a fit's profile_steps window) must
        share one guard — the loser gets the documented
        CaptureActiveError, not an opaque profiler failure."""
        a = ProfileCapture(str(tmp_path / "a"))
        b = ProfileCapture(str(tmp_path / "b"))
        a.start()
        try:
            with pytest.raises(CaptureActiveError):
                b.start()
        finally:
            a.stop()

    def test_rotation_bounded(self, tmp_path):
        cap = ProfileCapture(str(tmp_path), max_artifacts=2)
        for i in range(4):
            cap.start(tag=f"r{i}")
            cap.stop()
        arts = cap.artifacts()
        assert len(arts) == 2
        # newest survive
        assert arts[-1].endswith("r3")
        assert arts[0].endswith("r2")

    def test_idle_capture_adds_zero_steady_state_machinery(self):
        """Zero-overhead-when-idle is structural: an attached-but-idle
        ProfileCapture installs no hooks, runs no threads, and holds no
        profiler session — the predict path cannot pay for what does
        not exist. The timing check below is a belt-and-braces smoke
        with a deliberately loose bound (shared CI cores)."""
        W = np.random.RandomState(0).randn(32, 8).astype(np.float32)
        im = InferenceModel().load_fn(lambda p, x: x @ p, W)
        im.warmup(np.zeros((32,), np.float32), buckets=[4])
        x = np.ones((4, 32), np.float32)

        def p50(n=60):
            lat = []
            for _ in range(n):
                t0 = time.perf_counter()
                im.predict(x)
                lat.append(time.perf_counter() - t0)
            return float(np.percentile(lat, 50))

        im.predict(x)                       # warm
        base = p50()
        threads_before = {t.name for t in threading.enumerate()}
        cap = ProfileCapture(os.path.join("/tmp", "zoo-idle-probe"))
        with_idle = p50()
        assert not cap.active
        assert {t.name for t in threading.enumerate()} == threads_before
        # loose noise bound: an idle capture must not multiply latency
        assert with_idle < base * 3 + 0.005

    def test_fit_profile_steps_window(self, tmp_path):
        from analytics_zoo_tpu.keras import Sequential
        from analytics_zoo_tpu.keras import layers as L
        from analytics_zoo_tpu.learn.estimator import Estimator
        m = Sequential([L.Dense(8, input_shape=(4,))])
        est = Estimator.from_keras(m, optimizer="sgd", loss="mse")
        x = np.random.rand(64, 4).astype(np.float32)
        y = np.random.rand(64, 8).astype(np.float32)
        hist = est.fit((x, y), epochs=1, batch_size=8,
                       profile_steps=(2, 4), profile_dir=str(tmp_path))
        arts = hist.get("profile_artifacts")
        assert arts and os.path.isdir(arts[0])
        assert load_trace_events(arts[0])

    def test_fit_profile_steps_validation(self):
        from analytics_zoo_tpu.keras import Sequential
        from analytics_zoo_tpu.keras import layers as L
        from analytics_zoo_tpu.learn.estimator import Estimator
        m = Sequential([L.Dense(4, input_shape=(4,))])
        est = Estimator.from_keras(m, optimizer="sgd", loss="mse")
        x = np.random.rand(16, 4).astype(np.float32)
        with pytest.raises(ValueError, match="profile_steps"):
            est.fit((x, x), epochs=1, batch_size=8,
                    profile_steps=(4, 2))


class TestStackSampler:
    def test_samples_matching_threads_only(self):
        stop = threading.Event()

        def spin():
            while not stop.is_set():
                sum(range(500))

        t1 = threading.Thread(target=spin, name="serving-busy-loop",
                              daemon=True)
        t2 = threading.Thread(target=spin, name="unrelated-loop",
                              daemon=True)
        t1.start()
        t2.start()
        try:
            with StackSampler(interval_s=0.002) as sampler:
                time.sleep(0.25)
            report = sampler.report()
        finally:
            stop.set()
            t1.join(timeout=2)
            t2.join(timeout=2)
        assert "serving-busy-loop" in report["threads"]
        assert "unrelated-loop" not in report["threads"]
        top = report["threads"]["serving-busy-loop"]["top"]
        assert top and top[0]["count"] >= 1
        assert "spin" in " ".join(e["frame"] for e in top)


# ---------------------------------------------------------------------------
# Device-memory telemetry
# ---------------------------------------------------------------------------
class TestDeviceMemory:
    def test_watcher_publishes_gauges(self):
        w = DeviceMemoryWatcher(interval_s=30.0)
        snap = w.sample()
        assert snap
        g = get_registry().get("device_memory_live_bytes")
        labels = [dict(k) for k in g.label_keys()]
        assert any("device" in lbl for lbl in labels)
        peak = get_registry().get("device_memory_peak_bytes")
        assert peak is not None

    def test_watcher_thread_lifecycle(self):
        w = DeviceMemoryWatcher(interval_s=0.05)
        with w:
            time.sleep(0.15)
        assert w._thread is None

    def test_leak_check_clean(self):
        with leak_check(tolerance_bytes=1 << 20):
            r = jax.numpy.ones((128, 128)) @ jax.numpy.ones((128, 128))
            r.block_until_ready()
            del r

    def test_leak_check_detects_retained_device_bytes(self):
        keep = []
        with pytest.raises(DeviceMemoryLeak, match="grew past"):
            with leak_check(tolerance_bytes=1024):
                keep.append(jax.device_put(
                    np.ones((512, 512), np.float32)))
        keep.clear()

    def test_leak_check_reports_workload_error_not_leak(self):
        with pytest.raises(RuntimeError, match="workload"):
            with leak_check(tolerance_bytes=0):
                raise RuntimeError("workload failed")


# ---------------------------------------------------------------------------
# SLO health
# ---------------------------------------------------------------------------
class TestSLOTracker:
    def _tracker(self, **kw):
        defaults = dict(latency_ms=50.0, availability=0.99, window_s=60.0)
        defaults.update(kw)
        return SLOTracker(SLOObjectives(**defaults), min_interval_s=0.0)

    def test_validation(self):
        with pytest.raises(ValueError, match="latency_ms"):
            SLOObjectives(latency_ms=-1).validate()
        with pytest.raises(ValueError, match="availability"):
            SLOObjectives(availability=1.5).validate()
        with pytest.raises(ValueError, match="window_s"):
            SLOObjectives(latency_ms=10, window_s=0).validate()
        with pytest.raises(ValueError, match="latency_quantile"):
            SLOObjectives(latency_quantile=1.0).validate()

    def test_no_data_is_vacuously_met(self):
        r = self._tracker().evaluate(force=True)
        assert r["met"] is True
        assert r["latency"]["burn_rate"] == 0.0

    def test_first_evaluation_ignores_lifetime_history(self):
        """A first /healthz poll must not report an old, fully recovered
        outage (process-lifetime counters) as a live violation: with no
        ring baseline there is no window, so the verdict is vacuous."""
        reg = get_registry()
        hist = reg.histogram("serving_batch_ms", "e2e")
        recs = reg.counter("serving_records_total", "outcomes")
        for _ in range(50):
            hist.observe(500.0)           # hours-old slow requests
        recs.inc(1000, outcome="served")
        recs.inc(50, outcome="failed")    # hours-old failures
        r = self._tracker().evaluate(force=True)
        assert r["met"] is True
        assert r["latency"]["count"] == 0
        assert r["availability"]["burn_rate"] == 0.0

    def test_burn_rates_and_gauges(self):
        reg = get_registry()
        hist = reg.histogram("serving_batch_ms", "e2e")
        recs = reg.counter("serving_records_total", "outcomes")
        tr = self._tracker()
        tr.evaluate(force=True)              # window baseline
        for _ in range(95):
            hist.observe(10.0)
        for _ in range(5):
            hist.observe(500.0)              # 5% over a p95 target: ~at
        recs.inc(100, outcome="served")      # budget
        recs.inc(2, outcome="failed")
        r = tr.evaluate(force=True)
        lat = r["latency"]
        assert lat["observed_ms"] > 0
        assert lat["burn_rate"] == pytest.approx(1.0, rel=0.25)
        avail = r["availability"]
        # 2% failure rate against a 1% budget → burn ≈ 2
        assert avail["burn_rate"] == pytest.approx(2.0, rel=0.05)
        assert avail["met"] is False
        assert r["met"] is False
        assert reg.get("slo_burn_rate").value(
            objective="availability") == pytest.approx(2.0, rel=0.05)
        assert reg.get("slo_met").value(objective="all") == 0.0

    def test_window_slides_past_old_violations(self):
        reg = get_registry()
        hist = reg.histogram("serving_batch_ms", "e2e")
        tr = self._tracker(availability=None, window_s=0.2)
        tr.evaluate(force=True)
        for _ in range(50):
            hist.observe(500.0)              # all over target
        assert tr.evaluate(force=True)["met"] is False
        time.sleep(0.3)                      # violations age out
        tr.evaluate(force=True)              # rolls the ring
        r = tr.evaluate(force=True)
        assert r["latency"]["count"] == 0
        assert r["met"] is True

    def test_auto_evaluator_detects_without_external_polls(self, caplog):
        """Violation detection must not depend on scrape cadence: the
        engine-driven auto thread keeps the window warm and flips
        slo_met on its own."""
        import logging
        reg = get_registry()
        hist = reg.histogram("serving_batch_ms", "e2e")
        tr = self._tracker(availability=None, window_s=5.0)
        tr.start_auto(interval_s=0.05)
        try:
            time.sleep(0.12)                 # baseline samples land
            for _ in range(30):
                hist.observe(500.0)          # sustained violation
            with caplog.at_level(
                    logging.WARNING,
                    logger="analytics_zoo_tpu.observability"):
                _wait_until(
                    lambda: reg.get("slo_met").value(
                        objective="all") == 0.0,
                    timeout_s=5.0, msg="auto-evaluated SLO violation")
            assert any("SLO violated" in r.getMessage()
                       for r in caplog.records)
        finally:
            tr.stop_auto()
        assert tr._auto_thread is None

    def test_engine_drives_auto_evaluation(self, devices8):
        W, fn = _make_model()
        im = InferenceModel().load_fn(fn, W)
        broker = MemoryBroker()
        serving = ClusterServing(
            im, broker=broker, batch_size=4,
            slo=SLOObjectives(latency_ms=100.0, window_s=4.0)).start()
        try:
            assert serving.slo._auto_thread is not None
        finally:
            serving.stop()
        assert serving.slo._auto_thread is None

    def test_reporter_evaluates_and_warns_once(self, caplog):
        reg = get_registry()
        hist = reg.histogram("serving_batch_ms", "e2e")
        tr = self._tracker(availability=None)
        rep = MetricsReporter(interval_s=60.0, slo=tr)
        rep._report()                        # baseline, met
        for _ in range(20):
            hist.observe(500.0)
        import logging
        with caplog.at_level(logging.WARNING,
                             logger="analytics_zoo_tpu.observability"):
            rep._report()
            rep._report()                    # still violated: no re-warn
        warns = [r for r in caplog.records
                 if "SLO violated" in r.getMessage()]
        assert len(warns) == 1
        assert reg.get("slo_met").value(objective="all") == 0.0


# ---------------------------------------------------------------------------
# /healthz + /profile over HTTP, and the supervisor round trip
# ---------------------------------------------------------------------------
def _make_model(in_dim=4, out_dim=3, seed=0):
    W = np.random.RandomState(seed).randn(in_dim, out_dim).astype(
        np.float32)
    return W, (lambda p, x: x @ p)


class TestHealthz:
    def test_frontend_without_engine_is_alive(self):
        broker = MemoryBroker()
        fe = FrontEnd(broker, None, host="127.0.0.1", port=0).start()
        try:
            r = _get(f"http://127.0.0.1:{fe.port}/healthz")
            body = json.loads(r.read())
            assert r.status == 200
            assert body["ready"] is True and body["engine"] is None
        finally:
            fe.stop()

    def test_flips_through_supervisor_quarantine_round_trip(self,
                                                            devices8):
        """The acceptance scenario: ready → not-ready → ready driven by
        the SUPERVISOR (fault-injected dispatch failures quarantine the
        whole pool; clearing the fault lets the canary probes revive
        it), observed purely through GET /healthz."""
        W, fn = _make_model()
        im = InferenceModel(num_replicas=2).load_fn(fn, W)
        broker = MemoryBroker()
        serving = ClusterServing(
            im, broker=broker, batch_size=1, batch_timeout_ms=2,
            failure_threshold=2, probe_interval_s=0.1,
            latency_floor_ms=2000.0,
            slo=SLOObjectives(latency_ms=1000.0, window_s=30.0)).start()
        fe = FrontEnd(broker, serving, host="127.0.0.1", port=0).start()
        base = f"http://127.0.0.1:{fe.port}"
        try:
            r = _get(base + "/healthz")
            body = json.loads(r.read())
            assert r.status == 200 and body["ready"] is True
            assert body["healthy_replicas"] == 2
            assert "slo" in body          # SLO status rides the payload

            # fault every replica; pump records until the supervisor has
            # quarantined the whole pool
            faults.inject("replica.dispatch", faults.Fault())
            inq = InputQueue(broker)
            deadline = time.monotonic() + 20
            while im.healthy_replicas() > 0 and \
                    time.monotonic() < deadline:
                inq.enqueue(t=np.ones((4,), np.float32))
                time.sleep(0.01)
            assert im.healthy_replicas() == 0
            with pytest.raises(urllib.error.HTTPError) as exc:
                _get(base + "/healthz")
            assert exc.value.code == 503
            payload = json.loads(exc.value.read())
            assert payload["ready"] is False
            assert "quarantined" in payload["reason"]
            assert int(exc.value.headers["Retry-After"]) >= 1
            assert payload["supervisor"]["healthy"] == 0

            # recovery: canary probes revive the pool → ready again
            faults.clear("replica.dispatch")
            _wait_until(lambda: im.healthy_replicas() == 2,
                        msg="pool revival")
            r = _get(base + "/healthz")
            assert r.status == 200
            assert json.loads(r.read())["ready"] is True
        finally:
            fe.stop()
            serving.stop()

    def test_healthz_wrong_method_is_405(self):
        broker = MemoryBroker()
        fe = FrontEnd(broker, None, host="127.0.0.1", port=0).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as exc:
                _post(f"http://127.0.0.1:{fe.port}/healthz")
            assert exc.value.code == 405
            assert exc.value.headers["Allow"] == "GET"
        finally:
            fe.stop()


class TestProfileEndpoint:
    @pytest.fixture()
    def frontend(self, tmp_path):
        W, fn = _make_model()
        im = InferenceModel().load_fn(fn, W)
        broker = MemoryBroker()
        serving = ClusterServing(im, broker=broker, batch_size=4,
                                 batch_timeout_ms=2).start()
        fe = FrontEnd(broker, serving, host="127.0.0.1", port=0,
                      profile_dir=str(tmp_path),
                      profile_max_artifacts=2).start()
        yield fe, serving, str(tmp_path)
        fe.stop()
        serving.stop()

    def test_post_profile_returns_loadable_artifact(self, frontend):
        fe, _serving, root = frontend
        r = _post(f"http://127.0.0.1:{fe.port}/profile?seconds=0.3")
        manifest = json.loads(r.read())
        assert r.status == 200
        assert manifest["dir"].startswith(root)
        assert manifest["files"]
        assert load_trace_events(manifest["dir"])
        # host stack report for the pipeline threads rides along
        assert "host_stacks" in manifest
        assert any(name.startswith("serving-")
                   for name in manifest["host_stacks"]["threads"])

    def test_overlapping_captures_get_409(self, frontend):
        fe, _serving, _root = frontend
        url = f"http://127.0.0.1:{fe.port}/profile"
        results = {}

        def first():
            results["r"] = _post(url + "?seconds=1.2").status

        t = threading.Thread(target=first)
        t.start()
        time.sleep(0.4)                   # first capture is running
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(url + "?seconds=0.2")
        assert exc.value.code == 409
        t.join()
        assert results["r"] == 200
        # and the single-flight lock released: a later capture works
        assert _post(url + "?seconds=0.2").status == 200

    def test_rotation_bound_holds_over_http(self, frontend):
        fe, _serving, root = frontend
        url = f"http://127.0.0.1:{fe.port}/profile?seconds=0.1"
        for _ in range(3):
            assert _post(url).status == 200
        dirs = [d for d in os.listdir(root)
                if os.path.isdir(os.path.join(root, d))]
        assert len(dirs) <= 2             # profile_max_artifacts=2

    def test_bad_seconds_is_400(self, frontend):
        fe, _serving, _root = frontend
        for q in ("seconds=abc", "seconds=-1", "seconds=9999"):
            with pytest.raises(urllib.error.HTTPError) as exc:
                _post(f"http://127.0.0.1:{fe.port}/profile?{q}")
            assert exc.value.code == 400

    def test_profile_enabled_false_is_404(self):
        broker = MemoryBroker()
        fe = FrontEnd(broker, None, host="127.0.0.1", port=0,
                      profile_enabled=False).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as exc:
                _post(f"http://127.0.0.1:{fe.port}/profile?seconds=0.1")
            assert exc.value.code == 404
            assert "disabled" in json.loads(exc.value.read())["error"]
        finally:
            fe.stop()


# ---------------------------------------------------------------------------
# Gauge callback hardening (ISSUE 6 satellite)
# ---------------------------------------------------------------------------
class TestGaugeHardening:
    def test_raising_callback_degrades_to_nan_everywhere(self):
        reg = get_registry()
        g = reg.gauge("flaky_provider")
        g.set_function(lambda: 1 / 0)
        g.set(3.0, which="good")
        # snapshot: NaN series, good series intact
        series = {tuple(sorted(s["labels"].items())): s["value"]
                  for s in g._series_snapshot()}
        assert np.isnan(series[()])
        assert series[(("which", "good"),)] == 3.0
        # value(): NaN, not a raise
        assert np.isnan(g.value())
        # Prometheus render survives and emits NaN
        text = render_prometheus(reg)
        assert "flaky_provider NaN" in text
        # reporter digest survives
        from analytics_zoo_tpu.observability import digest
        assert "flaky_provider" in digest(reg.snapshot())

    def test_errors_are_counted_per_gauge(self):
        reg = get_registry()
        g = reg.gauge("counted_flake")
        g.set_function(lambda: 1 / 0)
        before = 0.0
        fam = reg.get("observability_gauge_errors_total")
        if fam is not None:
            before = fam.value(gauge="counted_flake")
        g.value()
        g._series_snapshot()
        fam = reg.get("observability_gauge_errors_total")
        assert fam.value(gauge="counted_flake") == before + 2

    def test_snapshot_registers_error_counter_without_deadlock(self):
        reg = get_registry()
        g = reg.gauge("deadlock_probe")
        g.set_function(lambda: 1 / 0)
        # full-registry snapshot triggers the error path while iterating
        # families — must complete, not deadlock or raise
        snap = reg.snapshot()
        assert "deadlock_probe" in snap


# ---------------------------------------------------------------------------
# Config surface
# ---------------------------------------------------------------------------
class TestServingConfigSLO:
    def _load(self, tmp_path, body):
        cfg_path = tmp_path / "config.yaml"
        cfg_path.write_text(body)
        from analytics_zoo_tpu.serving.config import ServingConfig
        return ServingConfig.load(str(cfg_path))

    def test_slo_block_parses_and_builds(self, tmp_path):
        cfg = self._load(tmp_path, """
model:
  path: /tmp/nowhere
params:
  slo:
    latency_ms: 50
    latency_quantile: 0.9
    availability: 0.999
    window_s: 120
  profile_dir: /tmp/profiles
  profile_max_artifacts: 3
""")
        obj = cfg.build_slo()
        assert obj.latency_ms == 50.0
        assert obj.latency_quantile == 0.9
        assert obj.availability == 0.999
        assert obj.window_s == 120.0
        assert cfg.profile_dir == "/tmp/profiles"
        assert cfg.profile_max_artifacts == 3

    def test_no_slo_block_builds_none(self, tmp_path):
        cfg = self._load(tmp_path, "model:\n  path: /tmp/nowhere\n")
        assert cfg.build_slo() is None

    def test_bad_slo_fails_at_load(self, tmp_path):
        with pytest.raises(ValueError, match="availability"):
            self._load(tmp_path, """
model:
  path: /tmp/nowhere
params:
  slo:
    availability: 2.0
""")

    def test_bad_profile_max_artifacts_fails_at_load(self, tmp_path):
        with pytest.raises(ValueError, match="profile_max_artifacts"):
            self._load(tmp_path, """
model:
  path: /tmp/nowhere
params:
  profile_max_artifacts: 0
""")
