"""NER / SequenceTagger / IntentEntity + CRF op tests (reference:
`pyzoo/test/zoo/tfpark/test_text_models.py`)."""

import itertools

import numpy as np
import pytest

import analytics_zoo_tpu as zoo
from analytics_zoo_tpu.models.textmodels import (IntentEntity, NER,
                                                 POSTagger, SequenceTagger)
from analytics_zoo_tpu.ops import crf


@pytest.fixture(autouse=True)
def ctx():
    c = zoo.init_orca_context(cluster_mode="local")
    yield c
    zoo.stop_orca_context()


def _data(n=8, s=6, w=5, wv=50, cv=20, seed=0):
    rng = np.random.RandomState(seed)
    words = rng.randint(0, wv, (n, s)).astype(np.int32)
    chars = rng.randint(0, cv, (n, s, w)).astype(np.int32)
    return words, chars


class TestCRFOps:
    def _brute_force(self, emissions, transitions):
        """Enumerate all paths for tiny shapes."""
        B, T, K = emissions.shape
        logZ = np.zeros(B)
        best = np.zeros((B, T), np.int64)
        for b in range(B):
            scores = {}
            for path in itertools.product(range(K), repeat=T):
                s = emissions[b, 0, path[0]]
                for t in range(1, T):
                    s += transitions[path[t - 1], path[t]] \
                        + emissions[b, t, path[t]]
                scores[path] = s
            vals = np.asarray(list(scores.values()))
            logZ[b] = np.log(np.sum(np.exp(vals - vals.max()))) + vals.max()
            best[b] = list(max(scores, key=scores.get))
        return logZ, best

    def test_log_likelihood_matches_enumeration(self):
        rng = np.random.RandomState(0)
        em = rng.randn(3, 4, 3).astype(np.float32)
        tr = rng.randn(3, 3).astype(np.float32)
        tags = rng.randint(0, 3, (3, 4))
        logZ, _ = self._brute_force(em, tr)
        ll = np.asarray(crf.crf_log_likelihood(em, tags, tr))
        # manual path score
        for b in range(3):
            s = em[b, 0, tags[b, 0]]
            for t in range(1, 4):
                s += tr[tags[b, t - 1], tags[b, t]] + em[b, t, tags[b, t]]
            np.testing.assert_allclose(ll[b], s - logZ[b], rtol=1e-4)

    def test_viterbi_matches_enumeration(self):
        rng = np.random.RandomState(1)
        em = rng.randn(4, 5, 3).astype(np.float32)
        tr = rng.randn(3, 3).astype(np.float32)
        _, best = self._brute_force(em, tr)
        tags, score = crf.viterbi_decode(em, tr)
        np.testing.assert_array_equal(np.asarray(tags), best)

    def test_masked_likelihood_ignores_padding(self):
        rng = np.random.RandomState(2)
        em = rng.randn(2, 5, 3).astype(np.float32)
        tr = rng.randn(3, 3).astype(np.float32)
        tags = rng.randint(0, 3, (2, 5))
        mask = np.asarray([[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]], np.float32)
        ll_masked = np.asarray(crf.crf_log_likelihood(em, tags, tr, mask))
        ll_short = np.asarray(crf.crf_log_likelihood(
            em[:1, :3], tags[:1, :3], tr))
        np.testing.assert_allclose(ll_masked[0], ll_short[0], rtol=1e-4)

    def test_crf_loss_trains_transitions(self):
        import jax
        rng = np.random.RandomState(3)
        em = rng.randn(4, 6, 3).astype(np.float32)
        tags = rng.randint(0, 3, (4, 6))
        tr0 = np.zeros((3, 3), np.float32)
        g = jax.grad(lambda tr: crf.crf_loss(em, tags, tr))(tr0)
        assert np.any(np.asarray(g) != 0)


class TestNER:
    def test_forward_and_fit(self):
        words, chars = _data()
        ner = NER(num_entities=4, word_vocab_size=50, char_vocab_size=20,
                  word_length=5, word_emb_dim=16, char_emb_dim=8,
                  tagger_lstm_dim=12)
        tags = np.random.RandomState(1).randint(0, 4, (8, 6)).astype(
            np.int32)
        from analytics_zoo_tpu.ops.objectives import get as get_loss
        ner.compile("adam", get_loss("sparse_categorical_crossentropy",
                                     from_logits=True))
        ner.fit([words, chars], tags, batch_size=8, nb_epoch=1)
        out = np.asarray(ner.predict([words, chars], batch_per_thread=8))
        assert out.shape == (8, 6, 4)

    def test_crf_decode_shapes(self):
        words, chars = _data()
        ner = NER(num_entities=3, word_vocab_size=50, char_vocab_size=20,
                  word_length=5, word_emb_dim=8, char_emb_dim=4,
                  tagger_lstm_dim=6)
        ner.model.ensure_built([words, chars])
        ner.transitions = np.random.RandomState(0).randn(3, 3)
        decoded = ner.decode([words, chars])
        assert decoded.shape == (8, 6)
        assert decoded.min() >= 0 and decoded.max() < 3
        loss = ner.crf_loss([words, chars],
                            np.zeros((8, 6), np.int32))
        assert np.isfinite(loss)

    def test_bad_crf_mode(self):
        with pytest.raises(ValueError, match="crf_mode"):
            NER(3, 10, 10, crf_mode="wild")


class TestSequenceTagger:
    def test_dual_heads(self):
        words, chars = _data()
        tagger = SequenceTagger(num_pos_labels=5, num_chunk_labels=3,
                                word_vocab_size=50, char_vocab_size=20,
                                word_length=5, feature_size=8)
        pos, chunk = tagger.predict([words, chars], batch_per_thread=8)
        assert np.asarray(pos).shape == (8, 6, 5)
        assert np.asarray(chunk).shape == (8, 6, 3)
        # probabilities
        np.testing.assert_allclose(np.asarray(pos).sum(-1),
                                   np.ones((8, 6)), rtol=1e-4)

    def test_word_only_input(self):
        words, _ = _data()
        tagger = POSTagger(num_pos_labels=4, num_chunk_labels=2,
                           word_vocab_size=50, feature_size=8)
        pos, chunk = tagger.predict(words, batch_per_thread=8)
        assert np.asarray(pos).shape == (8, 6, 4)

    def test_multi_output_fit(self):
        words, chars = _data()
        tagger = SequenceTagger(num_pos_labels=4, num_chunk_labels=3,
                                word_vocab_size=50, char_vocab_size=20,
                                word_length=5, feature_size=8)
        rng = np.random.RandomState(2)
        pos_y = rng.randint(0, 4, (8, 6)).astype(np.int32)
        chunk_y = rng.randint(0, 3, (8, 6)).astype(np.int32)
        tagger.compile("adam", ["sparse_categorical_crossentropy",
                                "sparse_categorical_crossentropy"])
        tagger.fit([words, chars], [pos_y, chunk_y], batch_size=8,
                   nb_epoch=1)

    def test_bad_classifier(self):
        with pytest.raises(ValueError, match="classifier"):
            SequenceTagger(3, 2, 10, classifier="svm")


class TestIntentEntity:
    def test_joint_outputs_and_fit(self):
        words, chars = _data()
        model = IntentEntity(num_intents=3, num_entities=4,
                             word_vocab_size=50, char_vocab_size=20,
                             word_length=5, word_emb_dim=8, char_emb_dim=4,
                             char_lstm_dim=4, tagger_lstm_dim=8)
        intent, tags = model.predict([words, chars], batch_per_thread=8)
        assert np.asarray(intent).shape == (8, 3)
        assert np.asarray(tags).shape == (8, 6, 4)
        rng = np.random.RandomState(3)
        iy = rng.randint(0, 3, 8).astype(np.int32)
        ty = rng.randint(0, 4, (8, 6)).astype(np.int32)
        model.compile("adam", ["sparse_categorical_crossentropy",
                               "sparse_categorical_crossentropy"])
        model.fit([words, chars], [iy, ty], batch_size=8, nb_epoch=1)


class TestRanker:
    """`models/common/Ranker.scala` NDCG@k / MAP semantics."""

    def test_ndcg_hand_example(self):
        from analytics_zoo_tpu.models.common import Ranker
        # perfect ranking → 1.0
        assert Ranker.ndcg_score([2, 1, 0], [0.9, 0.5, 0.1], k=3) \
            == pytest.approx(1.0)
        # worst ranking of one relevant item at k=1 → 0
        assert Ranker.ndcg_score([1, 0], [0.1, 0.9], k=1) == 0.0
        # no relevant items → 0 by convention
        assert Ranker.ndcg_score([0, 0], [0.5, 0.4], k=2) == 0.0
        with pytest.raises(ValueError):
            Ranker.ndcg_score([1], [1.0], k=0)

    def test_ndcg_partial(self):
        from analytics_zoo_tpu.models.common import Ranker
        # relevant item ranked second of two, k=2:
        # dcg = (2^1)/ln(3), idcg = (2^1)/ln(2) → ln(2)/ln(3)
        got = Ranker.ndcg_score([1, 0], [0.1, 0.9], k=2)
        assert got == pytest.approx(np.log(2) / np.log(3))

    def test_map_hand_example(self):
        from analytics_zoo_tpu.models.common import Ranker
        # relevant at positions 1 and 3 of the score-sorted list:
        # AP = (1/1 + 2/3) / 2
        got = Ranker.map_score([1, 0, 1], [0.9, 0.5, 0.2])
        assert got == pytest.approx((1.0 + 2.0 / 3.0) / 2)
        assert Ranker.map_score([0, 0], [0.9, 0.1]) == 0.0

    def test_knrm_evaluate_ndcg_map(self):
        from analytics_zoo_tpu.models.textmatching import KNRM
        knrm = KNRM(text1_length=4, text2_length=6, vocab_size=50,
                    embed_size=8, target_mode="ranking")
        knrm.model.ensure_built(np.zeros((1, 10), np.int32))
        rs = np.random.RandomState(0)
        queries = []
        for _ in range(3):
            x = rs.randint(1, 50, size=(5, 10)).astype(np.int32)
            y = (rs.rand(5) > 0.5).astype(np.float32)
            queries.append((x, y))
        ndcg = knrm.evaluate_ndcg(queries, k=3)
        mapv = knrm.evaluate_map(queries)
        assert 0.0 <= ndcg <= 1.0 and 0.0 <= mapv <= 1.0
