"""Test harness: single host stands in for a pod.

Mirrors the reference's test strategy (SURVEY.md §4): everything distributed
runs on one machine — there, `local[N]` Spark / local Ray; here, an 8-device
virtual CPU mesh via `--xla_force_host_platform_device_count=8`. Must be set
before jax initializes its backends, hence module-level in conftest.
"""

import os

# Force-override: the machine env pins JAX_PLATFORMS to the TPU plugin, and a
# sitecustomize preimports jax — so set both the env and the live jax config
# (backends initialize lazily, so this still takes effect).
# tests/tpu re-runs itself in a child pytest that needs the REAL backend;
# the child sets ZOO_TPU_SUBPROC so this pin steps aside there.
if os.environ.get("ZOO_TPU_SUBPROC") != "1":
    os.environ["JAX_PLATFORMS"] = "cpu"
    xla_flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in xla_flags:
        os.environ["XLA_FLAGS"] = (
            xla_flags + " --xla_force_host_platform_device_count=8").strip()
# Keep CPU tests deterministic and fast.
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402
import pytest  # noqa: E402

if os.environ.get("ZOO_TPU_SUBPROC") != "1":
    jax.config.update("jax_platforms", "cpu")


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture()
def rng():
    return jax.random.PRNGKey(0)
