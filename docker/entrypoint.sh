#!/bin/sh
# Start the in-container RESP2 server, then the serving loop + frontend.
set -e
python -m analytics_zoo_tpu.serving.cli redis --host 0.0.0.0 --port 6379 &
sleep 1
exec python -m analytics_zoo_tpu.serving.cli start --config /opt/zoo/config.yaml
