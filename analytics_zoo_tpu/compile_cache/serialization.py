"""AOT executable (de)serialization, with device retargeting.

`jax.experimental.serialize_executable.serialize` returns (payload
bytes, in_tree, out_tree); the pytrees pickle fine, so `pack` folds the
triple into one bytes blob. Two wrinkles this module owns:

- **Device retargeting** (`unpack(target_device_id=...)`): a serialized
  single-device executable bakes in its compile-time device id — both
  in the pickled args-info shardings and in the XLA executable's device
  assignment. The replicated serving pool persists ONE entry per bucket
  and loads it once per replica, so the deserializer re-pins both: the
  pickled device persistent-ids map to the target device, and the raw
  XLA executable reloads under `CompileOptions` carrying a fresh
  single-device `DeviceAssignment`. Multi-device (GSPMD/sharded)
  executables never retarget — their device set IS the key.
- **Compile spy-ability** (`compile_lowered`): every fresh AOT compile
  in the codebase funnels through this one function, so tests can
  monkeypatch it and assert a cache-warm warmup performs ZERO compiles.

Everything degrades: on a jax build without `serialize_executable`,
`HAVE_AOT` is False and callers fall back to plain jit (backed by
JAX's built-in persistent compilation cache when enabled).
"""

from __future__ import annotations

import io
import pickle
from typing import Optional

import jax

try:
    from jax.experimental import serialize_executable as _se
    from jax._src.lib import xla_client as _xc
    HAVE_AOT = True
except Exception:  # noqa: BLE001 — optional capability, gated everywhere
    _se = None
    _xc = None
    HAVE_AOT = False


def compile_lowered(lowered):
    """`lowered.compile()` — THE fresh-compile funnel (tests spy here)."""
    return lowered.compile()


def pack(compiled) -> bytes:
    """One bytes blob from a `jax.stages.Compiled`. Raises on anything
    unserializable (callbacks, unsupported backends) — callers treat
    that as 'skip persisting', never as fatal."""
    if not HAVE_AOT:
        raise RuntimeError("jax.experimental.serialize_executable "
                           "unavailable on this jax build")
    payload, in_tree, out_tree = _se.serialize(compiled)
    return pickle.dumps((payload, in_tree, out_tree), protocol=4)


class _RetargetUnpickler(_se._JaxPjrtUnpickler if HAVE_AOT else object):
    """`_JaxPjrtUnpickler` that lands every device reference — and the
    XLA executable's device assignment — on one target device."""

    def __init__(self, file, backend, target_id: int):
        super().__init__(file, backend)
        self.target_id = target_id

    def persistent_load(self, pid):
        if pid[0] == "device":
            return self.devices_by_id[self.target_id]
        if pid[0] == "exec":
            import numpy as np
            opts = _xc.CompileOptions()
            opts.device_assignment = _xc.DeviceAssignment.create(
                np.array([[self.target_id]], np.int32))
            return self.backend.deserialize_executable(pid[1], opts)
        return super().persistent_load(pid)


def args_treedef(compiled):
    """The pytree structure a `Compiled` expects for its inputs — the
    `((args...), {kwargs})` treedef, dict-key metadata included
    (`Compiled` rejects calls whose trees differ even when every leaf
    matches). Compare against `live_treedef(args)`."""
    return compiled.in_tree


def live_treedef(args) -> "jax.tree_util.PyTreeDef":
    """`args_treedef`-comparable structure of a positional-args call."""
    return jax.tree_util.tree_structure((tuple(args), {}))


def retree_call(compiled, stored_tree):
    """Adapter for a cache hit whose stored tree carries different
    auto-numbered layer names than the live params ("dense_3" stored,
    "dense_7" live): flatten the live args and rebuild them under the
    stored `in_tree` before calling. Sound because the canonical key
    (`structure_signature`) only matches trees whose jax flatten
    orders correspond. Serving-side only — its OUTPUTS are
    activations, so the stored names never leak back into a params
    tree the caller keeps."""

    def call(*args):
        leaves = jax.tree_util.tree_leaves((tuple(args), {}))
        new_args, new_kwargs = jax.tree_util.tree_unflatten(stored_tree,
                                                            leaves)
        return compiled(*new_args, **new_kwargs)

    return call


def unpack(data: bytes, target_device_id: Optional[int] = None):
    """Rebuild a callable `jax.stages.Compiled` from `pack` output.
    `target_device_id` re-pins a single-device executable onto that
    device (replica fan-out); None keeps the stored assignment (the
    single-device default path and all multi-device executables)."""
    if not HAVE_AOT:
        raise RuntimeError("jax.experimental.serialize_executable "
                           "unavailable on this jax build")
    payload, in_tree, out_tree = pickle.loads(data)
    if target_device_id is None:
        return _se.deserialize_and_load(payload, in_tree, out_tree)
    backend = jax.devices()[0].client
    unloaded, args_info_flat, no_kwargs = _RetargetUnpickler(
        io.BytesIO(payload), backend, target_device_id).load()
    args_info = in_tree.unflatten(args_info_flat)
    return jax.stages.Compiled(unloaded.load(), args_info, out_tree,
                               no_kwargs=no_kwargs)
