"""AOT cache wrapper for jitted trainer steps.

`build_train_step` / `build_train_run` hand back `jax.jit` callables
whose shapes are only known at the first batch. This wrapper sits in
front of one: per distinct argument signature it loads a persisted
executable (or lowers + compiles + persists once), then dispatches
every later call straight to the AOT executable — a trainer re-run
pays zero XLA compiles for shapes it has seen in any previous process.

Anything that defeats AOT serialization — an unserializable backend, a
signature that fails to lower, an executable rejecting its inputs —
permanently falls back to the wrapped jit callable for that signature,
where JAX's built-in persistent compilation cache (see
`enable_jax_persistent_cache`) still amortizes the XLA compile.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Dict, Set, Tuple

from analytics_zoo_tpu.compile_cache import serialization
from analytics_zoo_tpu.compile_cache.key import (abstract_signature,
                                                 cheap_signature, make_key)

log = logging.getLogger("analytics_zoo_tpu.compile_cache")


class AOTFunctionCache:
    """Wrap a jitted fn with per-signature AOT executable caching.

    NOT thread-safe for concurrent first-calls of the same signature
    (the training loop is single-dispatcher); steady-state calls are a
    dict hit + the executable call."""

    def __init__(self, jit_fn: Callable, cache, model_fp: str,
                 kind: str = "train", sharding: str = ""):
        self._jit = jit_fn
        self._cache = cache
        self._model_fp = model_fp
        self._kind = kind
        # mesh-axis + rule-table descriptor for GSPMD-sharded steps: the
        # argument SHAPES of a replicated and an fsdp-sharded step can
        # coincide exactly, so the disk key must carry the layout too
        self._sharding = sharding
        self._execs: Dict[Tuple, Any] = {}    # cheap sig -> executable
        self._failed: Set[Tuple] = set()
        self.sources: Dict[Tuple, str] = {}   # sig -> cached|compiled|jit

    @staticmethod
    def _cheap_sig(args) -> Tuple:
        """Steady-state dispatch key: per-leaf shape/dtype only (the
        shared `key.cheap_signature`). The full canonical
        `abstract_signature` (structure walk + per-key regex) runs ONCE
        per new shape in `_build`; paying it per training step would
        tax exactly the hot loop this cache exists to speed up. Leaf
        shapes are discriminating here because one wrapper serves one
        fixed (model, optimizer) — arg STRUCTURE can't change under it,
        only batch shapes."""
        return cheap_signature(args)

    def __call__(self, *args):
        csig = self._cheap_sig(args)
        ex = self._execs.get(csig)
        if ex is None and csig not in self._failed \
                and serialization.HAVE_AOT:
            ex = self._build(csig, args)
        if ex is None:
            return self._jit(*args)
        try:
            return ex(*args)
        except Exception as e:  # noqa: BLE001 — e.g. an input landed
            # with a sharding the persisted program wasn't built for;
            # the check fires BEFORE execution (no donation consumed),
            # so the jit retry sees intact buffers
            log.warning("AOT executable rejected a call (%s: %s); "
                        "falling back to jit for this signature",
                        type(e).__name__, e)
            self._execs.pop(csig, None)
            self._failed.add(csig)
            self.sources[csig] = "jit"
            return self._jit(*args)

    def _build(self, csig, args):
        sig = abstract_signature(args)
        key = make_key(self._kind, self._model_fp, sig, placement="train",
                       sharding=self._sharding)
        try:
            ex = self._cache.load(key)
            if ex is not None and serialization.args_treedef(ex) \
                    != serialization.live_treedef(args):
                # a naming-counter offset between processes: the stored
                # tree's keys differ from the live params/opt_state. A
                # train step RETURNS those trees, so re-treeing would
                # hand the caller stale key names — fall back to jit
                # (jax's persistent cache still amortizes the compile)
                # and leave the entry for its original tree shape.
                log.info("AOT entry tree mismatch for this signature; "
                         "using jit")
                self._failed.add(csig)
                self.sources[csig] = "jit"
                return None
            if ex is not None:
                self.sources[csig] = "cached"
            else:
                t0 = time.perf_counter()
                ex = serialization.compile_lowered(self._jit.lower(*args))
                self._cache.put(
                    key, ex,
                    compile_ms=(time.perf_counter() - t0) * 1e3)
                self.sources[csig] = "compiled"
            self._execs[csig] = ex
            return ex
        except Exception as e:  # noqa: BLE001 — AOT unavailable for
            # this shape: the jit path (+ jax's own persistent cache)
            # owns it from here
            log.info("AOT caching unavailable for signature (%s: %s); "
                     "using jit", type(e).__name__, e)
            self._failed.add(csig)
            self.sources[csig] = "jit"
            return None

    def executables(self) -> Dict[Tuple, Any]:
        """Live AOT executables by cheap signature — the roofline layer
        harvests `cost_analysis()` from these (a deserialized executable
        still answers it), so a cache-hit re-run gets utilization gauges
        without ever lowering."""
        return dict(self._execs)

    # the trainer's step-cache memo compares wrapped identity
    @property
    def wrapped(self) -> Callable:
        return self._jit
