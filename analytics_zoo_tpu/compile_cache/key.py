"""Cache-key anatomy: content fingerprints for executables.

An entry must be reusable exactly when recompiling would produce the
same program, and MUST miss when anything that feeds the compiler
changed. The key therefore folds in:

- the jax/jaxlib versions (serialized executables are not stable across
  releases) and the backend platform + device kind + device count
- a model fingerprint: the forward fn's bytecode (constants and closure
  cells included, recursively) plus the params tree structure and every
  leaf's shape/dtype — weight VALUES are runtime inputs and excluded
- the input signature: tree structure + per-leaf shape/dtype of the
  (bucket-padded) batch — so every bucket is its own entry and a dtype
  change invalidates
- the placement mode and, for sharded placement, the mesh axis layout —
  a GSPMD program for an 8-way mesh must never load into a 4-way one

The key is canonical JSON; its sha256 names the entry file. Fingerprints
are heuristic by design (two genuinely different models hashing equal is
made vanishingly unlikely by the bytecode + structure walk), and a false
MISS only costs a recompile.
"""

from __future__ import annotations

import hashlib
import json
import types
from dataclasses import dataclass, field
from typing import Any, Dict, Tuple

# Bump to invalidate every persisted executable when layout SEMANTICS
# change: v2 = ISSUE 7's ShardingRules.spec_for fsdp fallback for
# matched-but-untrimmable rules (the same table now resolves different
# placements on data×fsdp meshes, and a stale sharded executable would
# reject — or silently reshard — its inputs).
FORMAT_VERSION = 2

_MAX_DEPTH = 5
_MAX_ITEMS = 64


def _h(parts) -> str:
    return hashlib.sha256("\x1f".join(parts).encode()).hexdigest()[:16]


def fingerprint(obj: Any, depth: int = 0) -> str:
    """Stable-across-processes content fingerprint of a python object:
    functions hash by bytecode + consts + closure cells; arrays by
    shape/dtype (values are runtime inputs); layer-bearing objects by a
    structural walk of their scalar attributes. Bounded depth/width so a
    pathological object can't stall key construction."""
    if depth > _MAX_DEPTH:
        return "deep"
    if obj is None or isinstance(obj, (bool, int, float, str, bytes)):
        return repr(obj)
    # modules and classes identify by NAME, never by attribute walk: a
    # function closing over `import jax` would otherwise deep-walk the
    # whole package namespace (and trip over class-level `shape`/`dtype`
    # PROPERTIES masquerading as array attrs — jax.Array did exactly
    # that once the fused optimizer's update closed over the module)
    if isinstance(obj, types.ModuleType):
        return _h(["module", obj.__name__,
                   str(getattr(obj, "__version__", ""))])
    if isinstance(obj, type):
        return _h(["type", obj.__module__, obj.__qualname__])
    # bound methods: underlying function + owner structure
    owner = getattr(obj, "__self__", None)
    func = getattr(obj, "__func__", None)
    if owner is not None and func is not None:
        return _h(["method", fingerprint(func, depth + 1),
                   fingerprint(owner, depth + 1)])
    code = getattr(obj, "__code__", None)
    if code is not None:
        parts = ["fn", getattr(obj, "__qualname__", "?"),
                 hashlib.sha256(code.co_code).hexdigest()[:16],
                 repr(code.co_names)]
        for c in code.co_consts[:_MAX_ITEMS]:
            parts.append(fingerprint(c, depth + 1))
        for cell in (obj.__closure__ or ())[:_MAX_ITEMS]:
            try:
                parts.append(fingerprint(cell.cell_contents, depth + 1))
            except ValueError:      # empty cell
                parts.append("empty")
        return _h(parts)
    shape = getattr(obj, "shape", None)
    dtype = getattr(obj, "dtype", None)
    if shape is not None and dtype is not None:
        return f"arr{tuple(shape)}:{dtype}"
    if isinstance(obj, (list, tuple)):
        return _h([type(obj).__name__]
                  + [fingerprint(v, depth + 1) for v in obj[:_MAX_ITEMS]])
    if isinstance(obj, dict):
        items = sorted(obj.items(), key=lambda kv: str(kv[0]))[:_MAX_ITEMS]
        return _h(["dict"] + [f"{k}={fingerprint(v, depth + 1)}"
                              for k, v in items])
    layers = getattr(obj, "layers", None)
    if layers is not None:
        return _h([type(obj).__name__]
                  + [fingerprint(l, depth + 1) for l in layers[:_MAX_ITEMS]])
    # generic object: type + scalar attrs (hyperparameters like
    # strides and units live here) + CALLABLE attrs (a Dense stores
    # its activation as a jax function — two models differing only in
    # relu-vs-tanh must never share a key). Auto-generated `name`
    # attrs ("dense_3") are EXCLUDED: the numbering counter is
    # process-global, so the same model built after any other model
    # would fingerprint differently — identity comes from the layer
    # list order and the canonical structure signature instead.
    try:
        items = sorted(vars(obj).items())
    except TypeError:
        items = []
    parts = [type(obj).__name__]
    n = 0
    for k, v in items:
        if k == "name" or n >= _MAX_ITEMS:
            continue
        if isinstance(v, (bool, int, float, str, tuple)):
            parts.append(f"{k}={v!r}")
            n += 1
        elif callable(v):
            parts.append(f"{k}={fingerprint(v, depth + 1)}")
            n += 1
    return _h(parts)


_AUTONUM_RE = None


def structure_signature(tree: Any) -> str:
    """Canonical structure string of a pytree: container shapes, dict
    keys, and per-leaf shape/dtype — with auto-numbered layer keys
    ("dense_3") rewritten to build-order ordinals ("dense#0"). The
    layer-naming counter is process-global, so the same model built at
    a different point in a process (or under a different import order)
    carries different raw names; raw treedef strings would invalidate
    the whole cache on a mere counter offset.

    Soundness contract: two trees with EQUAL signatures flatten to
    corresponding leaf sequences under jax's dict ordering. Ordinals
    are assigned in dict-insertion (build) order, but children are
    EMITTED in sorted-raw-key order — exactly jax's flatten order. If
    a counter offset reorders the sorted sequence relative to build
    order (the "dense_9"/"dense_10" lexicographic flip), the emitted
    ordinal sequences differ, the signatures differ, and the lookup
    safely misses instead of positionally mis-mapping same-shaped
    layers."""
    global _AUTONUM_RE
    if _AUTONUM_RE is None:
        import re
        _AUTONUM_RE = re.compile(r"^(.+?)_(\d+)$")
    counters: Dict[str, int] = {}

    def canon(k) -> str:
        m = _AUTONUM_RE.match(str(k))
        base = m.group(1) if m else str(k)
        i = counters.get(base, 0)
        counters[base] = i + 1
        return f"{base}#{i}"

    def walk(t) -> str:
        if isinstance(t, dict):
            # ordinals in insertion (build) order ...
            labels = {k: canon(k) for k in t}
            # ... emission in sorted raw-key order (jax flatten order)
            return "{" + ",".join(f"{labels[k]}:{walk(t[k])}"
                                  for k in sorted(t, key=str)) + "}"
        if isinstance(t, (list, tuple)):
            return (type(t).__name__ + "["
                    + ",".join(walk(v) for v in t) + "]")
        if t is None:
            return "~"
        shape = getattr(t, "shape", None)
        dtype = getattr(t, "dtype", None)
        if shape is not None:
            return f"{tuple(shape)}:{dtype}"
        return type(t).__name__

    return walk(tree)


def model_fingerprint(fn: Any, params: Any) -> str:
    """Fingerprint of (forward fn, params STRUCTURE): what must match
    for a serialized forward executable to be the right program."""
    return _h([fingerprint(fn), structure_signature(params)])


def abstract_signature(tree: Any) -> Tuple[str, Tuple]:
    """(canonical structure str, ((shape, dtype), ...)) of a pytree of
    arrays — the per-call part of the key (and the in-process
    executable-table key)."""
    import jax
    leaves = jax.tree_util.tree_leaves(tree)
    return (structure_signature(tree),
            tuple((tuple(getattr(l, "shape", ())),
                   str(getattr(l, "dtype", type(l).__name__)))
                  for l in leaves))


def cheap_signature(tree: Any) -> Tuple:
    """Per-leaf (shape, dtype-name) tuple — the hot-path dispatch key
    shared by `AOTFunctionCache`, the trainer's step-cost tracker, and
    `InferenceModel`'s roofline cost table. Discriminating only when
    the tree STRUCTURE is fixed per consumer (one wrapper per model);
    pay `abstract_signature` when structure can vary. One
    implementation so the three consumers can never drift on dtype
    spelling."""
    import jax
    return tuple(
        (tuple(l.shape), l.dtype.name) if hasattr(l, "shape")
        else (type(l).__name__,)
        for l in jax.tree_util.tree_leaves(tree))


@dataclass
class CacheKey:
    """Canonical key: `fields` is the human-readable anatomy (stored in
    the entry header so `compile_cache_tool.py ls` can explain an
    entry); `digest` names the entry file."""

    fields: Dict[str, Any] = field(default_factory=dict)

    @property
    def digest(self) -> str:
        blob = json.dumps(self.fields, sort_keys=True, default=str)
        return hashlib.sha256(blob.encode()).hexdigest()[:40]


def make_key(kind: str, model_fp: str, signature, placement: str = "none",
             sharding: str = "", extra: Any = None,
             dtype: str = "") -> CacheKey:
    """Build the full cache key. `kind` separates serving forwards from
    trainer steps; `signature` is `abstract_signature(...)` of the call
    args; `sharding` describes the mesh layout for sharded placement;
    `dtype` names a non-default serving precision ("int8") so a
    quantize toggle is a guaranteed miss — empty ("", the f32 default)
    adds NO field, keeping pre-existing keys byte-identical."""
    import jax
    try:
        backend = jax.default_backend()
        dev = jax.devices(backend)[0]
        device_kind = getattr(dev, "device_kind", str(dev))
        n_devices = jax.device_count(backend)
    except Exception:  # noqa: BLE001 — key building must not crash
        backend, device_kind, n_devices = "unknown", "unknown", 0
    fields = {
        "format": FORMAT_VERSION,
        "jax": jax.__version__,
        "jaxlib": getattr(__import__("jaxlib"), "__version__", "?"),
        "backend": backend,
        "device_kind": device_kind,
        "n_devices": n_devices,
        "kind": kind,
        "model": model_fp,
        "signature": _sig_fields(signature),
        "placement": placement,
        "sharding": sharding,
    }
    if dtype:
        fields["dtype"] = dtype
    if extra is not None:
        fields["extra"] = fingerprint(extra)
    return CacheKey(fields)


def _sig_fields(signature):
    treedef, leaves = signature
    return {"tree": treedef,
            "leaves": [[list(shape), dtype] for shape, dtype in leaves]}
