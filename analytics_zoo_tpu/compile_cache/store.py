"""The disk store: CRC-checked, size-bounded, atomically written.

Entry file layout (`<digest>.aotc`):

    magic  b"AZCC"                      (4 bytes)
    format version                      (u32 LE)
    header length                       (u32 LE)
    header JSON (utf-8)                 — key fields + created + payload
                                          crc32c/length
    payload                             — `serialization.pack` bytes

The header is self-describing, so the maintenance tool
(`scripts/compile_cache_tool.py`) can `ls`/`stats`/`prune` a cache dir
with nothing but this module — there is no separate index file to race
on: the directory IS the index, scanned on demand.

Durability rules:

- writes go to a same-directory temp file then `os.replace` — a reader
  never sees a half-written entry, and a crashed writer leaves only a
  temp file that the next prune sweeps
- reads verify magic, format version, header shape, payload length and
  CRC32C (`utils/crc.py`); ANY failure — truncation, corruption, a
  different format version — deletes the entry and reports a miss.
  The load path cannot raise.
- LRU is file mtime: a hit touches the entry (`os.utime`); eviction
  removes oldest-touched first until the byte budget holds.

Telemetry (process-wide registry): `compile_cache_hits_total`,
`compile_cache_misses_total`, `compile_cache_load_ms`,
`compile_cache_compile_ms`, `compile_cache_bytes`.
"""

from __future__ import annotations

import json
import logging
import os
import struct
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from analytics_zoo_tpu.compile_cache import serialization
from analytics_zoo_tpu.compile_cache.key import FORMAT_VERSION, CacheKey
from analytics_zoo_tpu.utils.crc import crc32c

log = logging.getLogger("analytics_zoo_tpu.compile_cache")

MAGIC = b"AZCC"
ENTRY_SUFFIX = ".aotc"
_HDR = struct.Struct("<4sII")       # magic, format version, header length


def write_entry(path: str, key_fields: Dict[str, Any],
                payload: bytes) -> int:
    """Atomic write-then-rename of one entry; returns bytes written."""
    header = dict(key_fields)
    header["created"] = time.time()
    header["payload_len"] = len(payload)
    header["payload_crc32c"] = crc32c(payload)
    hjson = json.dumps(header, sort_keys=True, default=str).encode()
    blob = _HDR.pack(MAGIC, FORMAT_VERSION, len(hjson)) + hjson + payload
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                               prefix=".tmp-", suffix=ENTRY_SUFFIX + ".part")
    try:
        with os.fdopen(fd, "wb") as fh:
            fh.write(blob)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return len(blob)


def read_entry(path: str) -> Tuple[Dict[str, Any], bytes]:
    """Parse + verify one entry file; raises on ANY defect (magic,
    version, truncation, CRC). Callers on the load path catch and treat
    as a miss."""
    with open(path, "rb") as fh:
        head = fh.read(_HDR.size)
        if len(head) != _HDR.size:
            raise ValueError("truncated entry header")
        magic, version, hlen = _HDR.unpack(head)
        if magic != MAGIC:
            raise ValueError(f"bad magic {magic!r}")
        if version != FORMAT_VERSION:
            raise ValueError(f"format version {version} != "
                             f"{FORMAT_VERSION}")
        hjson = fh.read(hlen)
        if len(hjson) != hlen:
            raise ValueError("truncated entry header json")
        header = json.loads(hjson)
        payload = fh.read()
    if len(payload) != header.get("payload_len"):
        raise ValueError(f"payload length {len(payload)} != recorded "
                         f"{header.get('payload_len')}")
    if crc32c(payload) != header.get("payload_crc32c"):
        raise ValueError("payload CRC32C mismatch")
    return header, payload


def read_header(path: str) -> Dict[str, Any]:
    """Header only (for `ls`/`stats` — skips the payload CRC)."""
    with open(path, "rb") as fh:
        head = fh.read(_HDR.size)
        if len(head) != _HDR.size:
            raise ValueError("truncated entry header")
        magic, version, hlen = _HDR.unpack(head)
        if magic != MAGIC:
            raise ValueError(f"bad magic {magic!r}")
        hjson = fh.read(hlen)
        if len(hjson) != hlen:
            raise ValueError("truncated entry header json")
        header = json.loads(hjson)
    header["format_version"] = version
    return header


def scan_dir(path: str) -> List[Dict[str, Any]]:
    """The on-demand index: one dict per entry file (corrupt headers
    included, flagged) sorted oldest-touched first."""
    out = []
    try:
        names = sorted(os.listdir(path))
    except OSError:
        return out
    for name in names:
        if not name.endswith(ENTRY_SUFFIX):
            continue
        fp = os.path.join(path, name)
        try:
            st = os.stat(fp)
        except OSError:
            continue
        info = {"file": name, "digest": name[:-len(ENTRY_SUFFIX)],
                "bytes": st.st_size, "last_used": st.st_mtime}
        try:
            hdr = read_header(fp)
            info["header"] = hdr
            info["created"] = hdr.get("created")
        except Exception as e:  # noqa: BLE001 — tool must list anyway
            info["corrupt"] = str(e)
        out.append(info)
    out.sort(key=lambda i: i["last_used"])
    return out


def prune_dir(path: str, max_bytes: int) -> Tuple[int, int]:
    """Evict oldest-touched entries until the directory holds
    <= max_bytes; returns (entries removed, entry bytes freed). Stray
    temp files from crashed writers are swept too but NOT counted —
    they were never part of the entry ledger."""
    removed = freed = 0
    try:
        names = os.listdir(path)
    except OSError:
        return 0, 0
    for name in names:                      # crashed writers' leftovers
        if name.startswith(".tmp-"):
            try:
                os.unlink(os.path.join(path, name))
            except OSError:
                pass
    entries = scan_dir(path)
    total = sum(e["bytes"] for e in entries)
    for e in entries:
        if total <= max_bytes:
            break
        try:
            os.unlink(os.path.join(path, e["file"]))
        except OSError:
            continue
        total -= e["bytes"]
        removed += 1
        freed += e["bytes"]
    return removed, freed


def dir_bytes(path: str) -> int:
    return sum(e["bytes"] for e in scan_dir(path))


class CompileCache:
    """Disk-backed executable cache. Thread-safe; every public method is
    exception-free on the load path (corruption → miss, full disk →
    skip persist) — a cache problem must never take serving down."""

    def __init__(self, path: str, max_bytes: Optional[int] = None,
                 registry=None):
        if max_bytes is not None and max_bytes <= 0:
            raise ValueError(
                f"compile cache max_bytes={max_bytes} must be positive")
        self.path = os.path.abspath(os.path.expanduser(path))
        if os.path.exists(self.path) and not os.path.isdir(self.path):
            raise ValueError(
                f"compile cache path {self.path!r} exists and is not a "
                "directory")
        os.makedirs(self.path, exist_ok=True)
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        if registry is None:
            from analytics_zoo_tpu.observability.registry import get_registry
            registry = get_registry()
        self.registry = registry
        self._hits = registry.counter(
            "compile_cache_hits_total",
            "executables loaded from the persistent compilation cache")
        self._misses = registry.counter(
            "compile_cache_misses_total",
            "persistent compilation cache lookups that fell back to a "
            "fresh compile")
        self._load_ms = registry.histogram(
            "compile_cache_load_ms",
            "wall time to read + deserialize one cached executable")
        self._compile_ms = registry.histogram(
            "compile_cache_compile_ms",
            "wall time of fresh XLA compiles the cache then persisted")
        self._bytes = registry.gauge(
            "compile_cache_bytes",
            "bytes of serialized executables on disk in the cache dir")
        # in-memory dir accounting, maintained incrementally: stats()
        # sits on the /metrics scrape path, which must not pay an
        # os.listdir + header parse per entry per scrape. One scan at
        # construction; put/prune/clear/corrupt-unlink adjust deltas
        # (another process's writes show up on ITS side — telemetry,
        # not a ledger).
        entries = scan_dir(self.path)
        self._n_entries = len(entries)
        self._n_bytes = sum(e["bytes"] for e in entries)
        self._bytes.set(self._n_bytes)

    def _account(self, d_entries: int, d_bytes: int):
        """Adjust the in-memory dir accounting (callers hold _lock or
        are on single-owner paths); floor at zero against drift."""
        self._n_entries = max(0, self._n_entries + d_entries)
        self._n_bytes = max(0, self._n_bytes + d_bytes)
        self._bytes.set(self._n_bytes)

    # -- load/store --------------------------------------------------------
    def _entry_path(self, key: CacheKey) -> str:
        return os.path.join(self.path, key.digest + ENTRY_SUFFIX)

    def contains(self, key: CacheKey) -> bool:
        return os.path.exists(self._entry_path(key))

    def load(self, key: CacheKey,
             target_device_id: Optional[int] = None):
        """Hit → a callable `jax.stages.Compiled` (optionally re-pinned
        onto `target_device_id`); miss/corrupt/version-mismatch → None.
        Never raises."""
        fp = self._entry_path(key)
        t0 = time.perf_counter()
        try:
            header, payload = read_entry(fp)
            compiled = serialization.unpack(
                payload, target_device_id=target_device_id)
        except FileNotFoundError:
            self._misses.inc()
            return None
        except Exception as e:  # noqa: BLE001 — degrade to recompile
            log.warning("compile cache entry %s unusable (%s: %s); "
                        "falling back to fresh compile",
                        os.path.basename(fp), type(e).__name__, e)
            with self._lock:
                try:
                    size = os.path.getsize(fp)
                    os.unlink(fp)
                except OSError:
                    pass
                else:
                    self._account(-1, -size)
            self._misses.inc()
            return None
        try:
            os.utime(fp)            # LRU touch
        except OSError:
            pass
        self._hits.inc()
        self._load_ms.observe((time.perf_counter() - t0) * 1e3)
        return compiled

    def put(self, key: CacheKey, compiled,
            compile_ms: Optional[float] = None) -> bool:
        """Serialize + persist one executable; evict LRU past the byte
        budget. False (never an exception) when the executable can't be
        serialized or the disk write fails."""
        if compile_ms is not None:
            self._compile_ms.observe(compile_ms)
        try:
            payload = serialization.pack(compiled)
        except Exception as e:  # noqa: BLE001 — not serializable: skip
            log.info("executable not persistable (%s: %s); serving from "
                     "the in-process copy only", type(e).__name__, e)
            return False
        try:
            with self._lock:
                fp = self._entry_path(key)
                try:
                    old = os.path.getsize(fp)      # overwrite: replace,
                    d_entries = 0                  # don't double-count
                except OSError:
                    old, d_entries = 0, 1
                written = write_entry(fp, key.fields, payload)
                self._account(d_entries, written - old)
                if self.max_bytes is not None:
                    removed, freed = prune_dir(self.path, self.max_bytes)
                    self._account(-removed, -freed)
        except Exception as e:  # noqa: BLE001 — full/readonly disk
            log.warning("compile cache write failed (%s: %s)",
                        type(e).__name__, e)
            return False
        return True

    # -- maintenance (shared with scripts/compile_cache_tool.py) -----------
    def index(self) -> List[Dict[str, Any]]:
        return scan_dir(self.path)

    def total_bytes(self) -> int:
        return dir_bytes(self.path)

    def stats(self) -> Dict[str, Any]:
        """Cheap (in-memory) counters — this sits on the /metrics
        scrape path, so it must not rescan the directory. The
        maintenance tool's `stats` command scans for ground truth."""
        with self._lock:
            return {"path": self.path,
                    "entries": self._n_entries,
                    "bytes": self._n_bytes,
                    "hits": self._hits.value(),
                    "misses": self._misses.value(),
                    "max_bytes": self.max_bytes}

    def prune(self, max_bytes: int) -> Tuple[int, int]:
        with self._lock:
            removed, freed = prune_dir(self.path, max_bytes)
            self._account(-removed, -freed)
        return removed, freed

    def clear(self) -> int:
        with self._lock:
            n, freed = prune_dir(self.path, -1)
            self._account(-n, -freed)
        return n


_CACHES: Dict[str, "CompileCache"] = {}
_CACHES_LOCK = threading.Lock()


def get_cache(path: str, max_bytes: Optional[int] = None) -> CompileCache:
    """Process-level memo: one `CompileCache` per directory, so repeated
    fits (and the trainer + serving halves of one process) share hit/
    miss accounting and skip re-scanning the dir."""
    key = os.path.abspath(os.path.expanduser(path))
    with _CACHES_LOCK:
        cc = _CACHES.get(key)
        if cc is None:
            cc = _CACHES[key] = CompileCache(key, max_bytes=max_bytes)
        elif max_bytes is not None:
            cc.max_bytes = max_bytes
        return cc


def enable_jax_persistent_cache(cache_dir: str) -> bool:
    """The fallback layer: JAX's built-in persistent compilation cache
    (`jax_compilation_cache_dir`) under `<cache_dir>/xla`. Catches every
    compile AOT serialization can't (shapes lowered mid-run, eval/
    predict jits, backends without executable serialization) at the XLA
    level. Best-effort: False on jax builds without the knobs."""
    xla_dir = os.path.join(os.path.abspath(os.path.expanduser(cache_dir)),
                           "xla")
    try:
        os.makedirs(xla_dir, exist_ok=True)
        import jax
        jax.config.update("jax_compilation_cache_dir", xla_dir)
        # serving/trainer cold-start cares about EVERY compile, not just
        # the >1s ones jax defaults to persisting
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        return True
    except Exception as e:  # noqa: BLE001 — fallback layer is optional
        log.info("jax persistent compilation cache unavailable "
                 "(%s: %s)", type(e).__name__, e)
        return False
