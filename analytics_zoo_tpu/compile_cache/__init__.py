"""Persistent compilation cache — AOT executable serialization (ISSUE 4).

Every process start used to pay the full XLA compilation bill:
`InferenceModel.warmup()` compiled every (replica, bucket) executable
from scratch and the trainer re-lowered its step/run programs on every
launch — minutes of cold-start per restart on real TPUs, paid again for
every replica of a rolling deploy. This package amortizes that bill to
near-zero the way the JAX persistent-cache line of work does, but one
level higher: whole `jax.stages.Compiled` executables, serialized via
`jax.experimental.serialize_executable`, keyed by a content fingerprint
and stored on disk.

- `CompileCache` (`store.py`) — the disk store: CRC-checked entries,
  atomic write-then-rename, LRU eviction under a byte budget, and
  hit/miss/load/compile telemetry in the process-wide registry. A
  corrupt, truncated, or format-mismatched entry is silently a miss —
  never an exception on the load path.
- `make_key` / fingerprints (`key.py`) — the cache key anatomy: jax
  version, backend + device kind/count, model fn + params structure,
  input signature (bucket shape + dtype), placement + sharding spec.
- `pack` / `unpack` (`serialization.py`) — executable bytes, including
  the device-retargeting deserializer that lets ONE persisted entry
  load onto each replica's device (persist once, load N times).
- `AOTFunctionCache` — wraps a jitted trainer step: per input signature
  it loads/compiles-and-persists an AOT executable, falling back to the
  plain jit call (backed by JAX's built-in persistent cache, see
  `enable_jax_persistent_cache`) for anything AOT can't serialize.
"""

from analytics_zoo_tpu.compile_cache.key import (CacheKey, abstract_signature,
                                                 fingerprint, make_key,
                                                 model_fingerprint,
                                                 structure_signature)
from analytics_zoo_tpu.compile_cache.serialization import (
    HAVE_AOT, compile_lowered, pack, unpack)
from analytics_zoo_tpu.compile_cache.store import (CompileCache,
                                                   enable_jax_persistent_cache,
                                                   get_cache)
from analytics_zoo_tpu.compile_cache.aot_fn import AOTFunctionCache

__all__ = [
    "AOTFunctionCache", "CacheKey", "CompileCache", "HAVE_AOT",
    "abstract_signature", "compile_lowered", "enable_jax_persistent_cache",
    "fingerprint", "get_cache", "make_key", "model_fingerprint", "pack",
    "structure_signature", "unpack",
]
