"""AutoTS — `AutoTSTrainer`/`TSPipeline` (`zouwu/autots/forecast.py:22,86`).

Thin user-facing wrapper over the AutoML TimeSequencePredictor: the trainer
searches feature+model config, the pipeline carries the fitted artifacts
with fit/predict/evaluate/save/load."""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import pandas as pd

from analytics_zoo_tpu.automl.pipeline import (TimeSequencePipeline,
                                               TimeSequencePredictor)
from analytics_zoo_tpu.automl.recipe import LSTMGridRandomRecipe, Recipe


class TSPipeline:
    """`TSPipeline` (`zouwu/autots/forecast.py:86`)."""

    def __init__(self, inner: TimeSequencePipeline):
        self._inner = inner

    def predict(self, input_df: pd.DataFrame):
        return self._inner.predict(input_df)

    def evaluate(self, input_df: pd.DataFrame,
                 metrics: Sequence[str] = ("mse",)) -> Dict[str, float]:
        return self._inner.evaluate(input_df, metrics)

    def fit(self, input_df: pd.DataFrame, epoch_num: int = 1,
            batch_size: int = 32):
        """Incremental fit (`forecast.py:101`)."""
        return self._inner.fit(input_df, epochs=epoch_num,
                               batch_size=batch_size)

    def save(self, pipeline_file: str) -> str:
        return self._inner.save(pipeline_file)

    @classmethod
    def load(cls, pipeline_file: str) -> "TSPipeline":
        return cls(TimeSequencePipeline.load(pipeline_file))

    @property
    def config(self) -> Dict:
        return self._inner.config


class AutoTSTrainer:
    """`AutoTSTrainer` (`zouwu/autots/forecast.py:22`)."""

    def __init__(self, dt_col: str = "datetime", target_col: str = "value",
                 horizon: int = 1,
                 extra_features_col: Optional[Sequence[str]] = None,
                 seed: int = 0):
        self._predictor = TimeSequencePredictor(
            dt_col=dt_col, target_col=target_col, future_seq_len=horizon,
            extra_features_col=extra_features_col, seed=seed)

    def fit(self, train_df: pd.DataFrame,
            validation_df: Optional[pd.DataFrame] = None,
            recipe: Optional[Recipe] = None,
            metric: str = "mse", **search_kwargs) -> TSPipeline:
        """`search_kwargs` reach the SearchEngine: `n_workers=8` runs
        trials concurrently, `search_alg="tpe"` turns on the Bayesian
        sampler, `backend="ray"` dispatches via ray when importable."""
        recipe = recipe or LSTMGridRandomRecipe(num_rand_samples=1)
        pipeline = self._predictor.fit(train_df, validation_df,
                                       recipe=recipe, metric=metric,
                                       **search_kwargs)
        return TSPipeline(pipeline)
