"""Standalone forecasters (`zouwu/model/forecast/*.py`).

Uniform surface: `fit(x, y, epochs, batch_size)` on unrolled windows
(x: [B, past_len, F], y: [B, horizon]), `predict(x)`, `evaluate(x, y)` —
matching `LSTMForecaster` (`lstm_forecaster.py:21`), `MTNetForecaster`,
`TCNForecaster`, and the factorization-based `TCMFForecaster` (distributed
via Orca in the reference; single-host jit here)."""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

from analytics_zoo_tpu.automl.models import (TCMF, build_mtnet, build_tcn,
                                             build_seq2seq,
                                             build_vanilla_lstm,
                                             mtnet_past_seq_len)
from analytics_zoo_tpu.automl.pipeline import _metric_value


class _KerasForecaster:
    def __init__(self):
        self.model = None

    def fit(self, x: np.ndarray, y: np.ndarray, epochs: int = 10,
            batch_size: int = 32, validation_data=None):
        batch_size = min(batch_size, len(x))
        return self.model.fit(np.asarray(x, np.float32),
                              np.asarray(y, np.float32),
                              batch_size=batch_size, nb_epoch=epochs,
                              validation_data=validation_data)

    def predict(self, x: np.ndarray) -> np.ndarray:
        return np.asarray(self.model.predict(np.asarray(x, np.float32),
                                             batch_per_thread=64))

    def evaluate(self, x: np.ndarray, y: np.ndarray,
                 metrics: Sequence[str] = ("mse",)) -> Dict[str, float]:
        pred = self.predict(x)
        return {m: _metric_value(m, y, pred) for m in metrics}

    def save(self, path: str):
        self.model.save_weights(path)

    def restore(self, path: str):
        self.model.load_weights(path)


class LSTMForecaster(_KerasForecaster):
    """`lstm_forecaster.py:21`: 2-layer LSTM regressor."""

    def __init__(self, target_dim: int = 1, feature_dim: int = 1,
                 lstm_1_units: int = 16, lstm_2_units: int = 8,
                 dropout_1: float = 0.2, dropout_2: float = 0.2,
                 lr: float = 1e-3, past_seq_len: int = 2):
        super().__init__()
        self.model = build_vanilla_lstm(
            {"lstm_1_units": lstm_1_units, "lstm_2_units": lstm_2_units,
             "dropout_1": dropout_1, "dropout_2": dropout_2, "lr": lr},
            input_shape=(past_seq_len, feature_dim), output_dim=target_dim)


class Seq2SeqForecaster(_KerasForecaster):
    def __init__(self, target_dim: int = 1, feature_dim: int = 1,
                 latent_dim: int = 32, dropout: float = 0.2,
                 lr: float = 1e-3, past_seq_len: int = 4,
                 future_seq_len: int = 1):
        super().__init__()
        self.model = build_seq2seq(
            {"latent_dim": latent_dim, "dropout": dropout, "lr": lr},
            input_shape=(past_seq_len, feature_dim),
            output_dim=target_dim, horizon=future_seq_len)


class TCNForecaster(_KerasForecaster):
    def __init__(self, target_dim: int = 1, feature_dim: int = 1,
                 hidden_units: int = 32, levels: int = 3,
                 kernel_size: int = 3, dropout: float = 0.1,
                 lr: float = 1e-3, past_seq_len: int = 8):
        super().__init__()
        self.model = build_tcn(
            {"hidden_units": hidden_units, "levels": levels,
             "kernel_size": kernel_size, "dropout": dropout, "lr": lr},
            input_shape=(past_seq_len, feature_dim), output_dim=target_dim)


class MTNetForecaster(_KerasForecaster):
    """`mtnet_forecaster.py`: memory-network forecaster. Input windows must
    be (long_series_num + 1) * series_length long."""

    def __init__(self, target_dim: int = 1, feature_dim: int = 1,
                 long_series_num: int = 4, series_length: int = 4,
                 cnn_hid_size: int = 32, dropout: float = 0.1,
                 lr: float = 1e-3):
        super().__init__()
        self.config = {"time_step": series_length,
                       "long_num": long_series_num,
                       "cnn_hid_size": cnn_hid_size, "dropout": dropout,
                       "lr": lr}
        self.past_seq_len = mtnet_past_seq_len(self.config)
        self.model = build_mtnet(self.config, feature_dim=feature_dim)


class TCMFForecaster:
    """`tcmf_forecaster.py`: global matrix factorization over a panel of
    series. fit on {"id": [n], "y": [n, T]}, predict(horizon)."""

    def __init__(self, rank: int = 8, ar_lags: int = 8, steps: int = 300,
                 lr: float = 0.05, seed: int = 0):
        self._tcmf = TCMF(rank=rank, ar_lags=ar_lags, steps=steps, lr=lr,
                          seed=seed)
        self._ids: Optional[np.ndarray] = None

    def fit(self, x: Dict):
        y = np.asarray(x["y"], np.float32)
        self._ids = np.asarray(x.get("id", np.arange(len(y))))
        self._tcmf.fit(y)
        return self

    def predict(self, horizon: int = 24) -> Dict:
        preds = self._tcmf.predict(horizon)
        return {"id": self._ids, "prediction": preds}

    def evaluate(self, target_value: Dict,
                 metric: Sequence[str] = ("mse",)) -> Dict[str, float]:
        y_true = np.asarray(target_value["y"], np.float32)
        preds = self._tcmf.predict(y_true.shape[1])
        return {m: _metric_value(m, y_true, preds) for m in metric}
