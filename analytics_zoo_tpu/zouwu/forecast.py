"""Standalone forecasters (`zouwu/model/forecast/*.py`).

Uniform surface: `fit(x, y, epochs, batch_size)` on unrolled windows
(x: [B, past_len, F], y: [B, horizon]), `predict(x)`, `evaluate(x, y)` —
matching `LSTMForecaster` (`lstm_forecaster.py:21`), `MTNetForecaster`,
`TCNForecaster`, and the many-series `TCMFForecaster` — DeepGLO-hybrid
by default (`automl/tcmf.py`: global factorization + temporal nets, as
`tcmf/DeepGLO.py`), with a plain-factorization backend and
`distributed=True` sharded local-stage training over XShards (the
reference's Orca-trained mode)."""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

from analytics_zoo_tpu.automl.models import (TCMF, build_mtnet, build_tcn,
                                             build_seq2seq,
                                             build_vanilla_lstm,
                                             mtnet_past_seq_len)
from analytics_zoo_tpu.automl.pipeline import _metric_value


class _KerasForecaster:
    def __init__(self):
        self.model = None

    def fit(self, x: np.ndarray, y: np.ndarray, epochs: int = 10,
            batch_size: int = 32, validation_data=None):
        batch_size = min(batch_size, len(x))
        return self.model.fit(np.asarray(x, np.float32),
                              np.asarray(y, np.float32),
                              batch_size=batch_size, nb_epoch=epochs,
                              validation_data=validation_data)

    def predict(self, x: np.ndarray) -> np.ndarray:
        return np.asarray(self.model.predict(np.asarray(x, np.float32),
                                             batch_per_thread=64))

    def evaluate(self, x: np.ndarray, y: np.ndarray,
                 metrics: Sequence[str] = ("mse",)) -> Dict[str, float]:
        pred = self.predict(x)
        return {m: _metric_value(m, y, pred) for m in metrics}

    def save(self, path: str):
        self.model.save_weights(path)

    def restore(self, path: str):
        self.model.load_weights(path)


class LSTMForecaster(_KerasForecaster):
    """`lstm_forecaster.py:21`: 2-layer LSTM regressor."""

    def __init__(self, target_dim: int = 1, feature_dim: int = 1,
                 lstm_1_units: int = 16, lstm_2_units: int = 8,
                 dropout_1: float = 0.2, dropout_2: float = 0.2,
                 lr: float = 1e-3, past_seq_len: int = 2):
        super().__init__()
        self.model = build_vanilla_lstm(
            {"lstm_1_units": lstm_1_units, "lstm_2_units": lstm_2_units,
             "dropout_1": dropout_1, "dropout_2": dropout_2, "lr": lr},
            input_shape=(past_seq_len, feature_dim), output_dim=target_dim)


class Seq2SeqForecaster(_KerasForecaster):
    def __init__(self, target_dim: int = 1, feature_dim: int = 1,
                 latent_dim: int = 32, dropout: float = 0.2,
                 lr: float = 1e-3, past_seq_len: int = 4,
                 future_seq_len: int = 1):
        super().__init__()
        self.model = build_seq2seq(
            {"latent_dim": latent_dim, "dropout": dropout, "lr": lr},
            input_shape=(past_seq_len, feature_dim),
            output_dim=target_dim, horizon=future_seq_len)


class TCNForecaster(_KerasForecaster):
    def __init__(self, target_dim: int = 1, feature_dim: int = 1,
                 hidden_units: int = 32, levels: int = 3,
                 kernel_size: int = 3, dropout: float = 0.1,
                 lr: float = 1e-3, past_seq_len: int = 8):
        super().__init__()
        self.model = build_tcn(
            {"hidden_units": hidden_units, "levels": levels,
             "kernel_size": kernel_size, "dropout": dropout, "lr": lr},
            input_shape=(past_seq_len, feature_dim), output_dim=target_dim)


class MTNetForecaster(_KerasForecaster):
    """`mtnet_forecaster.py`: memory-network forecaster. Input windows must
    be (long_series_num + 1) * series_length long."""

    def __init__(self, target_dim: int = 1, feature_dim: int = 1,
                 long_series_num: int = 4, series_length: int = 4,
                 cnn_hid_size: int = 32, dropout: float = 0.1,
                 lr: float = 1e-3):
        super().__init__()
        self.config = {"time_step": series_length,
                       "long_num": long_series_num,
                       "cnn_hid_size": cnn_hid_size, "dropout": dropout,
                       "lr": lr}
        self.past_seq_len = mtnet_past_seq_len(self.config)
        self.model = build_mtnet(self.config, feature_dim=feature_dim)


class TCMFForecaster:
    """`tcmf_forecaster.py`: the many-series forecaster. Default backend
    is the DeepGLO hybrid (`automl/tcmf.py` — global factorization +
    temporal nets, matching `tcmf/DeepGLO.py`); `model="factorization"`
    keeps the plain `Y≈FX` + AR baseline. fit on {"id": [n],
    "y": [n, T]} or (distributed=True) an XShards of such panels;
    predict(horizon)."""

    def __init__(self, rank: int = 8, ar_lags: Optional[int] = None,
                 steps: int = 300, lr: float = 0.05, seed: int = 0,
                 model: str = "deepglo", distributed: bool = False,
                 **deepglo_kw):
        if model not in ("deepglo", "factorization"):
            raise ValueError("model must be deepglo|factorization")
        if distributed and model == "factorization":
            raise ValueError("distributed=True needs the deepglo backend "
                             "(the factorization baseline is single-host)")
        if model == "factorization":
            if deepglo_kw:
                raise TypeError(
                    f"{sorted(deepglo_kw)} only apply to the deepglo "
                    "backend")
            self._tcmf = TCMF(rank=rank, ar_lags=ar_lags or 8,
                              steps=steps, lr=lr, seed=seed)
        else:
            if ar_lags is not None:
                raise TypeError(
                    "ar_lags only applies to model='factorization' "
                    "(deepglo forecasts X with its temporal network)")
            from analytics_zoo_tpu.automl.tcmf import DeepGLO
            self._tcmf = DeepGLO(rank=rank, fact_steps=steps, lr=lr,
                                 seed=seed, **deepglo_kw)
        self.distributed = distributed
        self._ids: Optional[np.ndarray] = None

    def fit(self, x):
        from analytics_zoo_tpu.data.shards import XShards
        shards = None
        if isinstance(x, XShards):
            panels = x.collect()
            ids, offset = [], 0
            for p in panels:
                m = len(p["y"])
                # default ids number GLOBALLY across shards (per-shard
                # arange would alias series between shards)
                ids.append(np.asarray(
                    p.get("id", np.arange(offset, offset + m))))
                offset += m
            self._ids = np.concatenate(ids)
            if self.distributed:
                # fully sharded DeepGLO fit: the [n, T] panel is never
                # concatenated (global stage runs per shard too)
                self._tcmf.fit(shards=x)
                return self
            y = np.concatenate(
                [np.asarray(p["y"], np.float32) for p in panels])
        else:
            y = np.asarray(x["y"], np.float32)
            self._ids = np.asarray(x.get("id", np.arange(len(y))))
            if self.distributed:
                shards = XShards.partition({"y": y})
        if shards is not None:
            self._tcmf.fit(y, shards=shards)
        else:
            self._tcmf.fit(y)
        return self

    def predict(self, horizon: int = 24) -> Dict:
        preds = self._tcmf.predict(horizon)
        return {"id": self._ids, "prediction": preds}

    def evaluate(self, target_value: Dict,
                 metric: Sequence[str] = ("mse",)) -> Dict[str, float]:
        y_true = np.asarray(target_value["y"], np.float32)
        preds = self._tcmf.predict(y_true.shape[1])
        return {m: _metric_value(m, y_true, preds) for m in metric}
