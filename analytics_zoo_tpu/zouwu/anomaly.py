"""Anomaly detectors (`zouwu/model/anomaly.py`): threshold on forecast error
(re-exported from the model zoo) and an autoencoder detector over windows."""

from __future__ import annotations

from typing import Optional

import numpy as np

from analytics_zoo_tpu.models.anomalydetection import ThresholdDetector  # noqa: F401
from analytics_zoo_tpu.keras import Sequential
from analytics_zoo_tpu.keras import layers as L


class AEDetector:
    """Dense autoencoder on sliding windows; anomaly when reconstruction
    error exceeds the (1 - ratio) quantile (`anomaly.py` AEDetector)."""

    def __init__(self, roll_len: int = 24, compress_rate: float = 0.25,
                 ratio: float = 0.01, epochs: int = 20, lr: float = 1e-3,
                 batch_size: int = 32, seed: int = 0):
        self.roll_len = roll_len
        self.compress_rate = compress_rate
        self.ratio = ratio
        self.epochs = epochs
        self.batch_size = batch_size
        self.lr = lr
        self.model: Optional[Sequential] = None
        self.threshold: Optional[float] = None
        self._mean = self._std = None

    def _roll(self, y: np.ndarray) -> np.ndarray:
        y = np.asarray(y, np.float32).reshape(-1)
        if len(y) < self.roll_len:
            raise ValueError(f"series shorter than roll_len={self.roll_len}")
        return np.stack([y[i:i + self.roll_len]
                         for i in range(len(y) - self.roll_len + 1)])

    def fit(self, y: np.ndarray) -> "AEDetector":
        import optax
        win = self._roll(y)
        self._mean, self._std = win.mean(), win.std() + 1e-8
        win = (win - self._mean) / self._std
        hidden = max(2, int(self.roll_len * self.compress_rate))
        self.model = Sequential([
            L.Dense(hidden, activation="relu",
                    input_shape=(self.roll_len,)),
            L.Dense(self.roll_len),
        ])
        self.model.compile(optax.adam(self.lr), "mse")
        self.model.fit(win, win, batch_size=min(self.batch_size, len(win)),
                       nb_epoch=self.epochs)
        err = self._errors(win)
        self.threshold = float(np.quantile(err, 1.0 - self.ratio))
        return self

    def _errors(self, win_scaled: np.ndarray) -> np.ndarray:
        recon = np.asarray(self.model.predict(win_scaled,
                                              batch_per_thread=64))
        return np.mean((recon - win_scaled) ** 2, axis=1)

    def score(self, y: np.ndarray) -> np.ndarray:
        """Per-window anomaly flags (1 = anomalous window)."""
        if self.model is None:
            raise RuntimeError("fit first")
        win = (self._roll(y) - self._mean) / self._std
        return (self._errors(win) > self.threshold).astype(np.int32)

    def anomaly_indexes(self, y: np.ndarray) -> np.ndarray:
        """Indices (window starts) flagged anomalous."""
        return np.where(self.score(y) == 1)[0]
