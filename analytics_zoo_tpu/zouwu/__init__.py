"""Zouwu — user-facing time-series API (SURVEY §2.10, `pyzoo/zoo/zouwu/`).

`AutoTSTrainer`/`TSPipeline` (`zouwu/autots/forecast.py:22,86`) over the
AutoML search, plus standalone forecasters (`zouwu/model/forecast/*.py`) and
anomaly detectors (`zouwu/model/anomaly.py`).
"""

from analytics_zoo_tpu.zouwu.autots import AutoTSTrainer, TSPipeline  # noqa: F401
from analytics_zoo_tpu.zouwu.forecast import (  # noqa: F401
    LSTMForecaster, MTNetForecaster, Seq2SeqForecaster, TCNForecaster,
    TCMFForecaster)
from analytics_zoo_tpu.zouwu.anomaly import (  # noqa: F401
    AEDetector, ThresholdDetector)
