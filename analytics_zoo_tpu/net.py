"""Net loaders + transfer-learning graph surgery.

Reference: `Net.load[BigDL/Caffe/TF/Torch]` (`pipeline/api/Net.scala:51,103`),
`TFNet` frozen-graph/SavedModel inference (`pipeline/api/net/TFNet.scala:56`),
and `NetUtils.newGraph/freeze` transfer-learning surgery.

TPU mapping:
- `Net.load` — this framework's own saved models/weights.
- `Net.load_torch` — torch module -> native layers (`learn/torch_bridge`).
- `Net.load_tf` / `TFNet` — runs a TF SavedModel / frozen GraphDef through
  the in-image TensorFlow runtime (CPU) behind the same `predict` surface.
  This is the interop path the reference's TFNet JNI serves; for the TPU hot
  path, convert weights natively instead (e.g. `models/bert.py`
  `load_tf_checkpoint`) — a foreign graph cannot be jit-fused.
- `new_graph` / `freeze` — functional-model surgery: submodel at internal
  nodes; frozen layers' params leave the gradient path (they become
  captured constants, so jit folds them).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

import jax
import numpy as np

from analytics_zoo_tpu.keras.engine import KerasNet, Model, Node


class TFNet:
    """TF SavedModel / frozen-graph inference wrapper
    (`TFNet.scala:56,657`). Inference only, like the reference (backward
    exists there only via appended gradient ops)."""

    def __init__(self, tf_callable, input_names: Optional[List[str]] = None,
                 output_names: Optional[List[str]] = None):
        self._fn = tf_callable
        self.input_names = input_names
        self.output_names = output_names

    @classmethod
    def from_saved_model(cls, path: str,
                         signature: str = "serving_default") -> "TFNet":
        import tensorflow as tf
        loaded = tf.saved_model.load(path)
        fn = loaded.signatures[signature]
        cls_inst = cls(fn,
                       input_names=[k for k in fn.structured_input_signature[1]],
                       output_names=list(fn.structured_outputs))
        cls_inst._keepalive = loaded  # signatures hold weak refs
        return cls_inst

    @classmethod
    def from_frozen_graph(cls, path: str, inputs: Sequence[str],
                          outputs: Sequence[str],
                          input_dtypes: Optional[Sequence] = None) -> "TFNet":
        import tensorflow as tf
        gd = tf.compat.v1.GraphDef()
        with tf.io.gfile.GFile(path, "rb") as fh:
            gd.ParseFromString(fh.read())

        def _imported(*args):
            return tf.graph_util.import_graph_def(
                gd, input_map=dict(zip(inputs, args)),
                return_elements=list(outputs))

        dtypes = list(input_dtypes) if input_dtypes \
            else [tf.float32] * len(inputs)
        wrapped = tf.compat.v1.wrap_function(
            _imported, [tf.TensorSpec(None, dt) for dt in dtypes])
        return cls(wrapped, list(inputs), list(outputs))

    def _input_specs(self):
        sig = getattr(self._fn, "structured_input_signature", None)
        return sig[1] if sig else None

    def _run(self, xs):
        import tensorflow as tf
        specs = self._input_specs()
        if specs and self.input_names:
            # cast each input to its signature dtype (int token ids stay int)
            tensors = {
                name: tf.convert_to_tensor(
                    np.asarray(a).astype(
                        specs[name].dtype.as_numpy_dtype()))
                for name, a in zip(self.input_names, xs)}
            out = self._fn(**tensors)
            return [np.asarray(v) for v in out.values()] \
                if isinstance(out, dict) else [np.asarray(out)]
        tensors = [tf.convert_to_tensor(np.asarray(a)) for a in xs]
        out = self._fn(*tensors)
        return [np.asarray(v) for v in
                (out if isinstance(out, (list, tuple)) else [out])]

    def predict(self, x, batch_per_thread: int = 32):
        xs = list(x) if isinstance(x, (list, tuple)) else [x]
        xs = [np.asarray(a) for a in xs]
        n = xs[0].shape[0]
        chunks = []
        for s in range(0, n, batch_per_thread):
            chunks.append(self._run([a[s:s + batch_per_thread]
                                     for a in xs]))
        vals = [np.concatenate([c[i] for c in chunks])
                for i in range(len(chunks[0]))]
        return vals if len(vals) > 1 else vals[0]

    def to_inference_model(self, **kw):
        """Wrap for the serving stack (tf executes on host CPU)."""
        from analytics_zoo_tpu.serving.inference_model import InferenceModel
        im = InferenceModel(**kw)
        im._fn = lambda params, x: self.predict(x)
        im._params = {}
        im._jit = im._fn          # foreign runtime: no jax jit
        return im


class Net:
    """Loader facade (`Net.scala:51,103`)."""

    @staticmethod
    def load(path: str, cls=None):
        """Load a saved ZooModel dir (with `cls`) or bare weights into an
        existing architecture via `KerasNet.load_weights`."""
        if cls is not None:
            return cls.load_model(path)
        raise ValueError(
            "Net.load needs the model class for a ZooModel dir; for bare "
            "weights call model.load_weights(path) on the architecture")

    @staticmethod
    def load_torch(module) -> KerasNet:
        from analytics_zoo_tpu.learn.torch_bridge import convert_torch_module
        return convert_torch_module(module)

    @staticmethod
    def load_tf(path: str, inputs: Optional[Sequence[str]] = None,
                outputs: Optional[Sequence[str]] = None) -> TFNet:
        if inputs is not None and outputs is not None:
            return TFNet.from_frozen_graph(path, inputs, outputs)
        return TFNet.from_saved_model(path)

    @staticmethod
    def load_caffe(def_path: str, model_path: str):
        """Caffe import (`CaffeLoader.scala:718` analogue): deploy prototxt
        + binary caffemodel → native Model with pinned weights."""
        from analytics_zoo_tpu.caffe import load_caffe
        return load_caffe(def_path, model_path)

    @staticmethod
    def load_onnx(path: str):
        """ONNX import (`pipeline/api/onnx/onnx_loader.py:141` analogue):
        decode the ModelProto wire format, map ops onto native layers, pin
        exported weights."""
        from analytics_zoo_tpu.onnx import load_onnx
        return load_onnx(path)


# ---------------------------------------------------------------------------
# Graph surgery (`NetUtils.newGraph` / `freeze`)
# ---------------------------------------------------------------------------
def new_graph(model: Model, output_layer_names: Sequence[str]) -> Model:
    """Submodel ending at the named layers' output nodes — the transfer-
    learning trunk extractor (`NetUtils.newGraph`)."""
    wanted = set(output_layer_names)
    outputs: List[Node] = []
    for node in model._order:
        if node.layer is not None and node.layer.name in wanted:
            outputs.append(node)
            wanted.discard(node.layer.name)
    if wanted:
        raise ValueError(f"Layers not found in graph: {sorted(wanted)}")
    sub = Model(model.inputs, outputs)
    if model.params is not None:
        sub.params = {l.name: model.params[l.name] for l in sub._layers}
    return sub


class FrozenModel(KerasNet):
    """`freeze(names)`: the named layers' params become captured constants —
    out of the gradient path AND constant-folded by jit. `trainable_params`
    is what the optimizer sees; `apply` recombines."""

    def __init__(self, model: KerasNet, freeze_names: Sequence[str]):
        super().__init__()
        if model.params is None:
            raise ValueError("Freeze requires built params (fit or "
                             "ensure_built first)")
        self.inner = model
        names = set(freeze_names)
        layer_names = {l.name for l in model._ordered_layers()}
        missing = names - layer_names
        if missing:
            raise ValueError(f"Layers not found: {sorted(missing)}")
        # host copies on both sides: training donates its param buffers, and
        # aliasing the inner model's live arrays would delete them under it
        self.frozen = {k: jax.tree_util.tree_map(np.asarray, v)
                       for k, v in model.params.items() if k in names}
        self.params = {k: jax.tree_util.tree_map(np.asarray, v)
                       for k, v in model.params.items() if k not in names}

    def build(self, rng, input_shape=None):
        return self.params

    def apply(self, params, inputs, *, training=False, rng=None):
        full = dict(self.frozen)
        full.update(params)
        return self.inner.apply(full, inputs, training=training, rng=rng)

    def apply_and_state(self, params, inputs, *, training=False, rng=None):
        full = dict(self.frozen)
        full.update(params)
        out, upd = self.inner.apply_and_state(full, inputs,
                                              training=training, rng=rng)
        # drop state updates for frozen layers (their stats stay fixed)
        upd = {k: v for k, v in upd.items() if k not in self.frozen}
        return out, upd

    def compute_output_shape(self, input_shape):
        return self.inner.compute_output_shape(input_shape)

    def _ordered_layers(self):
        return [l for l in self.inner._ordered_layers()
                if l.name not in self.frozen]


def freeze(model: KerasNet, layer_names: Sequence[str]) -> FrozenModel:
    return FrozenModel(model, layer_names)


def freeze_up_to(model: Model, layer_name: str) -> FrozenModel:
    """Freeze every layer up to and including `layer_name` in topological
    order (`NetUtils.freezeUpTo`)."""
    names = []
    for node in model._order:
        if node.layer is None:
            continue
        if node.layer.name not in names:
            names.append(node.layer.name)
        if node.layer.name == layer_name:
            return freeze(model, names)
    raise ValueError(f"Layer {layer_name!r} not found")
