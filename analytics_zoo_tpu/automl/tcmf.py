"""DeepGLO-class TCMF: global factorization + temporal networks hybrid.

Reference: `pyzoo/zoo/automl/model/tcmf/DeepGLO.py` (904 LoC) — the
many-series forecaster whose three coupled pieces are (1) a low-rank
global factorization `Y ≈ F X`, (2) a temporal network over the basis
rows X ("X_seq": keeps X forecastable and regularizes the
factorization), and (3) a per-series local network ("Y_seq") that reads
each series' own history PLUS the global model's output as a covariate
and produces the final forecast. Prediction is rolling: X rolls forward
through X_seq, the global forecast is F·X_future, and Y_seq rolls over
[history, global] channels.

TPU-first deltas from the reference's torch implementation:
- the factorization + temporal-consistency refinement is ONE jitted
  `lax.scan` program (alternating Adam on {F, X} with the X_seq network
  frozen per phase) instead of per-minibatch Python loops;
- the temporal nets are the causal dilated-conv stack from
  `automl/models.py` (`CausalConv1D`) applied full-panel — every series
  is a batch row, so the MXU sees [n_series, T, C] convs;
- `distributed=True` trains the local net by per-shard gradient
  averaging over an `XShards` partition of the series panel (the
  Orca-trained mode of the reference), with identical numerics to the
  single-shard path when shards are equal-sized.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from analytics_zoo_tpu.automl.models import CausalConv1D


# ---------------------------------------------------------------------------
# functional TCN: [B, T, C_in] -> [B, T] one-step-ahead prediction
# ---------------------------------------------------------------------------
def _make_tcn(c_in: int, hidden: int, levels: int, kernel: int):
    convs = [CausalConv1D(hidden, kernel, dilation=2 ** i,
                          name=f"tcn{i}") for i in range(levels)]

    def init(rng):
        p = {}
        shape = (None, None, c_in)
        for i, c in enumerate(convs):
            rng, sub = jax.random.split(rng)
            p[f"c{i}"] = c.build(sub, shape)
            shape = shape[:-1] + (hidden,)
        rng, sub = jax.random.split(rng)
        p["head"] = (jax.random.normal(sub, (hidden, 1))
                     / math.sqrt(hidden)).astype(jnp.float32)
        return p

    def apply(p, x):
        h = x
        for i, c in enumerate(convs):
            h = c.call(p[f"c{i}"], h)
        return (h @ p["head"])[..., 0]          # [B, T]

    return init, apply


def _one_step_loss(apply_fn, params, x, target):
    """Causal one-step-ahead: prediction at position t (from inputs ≤ t)
    is scored against target[t+1]."""
    pred = apply_fn(params, x)                   # [B, T]
    return jnp.mean((pred[:, :-1] - target[:, 1:]) ** 2)


def _make_net_trainer(init_fn, apply_fn, steps: int, lr: float):
    """One jit-cached training program per net: data rides as traced
    arguments, so refine rounds reuse the compiled scan instead of
    recompiling a fresh closure each call."""
    opt = optax.adam(lr)

    @jax.jit
    def run(params, x, target):
        opt_state = opt.init(params)

        def step(carry, _):
            params, opt_state = carry
            l, g = jax.value_and_grad(
                lambda p: _one_step_loss(apply_fn, p, x, target))(params)
            updates, opt_state = opt.update(g, opt_state)
            return (optax.apply_updates(params, updates), opt_state), l
        (params, opt_state), ls = jax.lax.scan(
            step, (params, opt_state), None, length=steps)
        return params

    def train(x, target, rng):
        return run(init_fn(rng), x, target)

    return train


class DeepGLO:
    """Hybrid global-factorization + local-network forecaster
    (`DeepGLO.train_all_models` / `predict_horizon` capability)."""

    def __init__(self, rank: int = 8, hidden: int = 32, levels: int = 3,
                 kernel_size: int = 3, alpha: float = 0.3,
                 fact_steps: int = 300, seq_steps: int = 400,
                 refine_rounds: int = 2, lr: float = 0.05,
                 net_lr: float = 1e-2, seed: int = 0):
        self.rank, self.hidden = rank, hidden
        self.levels, self.kernel = levels, kernel_size
        self.alpha = alpha
        self.fact_steps, self.seq_steps = fact_steps, seq_steps
        self.refine_rounds = refine_rounds
        self.lr, self.net_lr = lr, net_lr
        self.seed = seed
        self.F = self.X = None
        self._x_params = self._y_params = None
        self._x_apply = self._y_apply = None
        self._y_mu = self._y_sd = None
        self._yn_parts = None

    # -- global stage ------------------------------------------------------
    def _fact_run(self, x_apply):
        """jit-cached factorization program: y/x_params/alpha are traced
        args so every refine round reuses one compiled scan. The temporal
        term is always present, scaled by alpha (0.0 = plain round)."""
        if getattr(self, "_fact_cached", None) is not None:
            return self._fact_cached
        opt = optax.adam(self.lr)

        @jax.jit
        def run(params, y, x_params, alpha):
            opt_state = opt.init(params)

            def loss(p):
                recon = jnp.mean((p["F"] @ p["X"] - y) ** 2)
                reg = 1e-4 * (jnp.mean(p["F"] ** 2)
                              + jnp.mean(p["X"] ** 2))
                # X rows must stay predictable by the (frozen) X_seq net
                xrows = p["X"][:, :, None]               # [k, T, 1]
                pred = x_apply(x_params, xrows)
                temporal = jnp.mean((pred[:, :-1] - p["X"][:, 1:]) ** 2)
                return recon + reg + alpha * temporal

            def step(carry, _):
                params, opt_state = carry
                l, g = jax.value_and_grad(loss)(params)
                updates, opt_state = opt.update(g, opt_state)
                return (optax.apply_updates(params, updates),
                        opt_state), l
            (params, opt_state), _ = jax.lax.scan(
                step, (params, opt_state), None, length=self.fact_steps)
            return params

        self._fact_cached = run
        return run

    def _factorize(self, y, x_params, x_apply, rng, temporal: bool):
        n, t = y.shape
        if self.F is None:
            kf, kx = jax.random.split(rng)
            params = {"F": jax.random.normal(kf, (n, self.rank)) * 0.1,
                      "X": jax.random.normal(kx, (self.rank, t)) * 0.1}
        else:
            params = {"F": jnp.asarray(self.F), "X": jnp.asarray(self.X)}
        alpha = jnp.float32(self.alpha if temporal else 0.0)
        params = self._fact_run(x_apply)(params, y, x_params, alpha)
        self.F = np.asarray(params["F"])
        self.X = np.asarray(params["X"])

    def _fact_sharded_fns(self, x_apply):
        """jit-cached per-fit program pieces for the sharded global stage
        (same role as `_fact_run` for the in-memory stage): one trace per
        fit, reused across the 1 + refine_rounds factorization rounds."""
        if getattr(self, "_fact_sharded_cached", None) is not None:
            return self._fact_sharded_cached
        opt = optax.adam(self.lr)

        @jax.jit
        def shard_grad(f_i, x, yn_i, w):
            def li(f_i, x):
                recon = jnp.mean((f_i @ x - yn_i) ** 2)
                return w * (recon + 1e-4 * jnp.mean(f_i ** 2))
            return jax.grad(li, argnums=(0, 1))(f_i, x)

        @jax.jit
        def central_grad(x, x_params, alpha):
            def lc(x):
                xrows = x[:, :, None]
                pred = x_apply(x_params, xrows)
                tmp = jnp.mean((pred[:, :-1] - x[:, 1:]) ** 2)
                return 1e-4 * jnp.mean(x ** 2) + alpha * tmp
            return jax.grad(lc)(x)

        @jax.jit
        def apply_updates(params, opt_state, grads):
            updates, opt_state = opt.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state

        self._fact_sharded_cached = (opt, shard_grad, central_grad,
                                     apply_updates)
        return self._fact_sharded_cached

    def _factorize_sharded(self, yns, sizes, x_params, x_apply, rng,
                           temporal: bool):
        """Distributed global stage: {F, X} Adam with F sharded by series
        panel. The full loss decomposes exactly — recon and the F-reg are
        size-weighted per-shard sums (mean over n·T rows = Σ (m_i/n)·
        mean_i), the X-reg and X_seq temporal term are central — so the
        assembled gradient equals the in-memory `_factorize` gradient and
        the Adam trajectories match (`tests/test_tcmf.py`). The [n, T]
        panel is never concatenated; only [m_i, rank] F parts and the
        [rank, T] X are updated."""
        n = sum(sizes)
        t = yns[0].shape[1]
        if self.F is None:
            kf, kx = jax.random.split(rng)
            f_full = jax.random.normal(kf, (n, self.rank)) * 0.1
            bounds = np.cumsum([0] + sizes)
            f_parts = tuple(f_full[lo:hi]
                            for lo, hi in zip(bounds[:-1], bounds[1:]))
            x = jax.random.normal(kx, (self.rank, t)) * 0.1
        else:
            bounds = np.cumsum([0] + sizes)
            f_parts = tuple(jnp.asarray(self.F[lo:hi])
                            for lo, hi in zip(bounds[:-1], bounds[1:]))
            x = jnp.asarray(self.X)
        alpha = jnp.float32(self.alpha if temporal else 0.0)
        opt, shard_grad, central_grad, apply_updates = \
            self._fact_sharded_fns(x_apply)
        params = {"F": f_parts, "X": x}
        opt_state = opt.init(params)
        ws = [jnp.float32(m / n) for m in sizes]
        for _ in range(self.fact_steps):
            g_x = central_grad(params["X"], x_params, alpha)
            g_f = []
            for f_i, yn_i, w in zip(params["F"], yns, ws):
                gf_i, gx_i = shard_grad(f_i, params["X"], yn_i, w)
                g_f.append(gf_i)
                g_x = g_x + gx_i
            params, opt_state = apply_updates(
                params, opt_state, {"F": tuple(g_f), "X": g_x})
        self.F = np.concatenate([np.asarray(f) for f in params["F"]])
        self.X = np.asarray(params["X"])

    def _run_global_stage(self, factorize, x_init, x_apply, r_x):
        """The alternating schedule shared by the in-memory and sharded
        paths: plain factorization (alpha=0, untrained X_seq), then
        refine_rounds of (train X_seq on X, re-factorize with the
        temporal term), then a final X_seq fit for prediction."""
        x_train = _make_net_trainer(x_init, x_apply, self.seq_steps,
                                    self.net_lr)
        self._x_params = x_init(r_x)
        factorize(False)
        for _ in range(self.refine_rounds):
            xrows = jnp.asarray(self.X)[:, :, None]
            self._x_params = x_train(xrows, jnp.asarray(self.X), r_x)
            factorize(True)
        xrows = jnp.asarray(self.X)[:, :, None]
        self._x_params = x_train(xrows, jnp.asarray(self.X), r_x)

    def _panels_from_parts(self, yns, sizes):
        """[yn, global-recon] input panels for the local stage, one global
        block per shard (never the full [n, T] reconstruction)."""
        panels, off = [], 0
        for yn, m in zip(yns, sizes):
            g = jnp.asarray(self.F[off:off + m] @ self.X)
            panels.append((jnp.stack([yn, g], axis=-1), yn, m))
            off += m
        return panels

    def _fit_sharded(self, shards) -> "DeepGLO":
        """Whole-pipeline distributed fit over an XShards of {"y": [m, T]}
        panels (VERDICT r3 #8): per-shard normalization, sharded global
        factorization, central X_seq refinement (X is [rank, T] — small),
        and the per-shard-gradient local stage. The full [n_series, T]
        panel is never materialized; per-series stats ([n, 1]) and the
        factor F ([n, rank]) are the only full-length arrays kept."""
        raws = [np.asarray(sh["y"], np.float32) for sh in shards.collect()]
        sizes = [p.shape[0] for p in raws]
        mus = [p.mean(axis=1, keepdims=True) for p in raws]
        sds = [p.std(axis=1, keepdims=True) + 1e-6 for p in raws]
        yns = [jnp.asarray((p - m) / s) for p, m, s in zip(raws, mus, sds)]
        self.F = self.X = None
        self._fact_cached = None
        self._fact_sharded_cached = None
        self._y_mu = np.concatenate(mus)
        self._y_sd = np.concatenate(sds)
        self._yn_parts = yns
        self._yn_hist = None
        rng = jax.random.PRNGKey(self.seed)
        r_fact, r_x, r_y = jax.random.split(rng, 3)

        x_init, x_apply = _make_tcn(1, self.hidden, self.levels,
                                    self.kernel)
        self._x_apply = x_apply
        self._run_global_stage(
            lambda temporal: self._factorize_sharded(
                yns, sizes, self._x_params, x_apply, r_fact,
                temporal=temporal),
            x_init, x_apply, r_x)

        y_init, y_apply = _make_tcn(2, self.hidden, self.levels,
                                    self.kernel)
        self._y_apply = y_apply
        self._y_params = self._train_local_panels(
            y_init, y_apply, self._panels_from_parts(yns, sizes), r_y)
        return self

    # -- fit ---------------------------------------------------------------
    def fit(self, y: Optional[np.ndarray] = None, shards=None) -> "DeepGLO":
        """y: [n_series, T]. `shards`: optional XShards of {"y": [m, T]}
        panels. With BOTH, the global stage runs in-memory and only the
        local stage trains by per-shard gradient averaging; with shards
        ONLY (y=None), the whole pipeline runs sharded
        (`_fit_sharded`)."""
        if y is None:
            if shards is None:
                raise ValueError("fit needs y or shards")
            return self._fit_sharded(shards)
        y = np.asarray(y, np.float32)
        self._yn_parts = None
        # every fit is fresh — a warm start from a previous panel would
        # silently bias (or shape-crash) the factorization
        self.F = self.X = None
        self._fact_cached = None
        self._y_mu = y.mean(axis=1, keepdims=True)
        self._y_sd = y.std(axis=1, keepdims=True) + 1e-6
        yn = (y - self._y_mu) / self._y_sd
        self._yn_hist = yn                       # rolling-forecast seed
        yj = jnp.asarray(yn)
        rng = jax.random.PRNGKey(self.seed)
        r_fact, r_x, r_y = jax.random.split(rng, 3)

        x_init, x_apply = _make_tcn(1, self.hidden, self.levels,
                                    self.kernel)
        self._x_apply = x_apply
        self._run_global_stage(
            lambda temporal: self._factorize(
                yj, self._x_params, x_apply, r_fact, temporal=temporal),
            x_init, x_apply, r_x)

        # local stage: per-series net over [y, global] channels
        y_init, y_apply = _make_tcn(2, self.hidden, self.levels,
                                    self.kernel)
        self._y_apply = y_apply
        g = jnp.asarray(self.F @ self.X)                 # global recon
        if shards is None:
            inp = jnp.stack([yj, g], axis=-1)            # [n, T, 2]
            self._y_params = _make_net_trainer(
                y_init, y_apply, self.seq_steps, self.net_lr)(
                inp, yj, r_y)
        else:
            self._y_params = self._train_local_sharded(
                y_init, y_apply, shards, r_y)
        return self

    def _train_local_sharded(self, y_init, y_apply, shards, rng):
        """Distributed local stage: same update rule, per-shard gradients
        combined SIZE-WEIGHTED each step (sum(m_i·g_i)/n — a smaller
        shard must not overweight its series), matching the full-batch
        gradient exactly for any shard split (the reference's
        Orca-distributed Y_seq training)."""
        panels = []
        offset = 0
        for sh in shards.collect():
            m = np.asarray(sh["y"], np.float32).shape[0]
            yn = jnp.asarray(
                (np.asarray(sh["y"], np.float32)
                 - self._y_mu[offset:offset + m])
                / self._y_sd[offset:offset + m])
            g = jnp.asarray(self.F[offset:offset + m] @ self.X)
            panels.append((jnp.stack([yn, g], axis=-1), yn, m))
            offset += m
        return self._train_local_panels(y_init, y_apply, panels, rng)

    def _train_local_panels(self, y_init, y_apply, panels, rng):
        """Core of the sharded local stage over prepared
        ([m, T, 2] input, [m, T] target, m) panels."""
        n_total = sum(m for _, _, m in panels)
        params = y_init(rng)
        opt = optax.adam(self.net_lr)
        opt_state = opt.init(params)

        @jax.jit
        def shard_grad(params, x, t):
            return jax.grad(
                lambda p: _one_step_loss(y_apply, p, x, t))(params)

        @jax.jit
        def apply_updates(params, opt_state, grads):
            updates, opt_state = opt.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state

        for _ in range(self.seq_steps):
            grads = None
            for x, t, m in panels:                   # one grad per shard
                g = jax.tree_util.tree_map(
                    lambda a, w=m / n_total: a * w,
                    shard_grad(params, x, t))
                grads = g if grads is None else jax.tree_util.tree_map(
                    jnp.add, grads, g)
            params, opt_state = apply_updates(params, opt_state, grads)
        return params

    # -- prediction --------------------------------------------------------
    def _roll(self, apply_fn, params, seq, horizon: int,
              covariate: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        """Autoregressive rolling (`predict_future_batch`): append the
        net's last-position prediction, `horizon` times. seq: [B, T];
        covariate: [B, T+horizon] extra channel (global forecast)."""
        out = seq
        for h in range(horizon):
            t = out.shape[1]
            if covariate is None:
                x = out[:, :, None]
            else:
                x = jnp.stack([out, covariate[:, :t]], axis=-1)
            nxt = apply_fn(params, x)[:, -1]
            out = jnp.concatenate([out, nxt[:, None]], axis=1)
        return out[:, -horizon:]

    def predict(self, horizon: int) -> np.ndarray:
        if self.F is None:
            raise RuntimeError("fit first")
        xf = self._roll(self._x_apply, self._x_params,
                        jnp.asarray(self.X), horizon)
        x_full = jnp.concatenate([jnp.asarray(self.X), xf], axis=1)
        if self._yn_parts is not None:
            # sharded fit: roll per panel, full history never concatenated
            outs, off = [], 0
            for yn in self._yn_parts:
                m = yn.shape[0]
                g = jnp.asarray(self.F[off:off + m]) @ x_full
                outs.append(self._roll(self._y_apply, self._y_params, yn,
                                       horizon, covariate=g))
                off += m
            out = jnp.concatenate(outs, axis=0)
        else:
            g_full = jnp.asarray(self.F) @ x_full    # [n, T+h] global
            # local refinement over [y, global]
            out = self._roll(self._y_apply, self._y_params,
                             jnp.asarray(self._yn_hist), horizon,
                             covariate=g_full)
        return np.asarray(out) * self._y_sd + self._y_mu

    def rolling_validation(self, y: np.ndarray, tau: int = 8,
                           n_windows: int = 3) -> float:
        """Mean horizon-MSE over n_windows rolling tau-step splits
        (`DeepGLO.rolling_validation`): fit on the prefix, score tau
        ahead, advance."""
        y = np.asarray(y, np.float32)
        errs = []
        for w in range(n_windows, 0, -1):
            split = y.shape[1] - w * tau
            self.fit(y[:, :split])                # fit() is always fresh
            pred = self.predict(tau)
            errs.append(float(np.mean(
                (pred - y[:, split:split + tau]) ** 2)))
        return float(np.mean(errs))
