"""Time-series model builders (`automl/model/`: VanillaLSTM.py, Seq2Seq.py
341, MTNet_keras.py 614, tcn.py 151, tcmf/DeepGLO.py 904).

Each builder takes a trial config dict and returns a compiled Keras-style
model with a uniform `fit/predict` surface so the search engine and the
zouwu forecasters drive them interchangeably. TCN's dilated causal convs are
a custom layer over `lax.conv_general_dilated` (the torch reference uses
Chomp1d+weight-norm; XLA fuses the pad+conv, so causality is just asymmetric
padding). TCMF is DeepGLO-lite: global matrix factorization Y ~ F @ X trained
by alternating jit'd gradient steps, X forecast forward by a per-factor
linear AR model."""

from __future__ import annotations

import math
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.keras import Input, Model, Sequential
from analytics_zoo_tpu.keras import layers as L
from analytics_zoo_tpu.keras.engine import Layer
import optax


# ---------------------------------------------------------------------------
# VanillaLSTM (`automl/model/VanillaLSTM.py`)
# ---------------------------------------------------------------------------
def build_vanilla_lstm(config: Dict, input_shape, output_dim: int = 1):
    """lstm_1 (seq) -> dropout -> lstm_2 -> dropout -> dense(out)."""
    m = Sequential([
        L.LSTM(int(config.get("lstm_1_units", 32)), input_shape=input_shape,
               return_sequences=True),
        L.Dropout(float(config.get("dropout_1", 0.2))),
        L.LSTM(int(config.get("lstm_2_units", 32))),
        L.Dropout(float(config.get("dropout_2", 0.2))),
        L.Dense(output_dim),
    ])
    m.compile(optax.adam(float(config.get("lr", 1e-3))), "mse", ["mse"])
    return m


# ---------------------------------------------------------------------------
# Seq2Seq forecaster (`automl/model/Seq2Seq.py`): numeric encoder-decoder
# ---------------------------------------------------------------------------
class _RepeatLast(Layer):
    """Take the encoder's final state and repeat it horizon times."""

    def __init__(self, horizon: int, **kw):
        super().__init__(**kw)
        self.horizon = horizon

    def call(self, params, x, *, training=False, rng=None):
        return jnp.repeat(x[:, None, :], self.horizon, axis=1)

    def compute_output_shape(self, input_shape):
        return (input_shape[0], self.horizon, input_shape[-1])


def build_seq2seq(config: Dict, input_shape, output_dim: int = 1,
                  horizon: int = 1):
    latent = int(config.get("latent_dim", 32))
    m = Sequential([
        L.LSTM(latent, input_shape=input_shape),       # encoder final state
        L.Dropout(float(config.get("dropout", 0.2))),
        _RepeatLast(horizon),
        L.LSTM(latent, return_sequences=True),          # decoder
        L.TimeDistributed(L.Dense(output_dim)),
        L.Reshape((horizon * output_dim,)) if output_dim == 1 else
        L.Reshape((horizon, output_dim)),
    ])
    m.compile(optax.adam(float(config.get("lr", 1e-3))), "mse", ["mse"])
    return m


# ---------------------------------------------------------------------------
# TCN (`automl/model/tcn.py`): dilated causal conv residual blocks
# ---------------------------------------------------------------------------
class CausalConv1D(Layer):
    """Causal dilated conv: left-pad (k-1)*d then VALID conv — the fused
    equivalent of the torch reference's pad+Chomp1d."""

    def __init__(self, filters: int, kernel_size: int, dilation: int = 1,
                 activation: Optional[str] = "relu", **kw):
        super().__init__(**kw)
        self.filters, self.k, self.d = filters, kernel_size, dilation
        self.activation = L.get_activation(activation) if activation else None

    def build(self, rng, input_shape):
        cin = input_shape[-1]
        fan_in = self.k * cin
        w = jax.random.normal(rng, (self.k, cin, self.filters)) \
            / math.sqrt(fan_in)
        return {"kernel": w.astype(jnp.float32),
                "bias": jnp.zeros((self.filters,), jnp.float32)}

    def call(self, params, x, *, training=False, rng=None):
        from analytics_zoo_tpu.keras.layers import _match_param_dtype
        x = _match_param_dtype(x, params["kernel"])
        pad = (self.k - 1) * self.d
        y = jax.lax.conv_general_dilated(
            x, params["kernel"], window_strides=(1,),
            padding=[(pad, 0)], rhs_dilation=(self.d,),
            dimension_numbers=("NWC", "WIO", "NWC"))
        y = y + params["bias"]
        return self.activation(y) if self.activation else y

    def compute_output_shape(self, input_shape):
        return input_shape[:-1] + (self.filters,)


class _TCNBlock(Layer):
    """Residual block: 2x causal conv + dropout, 1x1 shortcut on channel
    change (`tcn.py` TemporalBlock)."""

    def __init__(self, filters: int, kernel_size: int, dilation: int,
                 dropout: float = 0.0, **kw):
        super().__init__(**kw)
        self.c1 = CausalConv1D(filters, kernel_size, dilation,
                               name=self.name + "_c1")
        self.c2 = CausalConv1D(filters, kernel_size, dilation,
                               name=self.name + "_c2")
        self.filters = filters
        self.dropout = dropout

    def build(self, rng, input_shape):
        k1, k2, k3 = jax.random.split(rng, 3)
        p = {"c1": self.c1.build(k1, input_shape),
             "c2": self.c2.build(
                 k2, input_shape[:-1] + (self.filters,))}
        if input_shape[-1] != self.filters:
            p["shortcut"] = (jax.random.normal(
                k3, (input_shape[-1], self.filters))
                / math.sqrt(input_shape[-1])).astype(jnp.float32)
        return p

    def call(self, params, x, *, training=False, rng=None):
        y = self.c1.call(params["c1"], x)
        if training and rng is not None and self.dropout > 0:
            rng, sub = jax.random.split(rng)
            keep = 1.0 - self.dropout
            y = jnp.where(jax.random.bernoulli(sub, keep, y.shape),
                          y / keep, 0.0)
        y = self.c2.call(params["c2"], y)
        if training and rng is not None and self.dropout > 0:
            keep = 1.0 - self.dropout
            y = jnp.where(jax.random.bernoulli(rng, keep, y.shape),
                          y / keep, 0.0)
        sc = x @ params["shortcut"] if "shortcut" in params else x
        return jax.nn.relu(y + sc)

    def compute_output_shape(self, input_shape):
        return input_shape[:-1] + (self.filters,)


def build_tcn(config: Dict, input_shape, output_dim: int = 1):
    hidden = int(config.get("hidden_units", 32))
    levels = int(config.get("levels", 3))
    k = int(config.get("kernel_size", 3))
    drop = float(config.get("dropout", 0.1))
    layers = []
    for i in range(levels):
        kw = {"input_shape": input_shape} if i == 0 else {}
        layers.append(_TCNBlock(hidden, k, dilation=2 ** i, dropout=drop,
                                **kw))
    layers += [L.Select(1, -1), L.Dense(output_dim)]
    m = Sequential(layers)
    m.compile(optax.adam(float(config.get("lr", 1e-3))), "mse", ["mse"])
    return m


# ---------------------------------------------------------------------------
# MTNet (`automl/model/MTNet_keras.py`): memory of long_num windows encoded
# by CNN, attention against the current window, + AR highway
# ---------------------------------------------------------------------------
class _MTNetCore(Layer):
    def __init__(self, time_step: int, long_num: int, feature_dim: int,
                 cnn_hid: int, dropout: float, **kw):
        super().__init__(**kw)
        self.T, self.n, self.F = time_step, long_num, feature_dim
        self.cnn_hid = cnn_hid
        self.dropout = dropout

    def build(self, rng, input_shape):
        ks = jax.random.split(rng, 5)
        F, H = self.F, self.cnn_hid
        # conv over time within a window: kernel [w, F, H]
        w = min(3, self.T)
        return {
            "conv": (jax.random.normal(ks[0], (w, F, H))
                     / math.sqrt(w * F)).astype(jnp.float32),
            "conv_b": jnp.zeros((H,), jnp.float32),
            "attn": (jax.random.normal(ks[1], (H, H))
                     / math.sqrt(H)).astype(jnp.float32),
            "gru_out": (jax.random.normal(ks[2], (2 * H, H))
                        / math.sqrt(2 * H)).astype(jnp.float32),
            "head": (jax.random.normal(ks[3], (H, 1))
                     / math.sqrt(H)).astype(jnp.float32),
            "ar": (jax.random.normal(ks[4], (self.T,))
                   / math.sqrt(self.T)).astype(jnp.float32),
        }

    def _encode(self, params, wins):
        """wins: [B, n, T, F] -> [B, n, H] via causal conv + max pool."""
        from analytics_zoo_tpu.keras.layers import _match_param_dtype
        wins = _match_param_dtype(wins, params["conv"])
        B, n, T, F = wins.shape
        x = wins.reshape(B * n, T, F)
        y = jax.lax.conv_general_dilated(
            x, params["conv"], (1,), "VALID",
            dimension_numbers=("NWC", "WIO", "NWC"))
        y = jax.nn.relu(y + params["conv_b"])
        return jnp.max(y, axis=1).reshape(B, n, -1)

    def call(self, params, x, *, training=False, rng=None):
        # x: [B, (n+1)*T, F] — long memory windows + current window
        B = x.shape[0]
        wins = x.reshape(B, self.n + 1, self.T, self.F)
        mem, cur = wins[:, :-1], wins[:, -1:]
        m_enc = self._encode(params, mem)            # [B, n, H]
        c_enc = self._encode(params, cur)[:, 0]      # [B, H]
        scores = jnp.einsum("bnh,hk,bk->bn", m_enc, params["attn"], c_enc)
        alpha = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("bn,bnh->bh", alpha, m_enc)
        h = jax.nn.relu(jnp.concatenate([ctx, c_enc], axis=-1)
                        @ params["gru_out"])
        if training and rng is not None and self.dropout > 0:
            keep = 1.0 - self.dropout
            h = jnp.where(jax.random.bernoulli(rng, keep, h.shape),
                          h / keep, 0.0)
        nonlinear = (h @ params["head"])[:, 0]
        ar = jnp.einsum("bt,t->b", x[:, -self.T:, 0], params["ar"])
        return (nonlinear + ar)[:, None]

    def compute_output_shape(self, input_shape):
        return (input_shape[0], 1)


def build_mtnet(config: Dict, feature_dim: int):
    T = int(config.get("time_step", 4))
    n = int(config.get("long_num", 4))
    core = _MTNetCore(T, n, feature_dim,
                      int(config.get("cnn_hid_size", 32)),
                      float(config.get("dropout", 0.1)),
                      input_shape=((n + 1) * T, feature_dim))
    m = Sequential([core])
    m.compile(optax.adam(float(config.get("lr", 1e-3))), "mse", ["mse"])
    return m


def mtnet_past_seq_len(config: Dict) -> int:
    return (int(config.get("long_num", 4)) + 1) \
        * int(config.get("time_step", 4))


# ---------------------------------------------------------------------------
# TCMF / DeepGLO-lite (`automl/model/tcmf/DeepGLO.py`)
# ---------------------------------------------------------------------------
class TCMF:
    """Global factorization Y[n, t] ~ F[n, k] @ X[k, t]; forecast X with a
    per-factor linear AR(p) model. Captures DeepGLO's global component (the
    local per-series network is the reference's refinement stage)."""

    def __init__(self, rank: int = 8, ar_lags: int = 8, steps: int = 300,
                 lr: float = 0.05, seed: int = 0):
        self.rank, self.ar_lags = rank, ar_lags
        self.steps, self.lr = steps, lr
        self.seed = seed
        self.F = self.X = self.ar = None

    def fit(self, y: np.ndarray) -> "TCMF":
        y = jnp.asarray(y, jnp.float32)
        n, t = y.shape
        k = self.rank
        key = jax.random.PRNGKey(self.seed)
        kf, kx = jax.random.split(key)
        params = {"F": jax.random.normal(kf, (n, k)) * 0.1,
                  "X": jax.random.normal(kx, (k, t)) * 0.1}
        opt = optax.adam(self.lr)
        opt_state = opt.init(params)

        def loss(p):
            return jnp.mean((p["F"] @ p["X"] - y) ** 2) \
                + 1e-4 * (jnp.mean(p["F"] ** 2) + jnp.mean(p["X"] ** 2))

        @jax.jit
        def run(params, opt_state):
            def step(carry, _):
                params, opt_state = carry
                l, g = jax.value_and_grad(loss)(params)
                updates, opt_state = opt.update(g, opt_state)
                return (optax.apply_updates(params, updates), opt_state), l
            (params, opt_state), ls = jax.lax.scan(
                step, (params, opt_state), None, length=self.steps)
            return params, opt_state, ls

        params, opt_state, _ = run(params, opt_state)
        self.F = np.asarray(params["F"])
        self.X = np.asarray(params["X"])
        self._fit_ar()
        return self

    def _fit_ar(self):
        """Least-squares AR(p) per factor row of X."""
        p = min(self.ar_lags, self.X.shape[1] - 1)
        self.ar = []
        for row in self.X:
            A = np.stack([row[i:i + p] for i in range(len(row) - p)])
            b = row[p:]
            coef, *_ = np.linalg.lstsq(A, b, rcond=None)
            self.ar.append(coef)
        self.ar = np.stack(self.ar)           # [k, p]
        self._p = p

    def predict(self, horizon: int) -> np.ndarray:
        if self.F is None:
            raise RuntimeError("fit first")
        X = self.X.copy()
        for _ in range(horizon):
            nxt = np.einsum("kp,kp->k", self.ar, X[:, -self._p:])
            X = np.concatenate([X, nxt[:, None]], axis=1)
        return self.F @ X[:, -horizon:]


# ---------------------------------------------------------------------------
# registry used by the search pipeline
# ---------------------------------------------------------------------------
def build_model(config: Dict, input_shape, output_dim: int = 1):
    name = config.get("model", "VanillaLSTM")
    if name == "VanillaLSTM":
        return build_vanilla_lstm(config, input_shape, output_dim)
    if name == "Seq2Seq":
        # horizon steps of a single target -> [B, horizon] predictions
        return build_seq2seq(config, input_shape, output_dim=1,
                             horizon=output_dim)
    if name == "TCN":
        return build_tcn(config, input_shape, output_dim)
    if name == "MTNet":
        return build_mtnet(config, feature_dim=input_shape[-1])
    raise ValueError(f"Unknown model {name!r}")
