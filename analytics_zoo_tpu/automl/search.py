"""Search engine: sampling DSL + trial runner + successive halving.

Reference: `RayTuneSearchEngine` (`automl/search/ray_tune_search_engine.py:37`,
`compile` `:61`, `run` `:171`) with SearchAlg (skopt BO) and schedulers
(ASHA). Here: the same `compile(data, model_builder, recipe)` / `run()` /
`get_best_trials` surface, executed in-process. Trials are pure functions
`train_fn(config, data, budget) -> {"metric": float, ...}` so the engine is
agnostic to what a trial trains (a jit'd TPU model, an sklearn fit, ...).
"""

from __future__ import annotations

import copy
import itertools
import logging
import math
import random
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

log = logging.getLogger("analytics_zoo_tpu.automl")


# ---------------------------------------------------------------------------
# Sample functions (the tune.* DSL used in recipes)
# ---------------------------------------------------------------------------
class _Sampler:
    def sample(self, rng: random.Random):
        raise NotImplementedError


class _Choice(_Sampler):
    def __init__(self, options: Sequence):
        self.options = list(options)

    def sample(self, rng):
        return rng.choice(self.options)


class _Grid:
    """Expanded exhaustively (tune.grid_search)."""

    def __init__(self, options: Sequence):
        self.options = list(options)


class _Uniform(_Sampler):
    def __init__(self, lo, hi):
        self.lo, self.hi = lo, hi

    def sample(self, rng):
        return rng.uniform(self.lo, self.hi)


class _QUniform(_Sampler):
    def __init__(self, lo, hi, q):
        self.lo, self.hi, self.q = lo, hi, q

    def sample(self, rng):
        v = rng.uniform(self.lo, self.hi)
        return type(self.q)(round(v / self.q) * self.q)


class _LogUniform(_Sampler):
    def __init__(self, lo, hi):
        self.lo, self.hi = math.log(lo), math.log(hi)

    def sample(self, rng):
        return math.exp(rng.uniform(self.lo, self.hi))


class _RandInt(_Sampler):
    def __init__(self, lo, hi):
        self.lo, self.hi = lo, hi

    def sample(self, rng):
        return rng.randint(self.lo, self.hi - 1)  # tune.randint excl. upper


class hp:
    """Sample-function namespace (tune.*-compatible names)."""
    choice = _Choice
    grid_search = _Grid
    uniform = _Uniform
    quniform = _QUniform
    loguniform = _LogUniform
    randint = _RandInt


def _expand(space: Dict[str, Any], num_samples: int,
            seed: int = 0) -> List[Dict[str, Any]]:
    """Grid entries expand cartesian; samplers draw `num_samples` times per
    grid point (the GridRandomRecipe semantics)."""
    rng = random.Random(seed)
    grid_keys = [k for k, v in space.items() if isinstance(v, _Grid)]
    grid_values = [space[k].options for k in grid_keys]
    configs = []
    for combo in itertools.product(*grid_values) if grid_keys else [()]:
        for _ in range(num_samples):
            cfg = {}
            for k, v in space.items():
                if isinstance(v, _Grid):
                    continue
                cfg[k] = v.sample(rng) if isinstance(v, _Sampler) else v
            cfg.update(dict(zip(grid_keys, combo)))
            configs.append(cfg)
    # dedupe identical configs (all-grid spaces with num_samples>1)
    seen, out = set(), []
    for c in configs:
        key = tuple(sorted((k, repr(v)) for k, v in c.items()))
        if key not in seen:
            seen.add(key)
            out.append(c)
    return out


@dataclass
class Trial:
    config: Dict[str, Any]
    metric: Optional[float] = None
    results: Dict[str, Any] = field(default_factory=dict)
    budget: int = 0
    error: Optional[str] = None

    @property
    def ok(self) -> bool:
        return self.error is None and self.metric is not None


class SearchEngine:
    """`RayTuneSearchEngine`-shaped trial driver.

    train_fn(config, data, budget) -> dict with `metric` key (lower is
    better when mode="min"). `scheduler="asha"` runs successive halving:
    all configs get `grace_budget`, the top 1/eta advance with eta x budget,
    until `max_budget`.
    """

    def __init__(self, metric: str = "mse", mode: str = "min",
                 num_samples: int = 1, seed: int = 0,
                 scheduler: Optional[str] = None, eta: int = 3,
                 grace_budget: int = 1, max_budget: int = 9,
                 backend: str = "local"):
        if mode not in ("min", "max"):
            raise ValueError("mode must be min|max")
        if backend == "ray":
            # Ray Tune dispatch is not wired in this build; be explicit
            # rather than silently running local (trials execute serially
            # in-process either way on a single TPU host).
            log.warning("backend='ray' is not wired in this build; trials "
                        "run in-process on this host")
            backend = "local"
        self.metric, self.mode = metric, mode
        self.num_samples, self.seed = num_samples, seed
        self.scheduler, self.eta = scheduler, eta
        self.grace_budget, self.max_budget = grace_budget, max_budget
        self.backend = backend
        self.trials: List[Trial] = []
        self._train_fn: Optional[Callable] = None
        self._data = None
        self._configs: List[Dict] = []

    # -- compile/run surface (`ray_tune_search_engine.py:61,171`) ----------
    def compile(self, data, train_fn: Callable, recipe=None,
                search_space: Optional[Dict[str, Any]] = None
                ) -> "SearchEngine":
        if recipe is not None:
            search_space = dict(recipe.search_space())
            self.num_samples = getattr(recipe, "num_samples",
                                       self.num_samples)
        if not search_space:
            raise ValueError("Provide a recipe or search_space")
        self._train_fn = train_fn
        self._data = data
        self._configs = _expand(search_space, self.num_samples, self.seed)
        return self

    def run(self) -> List[Trial]:
        if self._train_fn is None:
            raise RuntimeError("compile() first")
        if self.scheduler == "asha":
            self.trials = self._run_asha()
        else:
            self.trials = [self._run_one(c, self.max_budget)
                           for c in self._configs]
        return self.trials

    def _run_one(self, config: Dict, budget: int) -> Trial:
        t = Trial(config=copy.deepcopy(config), budget=budget)
        try:
            results = self._train_fn(config, self._data, budget)
            t.results = results
            t.metric = float(results[self.metric])
        except Exception as e:  # noqa: BLE001 — a bad config must not kill
            log.warning("trial failed for %s: %s", config, e)
            t.error = f"{type(e).__name__}: {e}"
        return t

    def _run_asha(self) -> List[Trial]:
        alive = list(self._configs)
        budget = self.grace_budget
        done: List[Trial] = []
        while alive:
            rung = [self._run_one(c, budget) for c in alive]
            ok = sorted((t for t in rung if t.ok), key=self._key)
            done.extend(t for t in rung if not t.ok)
            if budget >= self.max_budget or len(ok) <= 1:
                done.extend(ok)
                break
            keep = max(1, len(ok) // self.eta)
            done.extend(ok[keep:])
            alive = [t.config for t in ok[:keep]]
            budget = min(budget * self.eta, self.max_budget)
        return done

    def _key(self, t: Trial):
        return t.metric if self.mode == "min" else -t.metric

    # -- results -----------------------------------------------------------
    def get_best_trials(self, k: int = 1) -> List[Trial]:
        ok = sorted((t for t in self.trials if t.ok), key=self._key)
        if not ok:
            raise RuntimeError("No successful trials")
        return ok[:k]

    def get_best_config(self) -> Dict[str, Any]:
        return self.get_best_trials(1)[0].config
