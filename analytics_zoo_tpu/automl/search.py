"""Search engine: sampling DSL + trial runner + successive halving + TPE.

Reference: `RayTuneSearchEngine` (`automl/search/ray_tune_search_engine.py:37`,
`compile` `:61`, `run` `:171`) with SearchAlg (skopt/BayesOpt wiring
`:244-282`) and schedulers (ASHA). Here: the same
`compile(data, model_builder, recipe)` / `run()` / `get_best_trials`
surface. Trials are pure functions
`train_fn(config, data, budget) -> {"metric": float, ...}` so the engine is
agnostic to what a trial trains (a jit'd TPU model, an sklearn fit, ...).

Execution backends (a TPU host has idle CPU cores during CPU-bound TS
trials):
  - "local": thread pool (default; jax/numpy release the GIL),
  - "process": spawn-based process pool (picklable train_fn/data only),
  - "ray": `ray.remote` when ray is importable, else falls back to local.
Search algorithms: "random" (sample the space up front) or "tpe" —
a Tree-structured Parzen Estimator (the reference's BO role): after
`tpe_startup` random trials, numeric dims are modelled with good/bad
Parzen (KDE) densities, categorical dims with smoothed good-set counts,
and candidates maximize the density ratio l(x)/g(x).
"""

from __future__ import annotations

import copy
import itertools
import logging
import math
import os
import random
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

log = logging.getLogger("analytics_zoo_tpu.automl")


# ---------------------------------------------------------------------------
# Sample functions (the tune.* DSL used in recipes)
# ---------------------------------------------------------------------------
class _Sampler:
    def sample(self, rng: random.Random):
        raise NotImplementedError


class _Choice(_Sampler):
    def __init__(self, options: Sequence):
        self.options = list(options)

    def sample(self, rng):
        return rng.choice(self.options)


class _Grid:
    """Expanded exhaustively (tune.grid_search)."""

    def __init__(self, options: Sequence):
        self.options = list(options)


class _Uniform(_Sampler):
    def __init__(self, lo, hi):
        self.lo, self.hi = lo, hi

    def sample(self, rng):
        return rng.uniform(self.lo, self.hi)


class _QUniform(_Sampler):
    def __init__(self, lo, hi, q):
        self.lo, self.hi, self.q = lo, hi, q

    def sample(self, rng):
        v = rng.uniform(self.lo, self.hi)
        return type(self.q)(round(v / self.q) * self.q)


class _LogUniform(_Sampler):
    def __init__(self, lo, hi):
        self.lo, self.hi = math.log(lo), math.log(hi)

    def sample(self, rng):
        return math.exp(rng.uniform(self.lo, self.hi))


class _RandInt(_Sampler):
    def __init__(self, lo, hi):
        self.lo, self.hi = lo, hi

    def sample(self, rng):
        return rng.randint(self.lo, self.hi - 1)  # tune.randint excl. upper


class hp:
    """Sample-function namespace (tune.*-compatible names)."""
    choice = _Choice
    grid_search = _Grid
    uniform = _Uniform
    quniform = _QUniform
    loguniform = _LogUniform
    randint = _RandInt


def _expand(space: Dict[str, Any], num_samples: int,
            seed: int = 0) -> List[Dict[str, Any]]:
    """Grid entries expand cartesian; samplers draw `num_samples` times per
    grid point (the GridRandomRecipe semantics)."""
    rng = random.Random(seed)
    grid_keys = [k for k, v in space.items() if isinstance(v, _Grid)]
    grid_values = [space[k].options for k in grid_keys]
    configs = []
    for combo in itertools.product(*grid_values) if grid_keys else [()]:
        for _ in range(num_samples):
            cfg = {}
            for k, v in space.items():
                if isinstance(v, _Grid):
                    continue
                cfg[k] = v.sample(rng) if isinstance(v, _Sampler) else v
            cfg.update(dict(zip(grid_keys, combo)))
            configs.append(cfg)
    # dedupe identical configs (all-grid spaces with num_samples>1)
    seen, out = set(), []
    for c in configs:
        key = tuple(sorted((k, repr(v)) for k, v in c.items()))
        if key not in seen:
            seen.add(key)
            out.append(c)
    return out


# ---------------------------------------------------------------------------
# TPE sampler (the BO search_alg; ref ray_tune_search_engine.py:244-282 role)
# ---------------------------------------------------------------------------
class _TPE:
    """Tree-structured Parzen Estimator over the recipe space.

    Observations are (config, metric) pairs; the best `gamma` fraction
    forms the "good" set. Numeric dims: 1-D gaussian KDE per set (in log
    space for loguniform); propose by sampling the good KDE and keeping
    the candidate with the best good/bad density ratio. Choice dims:
    categorical distribution from add-one-smoothed good-set counts."""

    def __init__(self, space: Dict[str, Any], mode: str, gamma: float = 0.25,
                 n_candidates: int = 24, seed: int = 0):
        self.space = space
        self.mode = mode
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.rng = random.Random(seed)

    # numeric encoding per dim kind --------------------------------------
    def _numeric(self, v) -> bool:
        return isinstance(v, (_Uniform, _QUniform, _LogUniform, _RandInt))

    def _encode(self, sampler, value) -> float:
        if isinstance(sampler, _LogUniform):
            return math.log(value)
        return float(value)

    def _decode(self, sampler, x: float):
        if isinstance(sampler, _LogUniform):
            x = math.exp(x)
            lo, hi = math.exp(sampler.lo), math.exp(sampler.hi)
            return min(max(x, lo), hi)
        if isinstance(sampler, _RandInt):
            return int(min(max(round(x), sampler.lo), sampler.hi - 1))
        if isinstance(sampler, _QUniform):
            q = sampler.q
            v = min(max(x, sampler.lo), sampler.hi)
            return type(q)(round(v / q) * q)
        return min(max(x, sampler.lo), sampler.hi)

    def _random_dim(self, v):
        if isinstance(v, _Grid):
            return self.rng.choice(v.options)
        return v.sample(self.rng) if isinstance(v, _Sampler) else v

    def _kde_sample(self, xs: List[float], bw: float) -> float:
        mu = self.rng.choice(xs)
        return self.rng.gauss(mu, bw)

    @staticmethod
    def _kde_logpdf(x: float, xs: List[float], bw: float) -> float:
        # max-component approximation is fine for ranking candidates
        return max(-((x - mu) ** 2) / (2 * bw * bw) for mu in xs)

    def suggest(self, observed: List["Trial"]) -> Dict[str, Any]:
        ok = [t for t in observed if t.ok]
        if len(ok) < 4:          # not enough evidence: random sample
            return {k: self._random_dim(v) for k, v in self.space.items()}
        key = (lambda t: t.metric) if self.mode == "min" \
            else (lambda t: -t.metric)
        ranked = sorted(ok, key=key)
        n_good = max(2, int(len(ranked) * self.gamma))
        good, bad = ranked[:n_good], ranked[n_good:] or ranked[-2:]

        cfg = {}
        for k, v in self.space.items():
            if not isinstance(v, (_Sampler, _Grid)):
                cfg[k] = v
                continue
            if isinstance(v, (_Choice, _Grid)):
                # grid dims participate as categoricals once the schedule
                # moves past the exhaustive startup expansion
                counts = {repr(o): 1.0 for o in v.options}  # +1 smoothing
                for t in good:
                    r = repr(t.config.get(k))
                    if r in counts:
                        counts[r] += 1.0
                total = sum(counts.values())
                pick = self.rng.random() * total
                acc = 0.0
                chosen = v.options[-1]
                for o in v.options:
                    acc += counts[repr(o)]
                    if pick <= acc:
                        chosen = o
                        break
                cfg[k] = chosen
                continue
            g = [self._encode(v, t.config[k]) for t in good
                 if k in t.config]
            b = [self._encode(v, t.config[k]) for t in bad
                 if k in t.config] or g
            spread = (max(g + b) - min(g + b)) or 1.0
            bw = max(spread / max(len(g), 2), 1e-12)
            best_x, best_score = None, -math.inf
            for _ in range(self.n_candidates):
                x = self._kde_sample(g, bw)
                score = (self._kde_logpdf(x, g, bw)
                         - self._kde_logpdf(x, b, bw))
                if score > best_score:
                    best_x, best_score = x, score
            cfg[k] = self._decode(v, best_x)
        return cfg


# ---------------------------------------------------------------------------
# GP/EI sampler (the reference's SkOpt SearchAlg role,
# ray_tune_search_engine.py:244-282 — Bayesian optimization proper)
# ---------------------------------------------------------------------------
class _GPBayes:
    """Gaussian-process Bayesian optimization with expected improvement.

    Configs encode to a unit-cube vector (numeric dims min-max scaled,
    loguniform in log space; choice/grid dims one-hot like skopt's
    categorical encoding). The surrogate is an RBF-kernel GP with a
    median-distance length scale and a small noise floor, fit by one
    Cholesky solve per suggestion (numpy only — no skopt dependency);
    suggestions maximize EI over random candidates. Same `suggest`
    surface as `_TPE` so the engine's model-based wave loop is shared."""

    def __init__(self, space: Dict[str, Any], mode: str,
                 n_candidates: int = 256, xi: float = 0.01, seed: int = 0):
        self.space = space
        self.mode = mode
        self.n_candidates = n_candidates
        self.xi = xi
        self.rng = random.Random(seed)

    # -- encoding ---------------------------------------------------------
    def _dims(self):
        for k, v in self.space.items():
            if isinstance(v, (_Choice, _Grid)):
                yield k, v, len(v.options)
            elif isinstance(v, _Sampler):
                yield k, v, 1

    def _encode_cfg(self, cfg: Dict[str, Any]) -> List[float]:
        vec: List[float] = []
        for k, v, width in self._dims():
            val = cfg.get(k)
            if isinstance(v, (_Choice, _Grid)):
                onehot = [0.0] * width
                reprs = [repr(o) for o in v.options]
                if repr(val) in reprs:
                    onehot[reprs.index(repr(val))] = 1.0
                vec.extend(onehot)
            else:
                lo, hi = v.lo, v.hi
                x = float(val)
                if isinstance(v, _LogUniform):
                    x = math.log(max(x, 1e-300))
                vec.append((x - lo) / ((hi - lo) or 1.0))
        return vec

    def _random_cfg(self) -> Dict[str, Any]:
        out = {}
        for k, v in self.space.items():
            if isinstance(v, _Grid):
                out[k] = self.rng.choice(v.options)
            elif isinstance(v, _Sampler):
                out[k] = v.sample(self.rng)
            else:
                out[k] = v
        return out

    def suggest(self, observed: List["Trial"]) -> Dict[str, Any]:
        import numpy as np
        ok = [t for t in observed if t.ok]
        if len(ok) < 4:
            return self._random_cfg()
        X = np.asarray([self._encode_cfg(t.config) for t in ok])
        y = np.asarray([t.metric for t in ok], float)
        if self.mode == "max":
            y = -y                                  # GP minimizes
        y_mu, y_sd = y.mean(), y.std() or 1.0
        yn = (y - y_mu) / y_sd

        # median-heuristic length scale over observed pairs
        d2 = ((X[:, None, :] - X[None, :, :]) ** 2).sum(-1)
        tri = d2[np.triu_indices(len(X), 1)]
        ls2 = float(np.median(tri[tri > 0])) if (tri > 0).any() else 1.0

        K = np.exp(-d2 / (2 * ls2)) + 1e-6 * np.eye(len(X))
        K += 1e-3 * np.eye(len(X))                  # observation noise
        L = np.linalg.cholesky(K)
        alpha = np.linalg.solve(L.T, np.linalg.solve(L, yn))

        cands = [self._random_cfg() for _ in range(self.n_candidates)]
        Xc = np.asarray([self._encode_cfg(c) for c in cands])
        d2c = ((Xc[:, None, :] - X[None, :, :]) ** 2).sum(-1)
        Ks = np.exp(-d2c / (2 * ls2))               # [n_cand, n_obs]
        mu = Ks @ alpha
        v = np.linalg.solve(L, Ks.T)
        var = np.clip(1.0 + 1e-3 - (v ** 2).sum(0), 1e-12, None)
        sd = np.sqrt(var)

        best = yn.min()
        imp = best - mu - self.xi
        z = imp / sd
        cdf = 0.5 * (1.0 + np.vectorize(math.erf)(z / math.sqrt(2)))
        pdf = np.exp(-0.5 * z * z) / math.sqrt(2 * math.pi)
        ei = imp * cdf + sd * pdf
        return cands[int(np.argmax(ei))]


# module-level so the spawn-based process pool can pickle it
def _run_trial_payload(payload):
    train_fn, data, config, budget, metric = payload
    try:
        results = train_fn(config, data, budget)
        return (results, float(results[metric]), None)
    except Exception as e:  # noqa: BLE001 — a bad config must not kill
        return ({}, None, f"{type(e).__name__}: {e}")


def _run_trial_ray(train_fn, data, config, budget, metric):
    """Ray task body: train_fn/data arrive as shared object-store refs."""
    return _run_trial_payload((train_fn, data, config, budget, metric))


@dataclass
class Trial:
    config: Dict[str, Any]
    metric: Optional[float] = None
    results: Dict[str, Any] = field(default_factory=dict)
    budget: int = 0
    error: Optional[str] = None

    @property
    def ok(self) -> bool:
        return self.error is None and self.metric is not None


class SearchEngine:
    """`RayTuneSearchEngine`-shaped trial driver.

    train_fn(config, data, budget) -> dict with `metric` key (lower is
    better when mode="min"). `scheduler="asha"` runs successive halving:
    all configs get `grace_budget`, the top 1/eta advance with eta x budget,
    until `max_budget`.
    """

    def __init__(self, metric: str = "mse", mode: str = "min",
                 num_samples: int = 1, seed: int = 0,
                 scheduler: Optional[str] = None, eta: int = 3,
                 grace_budget: int = 1, max_budget: int = 9,
                 backend: str = "local", n_workers: Optional[int] = None,
                 search_alg: Optional[str] = None):
        if mode not in ("min", "max"):
            raise ValueError("mode must be min|max")
        if search_alg not in (None, "random", "tpe", "bayes"):
            raise ValueError("search_alg must be random|tpe|bayes")
        if scheduler == "asha" and search_alg in ("tpe", "bayes"):
            raise ValueError(
                f"search_alg={search_alg!r} and scheduler='asha' are "
                "mutually exclusive in this engine: ASHA rungs re-evaluate "
                "a fixed population while model-based search grows one. "
                "Drop the scheduler to search at full budget.")
        if backend == "ray":
            try:
                import ray  # noqa: F401
            except ImportError:
                log.warning("backend='ray' requested but ray is not "
                            "importable; falling back to the local "
                            "thread-pool backend")
                backend = "local"
        elif backend not in ("local", "process", "serial"):
            raise ValueError("backend must be local|process|ray|serial")
        self.metric, self.mode = metric, mode
        self.num_samples, self.seed = num_samples, seed
        self.scheduler, self.eta = scheduler, eta
        self.grace_budget, self.max_budget = grace_budget, max_budget
        self.backend = backend
        self.n_workers = n_workers or min(os.cpu_count() or 1, 8)
        self.search_alg = search_alg or "random"
        self.trials: List[Trial] = []
        self._train_fn: Optional[Callable] = None
        self._data = None
        self._configs: List[Dict] = []
        self._space: Dict[str, Any] = {}
        self._ray_refs = None

    # -- compile/run surface (`ray_tune_search_engine.py:61,171`) ----------
    def compile(self, data, train_fn: Callable, recipe=None,
                search_space: Optional[Dict[str, Any]] = None
                ) -> "SearchEngine":
        if recipe is not None:
            search_space = dict(recipe.search_space())
            self.num_samples = getattr(recipe, "num_samples",
                                       self.num_samples)
        if not search_space:
            raise ValueError("Provide a recipe or search_space")
        self._train_fn = train_fn
        self._data = data
        self._space = dict(search_space)
        self._configs = _expand(search_space, self.num_samples, self.seed)
        self._ray_refs = None          # new fn/data → new object-store refs
        return self

    def run(self) -> List[Trial]:
        if self._train_fn is None:
            raise RuntimeError("compile() first")
        if self.scheduler == "asha":
            self.trials = self._run_asha()
        elif self.search_alg == "tpe":
            self.trials = self._run_model_based(
                _TPE(self._space, self.mode, seed=self.seed))
        elif self.search_alg == "bayes":
            self.trials = self._run_model_based(
                _GPBayes(self._space, self.mode, seed=self.seed))
        else:
            self.trials = self._map_trials(self._configs, self.max_budget)
        return self.trials

    # -- trial dispatch (serial / threads / processes / ray) ---------------
    def _map_trials(self, configs: List[Dict], budget: int) -> List[Trial]:
        payloads = [(self._train_fn, self._data, c, budget, self.metric)
                    for c in configs]
        if self.backend == "serial" or len(configs) <= 1:
            outs = [_run_trial_payload(p) for p in payloads]
        elif self.backend == "ray":
            import ray
            if not ray.is_initialized():
                ray.init(num_cpus=self.n_workers,
                         ignore_reinit_error=True)
            if self._ray_refs is None:
                # ship train_fn + data to the object store ONCE, not once
                # per trial per rung
                self._ray_refs = (ray.put(self._train_fn),
                                  ray.put(self._data),
                                  ray.remote(_run_trial_ray))
            fn_ref, data_ref, remote = self._ray_refs
            outs = ray.get([remote.remote(fn_ref, data_ref, c, budget,
                                          self.metric) for c in configs])
        elif self.backend == "process":
            # spawn (never fork: the parent holds a live XLA runtime)
            import concurrent.futures as cf
            import multiprocessing as mp
            import pickle
            try:
                pickle.dumps(payloads[0])
            except Exception as e:
                raise ValueError(
                    "backend='process' needs a picklable train_fn and "
                    "data (module-level function, no closures); use "
                    "backend='local' for closure train_fns") from e
            ctx = mp.get_context("spawn")
            with cf.ProcessPoolExecutor(self.n_workers,
                                        mp_context=ctx) as ex:
                outs = list(ex.map(_run_trial_payload, payloads))
        else:                                   # "local": thread pool
            import concurrent.futures as cf
            with cf.ThreadPoolExecutor(self.n_workers) as ex:
                outs = list(ex.map(_run_trial_payload, payloads))
        trials = []
        for c, (results, metric, err) in zip(configs, outs):
            t = Trial(config=copy.deepcopy(c), budget=budget,
                      results=results, metric=metric, error=err)
            if err:
                log.warning("trial failed for %s: %s", c, err)
            trials.append(t)
        return trials

    def _run_one(self, config: Dict, budget: int) -> Trial:
        return self._map_trials([config], budget)[0]

    def _run_model_based(self, sampler) -> List[Trial]:
        """Model-based sequential optimization (TPE or GP/EI) in
        n_workers-sized waves: total trials = len(expanded configs)
        (recipe num_samples)."""
        total = len(self._configs)
        done: List[Trial] = []
        # startup wave: first configs from the random expansion
        startup = min(max(4, self.n_workers), total)
        done.extend(self._map_trials(self._configs[:startup],
                                     self.max_budget))
        while len(done) < total:
            wave = min(self.n_workers, total - len(done))
            # constant-liar batching: pretend each in-wave suggestion
            # already scored at the incumbent best, so a deterministic
            # acquisition (GP-EI) doesn't hand the whole wave the same
            # config (TPE is stochastic but also benefits)
            ok = [t for t in done if t.ok]
            lie = None
            if ok:
                vals = [t.metric for t in ok]
                lie = min(vals) if self.mode == "min" else max(vals)
            configs = []
            fantasies = list(done)
            for _ in range(wave):
                cfg = sampler.suggest(fantasies)
                configs.append(cfg)
                if lie is not None:
                    fantasies.append(Trial(config=cfg, metric=lie))
            done.extend(self._map_trials(configs, self.max_budget))
        return done

    def _run_asha(self) -> List[Trial]:
        alive = list(self._configs)
        budget = self.grace_budget
        done: List[Trial] = []
        while alive:
            rung = self._map_trials(alive, budget)
            ok = sorted((t for t in rung if t.ok), key=self._key)
            done.extend(t for t in rung if not t.ok)
            if budget >= self.max_budget or len(ok) <= 1:
                done.extend(ok)
                break
            keep = max(1, len(ok) // self.eta)
            done.extend(ok[keep:])
            alive = [t.config for t in ok[:keep]]
            budget = min(budget * self.eta, self.max_budget)
        return done

    def _key(self, t: Trial):
        return t.metric if self.mode == "min" else -t.metric

    # -- results -----------------------------------------------------------
    def get_best_trials(self, k: int = 1) -> List[Trial]:
        ok = sorted((t for t in self.trials if t.ok), key=self._key)
        if not ok:
            raise RuntimeError("No successful trials")
        return ok[:k]

    def get_best_config(self) -> Dict[str, Any]:
        return self.get_best_trials(1)[0].config
