"""AutoXGBoost — hyperparameter search over gradient-boosted trees.

Reference: `pyzoo/zoo/orca/automl/` AutoXGBoost glue (XGBoost hyperparams
searched with the automl search engine). Uses the `xgboost` package when
present; otherwise falls back to sklearn's HistGradientBoosting (same
model family, keeps the API usable in environments without xgboost — the
reference likewise degrades when its optional deps are missing).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

from analytics_zoo_tpu.automl.search import SearchEngine, hp


def _make_model(task: str, config: Dict):
    common = dict(
        n_estimators=int(config.get("n_estimators", 100)),
        max_depth=int(config.get("max_depth", 6)),
        learning_rate=float(config.get("lr", 0.1)),
    )
    try:
        import xgboost as xgb
        cls = xgb.XGBRegressor if task == "regression" else xgb.XGBClassifier
        return cls(subsample=float(config.get("subsample", 1.0)),
                   min_child_weight=int(config.get("min_child_weight", 1)),
                   **common)
    except ImportError:
        from sklearn.ensemble import (HistGradientBoostingClassifier,
                                      HistGradientBoostingRegressor)
        cls = HistGradientBoostingRegressor if task == "regression" \
            else HistGradientBoostingClassifier
        return cls(max_iter=common["n_estimators"],
                   max_depth=common["max_depth"],
                   learning_rate=common["learning_rate"])


def _default_space() -> Dict:
    return {
        "n_estimators": hp.randint(50, 300),
        "max_depth": hp.choice([3, 4, 5, 6, 8]),
        "lr": hp.loguniform(1e-2, 3e-1),
        "subsample": hp.uniform(0.6, 1.0),
        "min_child_weight": hp.choice([1, 2, 3]),
    }


class _AutoXGB:
    task = "regression"

    def __init__(self, search_space: Optional[Dict] = None,
                 n_sampling: int = 4, seed: int = 0):
        self.search_space = search_space or _default_space()
        self.n_sampling = n_sampling
        self.seed = seed
        self.best_config: Optional[Dict] = None
        self.best_model = None

    def _score(self, model, x, y) -> float:
        pred = model.predict(x)
        if self.task == "regression":
            return -float(np.mean((pred - y) ** 2))       # higher better
        return float(np.mean(pred == y))

    def fit(self, x, y, validation_data=None) -> "_AutoXGB":
        x = np.asarray(x)
        y = np.asarray(y)
        if validation_data is None:
            n = int(len(x) * 0.8)
            xv, yv = x[n:], y[n:]
            x, y = x[:n], y[:n]
        else:
            xv, yv = (np.asarray(validation_data[0]),
                      np.asarray(validation_data[1]))
        models = {}

        def train_fn(config, data, budget):
            model = _make_model(self.task, config)
            model.fit(data[0], data[1])
            score = self._score(model, xv, yv)
            models[id(model)] = model
            return {"score": score, "_model_id": id(model)}

        # thread backend only: train_fn shares the `models` dict with this
        # process (xgboost/sklearn release the GIL during fit)
        engine = SearchEngine(metric="score", mode="max",
                              num_samples=self.n_sampling, seed=self.seed,
                              backend="local")
        engine.compile((x, y), train_fn,
                       search_space=self.search_space)
        engine.run()
        best = engine.get_best_trials(1)[0]
        self.best_config = best.config
        self.best_model = models[best.results["_model_id"]]
        return self

    def predict(self, x) -> np.ndarray:
        if self.best_model is None:
            raise RuntimeError("fit() first")
        return np.asarray(self.best_model.predict(np.asarray(x)))

    def evaluate(self, x, y, metrics: Sequence[str] = ("mse",)
                 ) -> Dict[str, float]:
        pred = self.predict(x)
        y = np.asarray(y)
        out = {}
        for m in metrics:
            if m == "mse":
                out[m] = float(np.mean((pred - y) ** 2))
            elif m == "mae":
                out[m] = float(np.mean(np.abs(pred - y)))
            elif m == "accuracy":
                out[m] = float(np.mean(pred == y))
            else:
                raise ValueError(f"Unsupported metric {m}")
        return out


class AutoXGBRegressor(_AutoXGB):
    """`AutoXGBRegressor` (orca.automl)."""
    task = "regression"


class AutoXGBClassifier(_AutoXGB):
    """`AutoXGBClassifier` (orca.automl)."""
    task = "classification"
