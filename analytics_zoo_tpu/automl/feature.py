"""Time-series feature engineering (`automl/feature/time_sequence.py:563`).

`TimeSequenceFeatureTransformer`: datetime-derived features (hour, day of
week, weekend, month...), standard scaling fitted on train only, and
sliding-window unroll into (x[B, past_seq_len, F], y[B, horizon]) — the
reference's fit_transform/transform/post_processing contract, including
inverse-scaling predictions back to the original target unit."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pandas as pd

# feature name -> extractor over a pandas DatetimeIndex/Series
_DT_FEATURES = {
    "HOUR": lambda dt: dt.dt.hour,
    "DAY": lambda dt: dt.dt.day,
    "MONTH": lambda dt: dt.dt.month,
    "DAYOFYEAR": lambda dt: dt.dt.dayofyear,
    "WEEKDAY": lambda dt: dt.dt.weekday,
    "WEEKOFYEAR": lambda dt: dt.dt.isocalendar().week.astype(np.int64),
    "MINUTE": lambda dt: dt.dt.minute,
    "IS_WEEKEND": lambda dt: (dt.dt.weekday >= 5).astype(np.int64),
    "IS_AWAKE": lambda dt: ((dt.dt.hour >= 6) & (dt.dt.hour <= 23))
    .astype(np.int64),
    "IS_BUSY_HOURS": lambda dt: dt.dt.hour.isin([7, 8, 9, 17, 18, 19])
    .astype(np.int64),
}

DEFAULT_FEATURES = ("HOUR", "IS_WEEKEND", "WEEKDAY", "MONTH")


class TimeSequenceFeatureTransformer:
    def __init__(self, dt_col: str = "datetime", target_col: str = "value",
                 extra_features_col: Optional[Sequence[str]] = None,
                 selected_features: Sequence[str] = DEFAULT_FEATURES,
                 past_seq_len: int = 2, future_seq_len: int = 1,
                 drop_missing: bool = True):
        self.dt_col, self.target_col = dt_col, target_col
        self.extra_features_col = list(extra_features_col or [])
        self.selected_features = list(selected_features)
        self.past_seq_len = past_seq_len
        self.future_seq_len = future_seq_len
        self.drop_missing = drop_missing
        self._mean: Optional[np.ndarray] = None
        self._std: Optional[np.ndarray] = None

    # -- internals ---------------------------------------------------------
    def _feature_frame(self, df: pd.DataFrame) -> np.ndarray:
        if self.dt_col not in df.columns:
            raise ValueError(f"Missing datetime column {self.dt_col!r}")
        if self.target_col not in df.columns:
            raise ValueError(f"Missing target column {self.target_col!r}")
        df = df.copy()
        if self.drop_missing:
            df = df.dropna(subset=[self.target_col])
        dt = pd.to_datetime(df[self.dt_col])
        cols = [df[self.target_col].astype(np.float32)]
        for name in self.selected_features:
            if name not in _DT_FEATURES:
                raise ValueError(f"Unknown datetime feature {name!r}; "
                                 f"choose from {sorted(_DT_FEATURES)}")
            cols.append(_DT_FEATURES[name](dt).astype(np.float32))
        for c in self.extra_features_col:
            cols.append(df[c].astype(np.float32))
        return np.stack([np.asarray(c) for c in cols], axis=1)  # [T, F]

    def _unroll(self, mat: np.ndarray, with_y: bool
                ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        L, H = self.past_seq_len, self.future_seq_len
        n = len(mat) - L - (H if with_y else 0) + 1
        if n <= 0:
            raise ValueError(
                f"Series of length {len(mat)} too short for past_seq_len="
                f"{L} + future_seq_len={H}")
        x = np.stack([mat[i:i + L] for i in range(n)])
        y = None
        if with_y:
            y = np.stack([mat[i + L:i + L + H, 0] for i in range(n)])
        return x.astype(np.float32), \
            (y.astype(np.float32) if y is not None else None)

    # -- surface (`time_sequence.py` fit_transform/transform) --------------
    def fit_transform(self, df: pd.DataFrame
                      ) -> Tuple[np.ndarray, np.ndarray]:
        mat = self._feature_frame(df)
        self._mean = mat.mean(axis=0)
        self._std = mat.std(axis=0) + 1e-8
        mat = (mat - self._mean) / self._std
        return self._unroll(mat, with_y=True)

    def transform(self, df: pd.DataFrame, is_train: bool = False):
        if self._mean is None:
            raise RuntimeError("fit_transform first")
        mat = (self._feature_frame(df) - self._mean) / self._std
        x, y = self._unroll(mat, with_y=is_train)
        return (x, y) if is_train else x

    def post_processing(self, y_scaled: np.ndarray) -> np.ndarray:
        """Inverse-scale predictions back to target units."""
        if self._mean is None:
            raise RuntimeError("fit_transform first")
        return y_scaled * self._std[0] + self._mean[0]

    # -- persistence -------------------------------------------------------
    def state(self) -> Dict:
        return {
            "dt_col": self.dt_col, "target_col": self.target_col,
            "extra_features_col": self.extra_features_col,
            "selected_features": self.selected_features,
            "past_seq_len": self.past_seq_len,
            "future_seq_len": self.future_seq_len,
            "mean": None if self._mean is None else self._mean.tolist(),
            "std": None if self._std is None else self._std.tolist(),
        }

    @classmethod
    def from_state(cls, state: Dict) -> "TimeSequenceFeatureTransformer":
        t = cls(dt_col=state["dt_col"], target_col=state["target_col"],
                extra_features_col=state["extra_features_col"],
                selected_features=state["selected_features"],
                past_seq_len=state["past_seq_len"],
                future_seq_len=state["future_seq_len"])
        if state["mean"] is not None:
            t._mean = np.asarray(state["mean"], np.float32)
            t._std = np.asarray(state["std"], np.float32)
        return t

    @property
    def feature_dim(self) -> int:
        return 1 + len(self.selected_features) + len(self.extra_features_col)
