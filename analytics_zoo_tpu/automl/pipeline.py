"""TimeSequencePipeline + TimeSequencePredictor
(`automl/pipeline/time_sequence.py:233`, `automl/regression/
time_sequence_predictor.py:99`).

Predictor.fit searches a recipe's space with the local SearchEngine (each
trial = transformer + model trained for the rung's epoch budget, scored on
held-out data), then refits the best config into a `TimeSequencePipeline`
that carries transformer state + model weights through save/load."""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np
import pandas as pd

from analytics_zoo_tpu.automl.feature import TimeSequenceFeatureTransformer
from analytics_zoo_tpu.automl.models import (build_model, mtnet_past_seq_len)
from analytics_zoo_tpu.automl.recipe import LSTMGridRandomRecipe, Recipe
from analytics_zoo_tpu.automl.search import SearchEngine


def _past_seq_len(config: Dict) -> int:
    if config.get("model") == "MTNet":
        return mtnet_past_seq_len(config)
    return int(config.get("past_seq_len", 2))


def _metric_value(name: str, y_true: np.ndarray, y_pred: np.ndarray) -> float:
    y_true = np.asarray(y_true).reshape(len(y_true), -1)
    y_pred = np.asarray(y_pred).reshape(len(y_pred), -1)
    err = y_true - y_pred
    if name == "mse":
        return float(np.mean(err ** 2))
    if name == "rmse":
        return float(np.sqrt(np.mean(err ** 2)))
    if name == "mae":
        return float(np.mean(np.abs(err)))
    if name == "smape":
        denom = (np.abs(y_true) + np.abs(y_pred)) / 2 + 1e-8
        return float(np.mean(np.abs(err) / denom) * 100)
    if name == "r2":
        ss_res = np.sum(err ** 2)
        ss_tot = np.sum((y_true - y_true.mean()) ** 2) + 1e-12
        return float(1 - ss_res / ss_tot)
    raise ValueError(f"Unknown metric {name!r}")


class TimeSequencePipeline:
    def __init__(self, transformer: TimeSequenceFeatureTransformer,
                 model, config: Dict):
        self.transformer = transformer
        self.model = model
        self.config = dict(config)

    # -- inference/eval (`time_sequence.py` predict/evaluate) -------------
    def predict(self, df: pd.DataFrame) -> np.ndarray:
        x = self.transformer.transform(df, is_train=False)
        y_scaled = self.model.predict(x, batch_per_thread=64)
        return self.transformer.post_processing(np.asarray(y_scaled))

    def evaluate(self, df: pd.DataFrame,
                 metrics: Sequence[str] = ("mse",)) -> Dict[str, float]:
        x, y = self.transformer.transform(df, is_train=True)
        y_pred = np.asarray(self.model.predict(x, batch_per_thread=64))
        y_true = self.transformer.post_processing(y)
        y_pred = self.transformer.post_processing(y_pred)
        return {m: _metric_value(m, y_true, y_pred) for m in metrics}

    def fit(self, df: pd.DataFrame, epochs: int = 1, batch_size: int = 32):
        """Incremental fit on new data (transformer stays frozen)."""
        x, y = self.transformer.transform(df, is_train=True)
        return self.model.fit(x, y, batch_size=min(batch_size, len(x)),
                              nb_epoch=epochs)

    # -- persistence -------------------------------------------------------
    def save(self, path: str) -> str:
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "pipeline.json"), "w") as fh:
            json.dump({"config": self.config,
                       "transformer": self.transformer.state()}, fh)
        self.model.save_weights(os.path.join(path, "weights"))
        return path

    @classmethod
    def load(cls, path: str) -> "TimeSequencePipeline":
        with open(os.path.join(path, "pipeline.json")) as fh:
            blob = json.load(fh)
        transformer = TimeSequenceFeatureTransformer.from_state(
            blob["transformer"])
        config = blob["config"]
        input_shape = (_past_seq_len(config), transformer.feature_dim)
        model = build_model(config, input_shape,
                            output_dim=transformer.future_seq_len)
        model.ensure_built(np.zeros((1,) + input_shape, np.float32))
        model.load_weights(os.path.join(path, "weights"))
        return cls(transformer, model, config)


class TimeSequencePredictor:
    """`TimeSequencePredictor.fit` -> best pipeline."""

    def __init__(self, dt_col: str = "datetime", target_col: str = "value",
                 future_seq_len: int = 1,
                 extra_features_col: Optional[Sequence[str]] = None,
                 drop_missing: bool = True, seed: int = 0):
        self.dt_col, self.target_col = dt_col, target_col
        self.future_seq_len = future_seq_len
        self.extra_features_col = extra_features_col
        self.drop_missing = drop_missing
        self.seed = seed
        self.search_engine: Optional[SearchEngine] = None

    def _make_transformer(self, config: Dict
                          ) -> TimeSequenceFeatureTransformer:
        return TimeSequenceFeatureTransformer(
            dt_col=self.dt_col, target_col=self.target_col,
            extra_features_col=self.extra_features_col,
            past_seq_len=_past_seq_len(config),
            future_seq_len=self.future_seq_len,
            drop_missing=self.drop_missing)

    def _train_once(self, config: Dict, train_df, val_df, epochs: int):
        transformer = self._make_transformer(config)
        x, y = transformer.fit_transform(train_df)
        model = build_model(config, (x.shape[1], x.shape[2]),
                            output_dim=self.future_seq_len)
        batch = min(int(config.get("batch_size", 32)), len(x))
        model.fit(x, y, batch_size=batch, nb_epoch=epochs)
        vx, vy = transformer.transform(val_df, is_train=True)
        y_pred = np.asarray(model.predict(vx, batch_per_thread=64))
        return transformer, model, vy, y_pred

    def fit(self, input_df: pd.DataFrame,
            validation_df: Optional[pd.DataFrame] = None,
            recipe: Optional[Recipe] = None, metric: str = "mse",
            search_alg: Optional[str] = None,
            n_workers: Optional[int] = None, backend: str = "local",
            ) -> TimeSequencePipeline:
        recipe = recipe or LSTMGridRandomRecipe(num_rand_samples=1)
        if validation_df is None:
            split = int(len(input_df) * 0.8)
            input_df, validation_df = input_df.iloc[:split], \
                input_df.iloc[split:]

        def train_fn(config, data, budget):
            train_df, val_df = data
            _, _, vy, y_pred = self._train_once(config, train_df, val_df,
                                                epochs=budget)
            return {metric: _metric_value(metric, vy, y_pred)}

        mode = "max" if metric == "r2" else "min"
        # TPE replaces the ASHA schedule (mutually exclusive in the
        # engine): Bayesian suggestions all run at full budget
        scheduler = None if search_alg == "tpe" else "asha"
        engine = SearchEngine(metric=metric, mode=mode, seed=self.seed,
                              scheduler=scheduler, grace_budget=1,
                              max_budget=recipe.training_iteration,
                              search_alg=search_alg, n_workers=n_workers,
                              backend=backend)
        engine.compile((input_df, validation_df), train_fn, recipe=recipe)
        engine.run()
        self.search_engine = engine
        best = engine.get_best_config()
        transformer, model, _, _ = self._train_once(
            best, input_df, validation_df,
            epochs=recipe.training_iteration)
        return TimeSequencePipeline(transformer, model, best)
