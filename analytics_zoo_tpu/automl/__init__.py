"""AutoML: hyperparameter search + time-series pipelines (SURVEY §2.10).

The reference drives Ray Tune trials (`automl/search/ray_tune_search_engine.py:37`)
over recipe-defined spaces (`automl/config/recipe.py`). This environment has
no Ray, and a TPU host runs one trial at a time anyway — so the engine here
executes trials in-process with the same surface: sample functions, recipes,
ASHA-style successive halving. `backend="ray"` logs a warning and runs
locally (Ray Tune dispatch is not wired in this build).
"""

from analytics_zoo_tpu.automl.search import (  # noqa: F401
    SearchEngine, hp)
from analytics_zoo_tpu.automl.recipe import (  # noqa: F401
    Recipe, LSTMGridRandomRecipe, LSTMRandomRecipe, Seq2SeqRandomRecipe,
    TCNGridRandomRecipe, MTNetGridRandomRecipe, BayesRecipe)
from analytics_zoo_tpu.automl.feature import (  # noqa: F401
    TimeSequenceFeatureTransformer)
from analytics_zoo_tpu.automl.pipeline import (  # noqa: F401
    TimeSequencePipeline, TimeSequencePredictor)
