"""Search-space recipes (`automl/config/recipe.py:643`'s presets).

Each recipe returns a search space over BOTH feature params (past_seq_len,
selected datetime features) and model params (units, dropout, lr, batch) —
the reference's coupled feature+model search. Names/defaults follow the
reference recipes; samplers use the local `hp` DSL.
"""

from __future__ import annotations

from typing import Any, Dict

from analytics_zoo_tpu.automl.search import hp


class Recipe:
    num_samples = 1
    training_iteration = 10   # max epochs budget for the scheduler

    def search_space(self) -> Dict[str, Any]:
        raise NotImplementedError


class LSTMGridRandomRecipe(Recipe):
    """`recipe.py` LSTMGridRandomRecipe: grid over units, random over
    lr/dropout/past_seq_len."""

    def __init__(self, num_rand_samples: int = 1, epochs: int = 5,
                 look_back: int = 2, batch_size: int = 32):
        self.num_samples = num_rand_samples
        self.training_iteration = epochs
        self.look_back = look_back
        self.batch_size = batch_size

    def search_space(self):
        return {
            "model": "VanillaLSTM",
            "lstm_1_units": hp.grid_search([16, 32]),
            "lstm_2_units": hp.grid_search([16, 32]),
            "dropout_1": hp.uniform(0.2, 0.5),
            "dropout_2": hp.uniform(0.2, 0.5),
            "lr": hp.loguniform(1e-3, 1e-2),
            "batch_size": self.batch_size,
            "past_seq_len": self.look_back,
            "epochs": self.training_iteration,
        }


class LSTMRandomRecipe(LSTMGridRandomRecipe):
    """All-random variant."""

    def search_space(self):
        space = super().search_space()
        space["lstm_1_units"] = hp.choice([8, 16, 32, 64])
        space["lstm_2_units"] = hp.choice([8, 16, 32, 64])
        return space


class Seq2SeqRandomRecipe(Recipe):
    def __init__(self, num_rand_samples: int = 1, epochs: int = 5,
                 look_back: int = 4):
        self.num_samples = num_rand_samples
        self.training_iteration = epochs
        self.look_back = look_back

    def search_space(self):
        return {
            "model": "Seq2Seq",
            "latent_dim": hp.choice([16, 32, 64]),
            "dropout": hp.uniform(0.2, 0.5),
            "lr": hp.loguniform(1e-3, 1e-2),
            "batch_size": hp.choice([32, 64]),
            "past_seq_len": self.look_back,
            "epochs": self.training_iteration,
        }


class TCNGridRandomRecipe(Recipe):
    def __init__(self, num_rand_samples: int = 1, epochs: int = 5,
                 look_back: int = 8):
        self.num_samples = num_rand_samples
        self.training_iteration = epochs
        self.look_back = look_back

    def search_space(self):
        return {
            "model": "TCN",
            "hidden_units": hp.grid_search([16, 32]),
            "levels": hp.choice([2, 3]),
            "kernel_size": hp.choice([2, 3]),
            "dropout": hp.uniform(0.0, 0.3),
            "lr": hp.loguniform(1e-3, 1e-2),
            "batch_size": 32,
            "past_seq_len": self.look_back,
            "epochs": self.training_iteration,
        }


class MTNetGridRandomRecipe(Recipe):
    """`recipe.py` MTNetGridRandomRecipe (long_num x time_step windows)."""

    def __init__(self, num_rand_samples: int = 1, epochs: int = 5,
                 time_step=(3, 4), long_num=(3, 4)):
        self.num_samples = num_rand_samples
        self.training_iteration = epochs
        self.time_step = list(time_step)
        self.long_num = list(long_num)

    def search_space(self):
        return {
            "model": "MTNet",
            "time_step": hp.grid_search(self.time_step),
            "long_num": hp.grid_search(self.long_num),
            "cnn_hid_size": hp.choice([16, 32]),
            "dropout": hp.uniform(0.1, 0.3),
            "lr": hp.loguniform(1e-3, 1e-2),
            "batch_size": 32,
            "epochs": self.training_iteration,
        }


class BayesRecipe(Recipe):
    """The reference's BayesRecipe drives skopt BO; without skopt this is a
    dense random recipe over the same continuous space (`recipe.py`
    BayesRecipe ranges)."""

    def __init__(self, num_samples: int = 8, epochs: int = 5,
                 look_back: int = 2):
        self.num_samples = num_samples
        self.training_iteration = epochs
        self.look_back = look_back

    def search_space(self):
        return {
            "model": "VanillaLSTM",
            "lstm_1_units": hp.randint(8, 65),
            "lstm_2_units": hp.randint(8, 65),
            "dropout_1": hp.uniform(0.2, 0.5),
            "dropout_2": hp.uniform(0.2, 0.5),
            "lr": hp.loguniform(1e-4, 1e-1),
            "batch_size": hp.choice([32, 64]),
            "past_seq_len": self.look_back,
            "epochs": self.training_iteration,
        }
