"""ReplicaSupervisor — quarantine/revival over the replica pool
(ISSUE 5 tentpole, part 1).

The reference survives a bad executor because Flink reschedules the
task slot; here one wedged/poisoned chip would keep receiving routed
batches forever, each degrading to "NaN". The supervisor sits above the
router and turns a bad replica into lost CAPACITY instead of lost
correctness:

- every routed batch reports its outcome + dispatch latency through
  `InferenceModel._on_replica_event` (installed by this class);
- `failure_threshold` CONSECUTIVE failures on one replica quarantine
  it (the router stops considering it, queued work re-dispatches to
  healthy replicas, in-flight permits transfer);
- a healthy replica whose dispatch latency is a sustained outlier —
  more than `latency_factor` × the pool's rolling median, above an
  absolute floor, `failure_threshold` times in a row — is quarantined
  too (a chip can be sick without raising);
- a probe thread re-tries each quarantined replica every
  `probe_interval_s` with a **canary batch** (the most recent batch
  any replica dispatched); a probe success revives the replica.

All-quarantined is a legal state: the router fails fast
(`NoHealthyReplicaError`), the dispatch stage parks batches until a
revival, and the HTTP frontend answers 503 + Retry-After instead of
hanging (see `http_frontend.py`).

Registry families: `serving_replica_quarantined_total{replica,reason}`,
`serving_replica_revivals_total{replica}`, `serving_replica_healthy`
(live gauge).
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Deque, Dict, Optional

log = logging.getLogger("analytics_zoo_tpu.serving")


class ReplicaSupervisor:
    def __init__(self, model, failure_threshold: int = 3,
                 latency_factor: float = 8.0,
                 latency_floor_ms: float = 50.0,
                 probe_interval_s: float = 0.5,
                 probe_timeout_s: float = 10.0,
                 registry=None):
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}")
        self.model = model
        self.failure_threshold = failure_threshold
        self.latency_factor = latency_factor
        self.latency_floor_ms = latency_floor_ms
        self.probe_interval_s = probe_interval_s
        self.probe_timeout_s = probe_timeout_s
        self._consec: Dict[int, int] = collections.defaultdict(int)
        self._slow: Dict[int, int] = collections.defaultdict(int)
        self._suspended = False
        # rolling pool-wide latency window: the outlier baseline. One
        # shared deque (not per-replica): a sick replica must stand out
        # against the POOL, not against its own degraded history.
        self._lat_window: Deque[float] = collections.deque(maxlen=128)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if registry is None:
            from analytics_zoo_tpu.observability.registry import get_registry
            registry = get_registry()
        self.quarantined_total = registry.counter(
            "serving_replica_quarantined_total",
            "replicas quarantined by the supervisor, by replica and "
            "reason (failures, latency)")
        self.revivals_total = registry.counter(
            "serving_replica_revivals_total",
            "quarantined replicas revived by a successful canary probe")
        self._healthy_gauge = registry.gauge(
            "serving_replica_healthy",
            "replicas currently accepting routed work (live)")
        self._healthy_fn = model.healthy_replicas
        self._healthy_gauge.set_function(self._healthy_fn)
        model._on_replica_event = self._record

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ReplicaSupervisor":
        self._thread = threading.Thread(target=self._probe_loop,
                                        name="replica-supervisor",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            # the probe loop never blocks on a replica (async probes),
            # so it exits within one probe interval
            self._thread.join(timeout=5.0)
            self._thread = None
        if self.model._on_replica_event is self._record:
            self.model._on_replica_event = None
        # compare-and-release, same discipline as the engine's gauges:
        # a stopped supervisor must not pin the model in the registry
        self._healthy_gauge.release_function(self._healthy_fn, freeze=True)

    # -- rollout hand-off (ISSUE 14) ---------------------------------------
    def suspend(self):
        """Stop judging outcomes while a model swap is in flight: the
        first post-swap batches of a restructured version pay honest
        re-warmup latency, and counting those as outliers (or a torn
        mid-swap dispatch as a failure streak) would quarantine healthy
        replicas exactly when the rollout needs them. The canary probe
        loop keeps running — revival is still wanted mid-swap."""
        with self._lock:
            self._suspended = True

    def resume(self):
        """Re-arm supervision after a swap, with a CLEAN slate: strikes
        reset and the latency window drops — the new version's latency
        family must build its own baseline, not be judged against the
        old model's."""
        with self._lock:
            self._suspended = False
            self._consec.clear()
            self._slow.clear()
            self._lat_window.clear()

    # -- outcome stream (called from replica worker threads) ---------------
    def _record(self, replica: int, ok: bool, latency_s: float):
        quarantine_as = None
        with self._lock:
            if self._suspended:
                return
            if not ok:
                self._consec[replica] += 1
                if self._consec[replica] >= self.failure_threshold:
                    quarantine_as = "failures"
            else:
                self._consec[replica] = 0
                lat_ms = latency_s * 1e3
                baseline = self._median_ms()
                if baseline is not None and \
                        lat_ms > self.latency_floor_ms and \
                        lat_ms > self.latency_factor * baseline:
                    self._slow[replica] += 1
                    if self._slow[replica] >= self.failure_threshold:
                        quarantine_as = "latency"
                else:
                    self._slow[replica] = 0
                    # only in-family latencies feed the baseline, or a
                    # sustained outage would drag the median up until
                    # the outlier test can never trip again
                    self._lat_window.append(lat_ms)
        if quarantine_as is not None:
            self.quarantine(replica, reason=quarantine_as)

    def _median_ms(self) -> Optional[float]:
        # caller holds the lock; a thin window has no credible baseline
        if len(self._lat_window) < 16:
            return None
        ordered = sorted(self._lat_window)
        return ordered[len(ordered) // 2]

    # -- actions -----------------------------------------------------------
    def quarantine(self, replica: int, reason: str = "manual") -> bool:
        """Pull one replica out of the routing set (idempotent). Returns
        True when this call performed the transition."""
        if not self.model.quarantine_replica(replica):
            return False
        with self._lock:
            self._consec[replica] = 0
            self._slow[replica] = 0
        log.warning("replica %d quarantined (%s); %d healthy remain",
                    replica, reason, self.model.healthy_replicas())
        self.quarantined_total.inc(replica=str(replica), reason=reason)
        return True

    def revive(self, replica: int) -> bool:
        if not self.model.revive_replica(replica):
            return False
        log.info("replica %d revived by canary probe", replica)
        self.revivals_total.inc(replica=str(replica))
        return True

    # -- canary probe loop -------------------------------------------------
    def _probe_loop(self):
        """Async probes, at most ONE outstanding per replica: the loop
        never blocks on a wedged replica (a hung probe would otherwise
        delay every OTHER replica's revival by probe_timeout_s per
        cycle), and a replica that stays wedged accumulates exactly one
        canary job on its queue, not one per cycle."""
        probes: Dict[int, tuple] = {}      # index -> (pending, started)
        while not self._stop.wait(self.probe_interval_s):
            try:
                quarantined = set(self.model.quarantined_replicas())
                for index in list(probes):
                    if index not in quarantined:
                        probes.pop(index)  # revived/retired elsewhere
                for index in quarantined:
                    if self._stop.is_set():
                        return
                    entry = probes.get(index)
                    if entry is not None:
                        pending, _started = entry
                        if not pending._event.is_set():
                            # still in the wedged worker's queue: wait —
                            # re-enqueueing would pile canaries forever.
                            # (If the worker ever drains it, the event
                            # sets and the next cycle reads the verdict.)
                            continue
                        probes.pop(index)
                        try:
                            pending.result()
                        except Exception:  # noqa: BLE001 — the verdict
                            continue       # still sick; re-probe next cycle
                        self.revive(index)
                        continue
                    pending = self.model.probe_replica_async(index)
                    if pending is not None:
                        probes[index] = (pending, time.monotonic())
            except Exception as e:  # noqa: BLE001 — probe loop must
                # survive anything (a raising replica is exactly what
                # it exists to poke at)
                log.debug("canary probe cycle failed: %s", e)

    # -- views -------------------------------------------------------------
    def stats(self) -> Dict[str, object]:
        with self._lock:
            return {
                "healthy": self.model.healthy_replicas(),
                "quarantined": self.model.quarantined_replicas(),
                "consecutive_failures": dict(self._consec),
                "latency_strikes": dict(self._slow),
                "suspended": self._suspended,
            }
