"""Cluster Serving CLI — `cluster-serving-start/stop/cli` analogue
(`scripts/cluster-serving/`).

    python -m analytics_zoo_tpu.serving.cli start --config config.yaml
    python -m analytics_zoo_tpu.serving.cli broker --port 6380
    python -m analytics_zoo_tpu.serving.cli metrics --url http://host:http_port

`start` runs the serving loop (and HTTP frontend when http_port is set) in
the foreground; `broker` runs a standalone TCP broker so clients on other
hosts/processes can enqueue (the image has no Redis server)."""

from __future__ import annotations

import argparse
import json
import logging
import signal
import sys
import time


def cmd_start(args) -> int:
    from analytics_zoo_tpu.serving.config import ServingConfig
    from analytics_zoo_tpu.serving.http_frontend import FrontEnd
    from analytics_zoo_tpu.serving.server import ClusterServing
    from analytics_zoo_tpu.serving.broker import connect_broker
    replicas = getattr(args, "num_replicas", None)
    if replicas is not None:
        try:
            replicas = int(replicas)
        except ValueError:
            pass                    # 'auto' (load() validates spellings)
    # overrides go INTO load(): validation must see the effective values,
    # or a config authored for a bigger host could never be rescued here
    cfg = ServingConfig.load(args.config, num_replicas=replicas,
                             placement=getattr(args, "placement", None),
                             compile_cache_dir=getattr(
                                 args, "compile_cache_dir", None),
                             mesh=getattr(args, "mesh", None))
    if getattr(args, "engine_id", None):
        # fleet override (ISSUE 10): each process in a scale-out gets
        # its own identity at launch ("auto" generates one)
        cfg.engine_id = args.engine_id
        cfg._validate_fleet()
    if getattr(args, "partitions", None) is not None:
        cfg.partitions = args.partitions
    if getattr(args, "reshard", False):
        cfg.reshard = True
    cfg._validate_partitions()
    engine_id = cfg.resolve_engine_id()
    if cfg.partitions > 1 and engine_id is None:
        # same discipline as rollout below: a partitioned engine with
        # no fleet identity cannot lease partitions — fail before the
        # consumer group sees this process
        raise SystemExit(
            "params.partitions > 1 needs a fleet identity: pass "
            "--engine-id (or set params.engine_id) — the partition "
            "lease table keys ownership on it")
    if cfg.rollout_model_dir and engine_id is None:
        # fail BEFORE the engine joins the consumer group: dying on a
        # config error after reading records would strand them in the
        # PEL until a peer's claim sweep
        raise SystemExit(
            "params.rollout.model_dir needs a fleet identity: "
            "pass --engine-id (or set params.engine_id)")
    if cfg.model_encrypted and cfg.http_port is None:
        raise SystemExit(
            "secure.model_encrypted needs http_port: the secret/salt "
            "arrive via the frontend's POST /model-secure")
    broker = connect_broker(cfg.broker_url)
    frontend = None
    if cfg.http_port is not None:
        # frontend first: with model_encrypted, build_model blocks until
        # someone POSTs the secret/salt to /model-secure
        frontend = FrontEnd(
            broker, None, port=cfg.http_port,
            tokens_per_second=cfg.tokens_per_second,
            token_acquire_timeout_ms=cfg.token_acquire_timeout_ms,
            tls_certfile=cfg.tls_certfile,
            tls_keyfile=cfg.tls_keyfile,
            profile_dir=cfg.profile_dir,
            profile_max_artifacts=cfg.profile_max_artifacts,
            profile_enabled=cfg.profile_enabled,
            # fleet mode: the frontend doubles as the fleet gateway
            # (engine heartbeats -> /healthz + serving_engines_* gauges)
            fleet_stream=cfg.stream if engine_id else None,
            engine_ttl_s=cfg.engine_ttl_s,
            # tiered admission (ISSUE 11): cheap early 429s per tier
            admission=cfg.build_admission(broker),
            admission_header=cfg.admission_header,
            # partitioned request plane (ISSUE 16): /predict enqueues
            # hash-route across the same partition streams the engines
            # lease
            partitions=cfg.partitions,
            # fleet trace plane (ISSUE 17): /trace/<request_id> serves
            # merged cross-process timelines; trace_sample>0 also stamps
            # trace context on enqueued records
            trace_sample=cfg.trace_sample,
            trace_buffer_spans=cfg.trace_buffer_spans,
            trace_export_interval_s=cfg.trace_export_interval_s,
            # streaming continuity (ISSUE 20): keepalive comments hold
            # proxies open; a stalled stream with flatlined engine
            # heartbeats closes with an explicit error event
            stream_keepalive_s=cfg.decode_keepalive_s,
            stream_stall_timeout_s=(cfg.engine_ttl_s * 2
                                    if cfg.generative else None)).start()
        scheme = "https" if frontend.tls else "http"
        print(f"{scheme} frontend on :{frontend.port}", flush=True)
    if cfg.generative:
        # continuous-batching decode engine (ISSUE 18): replaces the
        # request-batched dispatch path entirely — the frontend (if any)
        # keeps serving /predict, now with ?stream=1 SSE token relay
        return _start_generative(cfg, broker, frontend)
    model = cfg.build_model(broker=broker)
    mesh_note = ""
    if model.placement == "sharded" and model.mesh is not None:
        axes = ",".join(f"{a}={s}"
                        for a, s in model.mesh.axis_sizes.items()
                        if s != 1)
        mesh_note = f" mesh=[{axes or 'single-device'}]"
    print(f"placement={model.placement} replicas={model.num_replicas} "
          f"devices={len(model.devices)}"
          f"{mesh_note} dtype={model.serving_dtype}", flush=True)
    if cfg.warmup_shapes:
        # pre-compile every REACHABLE shape bucket BEFORE the stream
        # opens: no XLA compile ever lands on a request. The reader never
        # hands dispatch more than batch_size records, so buckets past
        # the one covering batch_size would pay compile time (and cached
        # executable memory) for batches that cannot occur
        import numpy as np

        from analytics_zoo_tpu.serving.inference_model import _next_bucket
        dtype = np.dtype(cfg.warmup_dtype)
        cap = _next_bucket(cfg.batch_size, model.buckets)
        buckets = [b for b in model.buckets if b <= cap]
        for shape in cfg.warmup_shapes:
            model.warmup(np.zeros(tuple(shape), dtype), buckets=buckets)
        print(f"warmed {len(model.warmed_buckets)} shape buckets: "
              f"{json.dumps(model.warmup_report)}", flush=True)
        if model.compile_cache is not None:
            # what this restart actually paid: per-(replica, bucket)
            # cache hits vs fresh compiles, plus the dir's state
            src = model.warmup_source
            s = model.compile_cache.stats()
            print("compile cache: "
                  f"{sum(1 for v in src.values() if v == 'cached')} "
                  "warmed from disk, "
                  f"{sum(1 for v in src.values() if v == 'compiled')} "
                  f"compiled fresh ({s['entries']} entries, "
                  f"{s['bytes']} bytes in {s['path']})", flush=True)
    tracer = None
    if cfg.trace or cfg.trace_path or cfg.trace_sample > 0:
        from analytics_zoo_tpu.observability import Tracer, get_registry
        tracer = Tracer(max_spans=cfg.trace_buffer_spans,
                        registry=get_registry())
    serving = ClusterServing(model, broker, stream=cfg.stream,
                             batch_size=cfg.batch_size,
                             batch_timeout_ms=cfg.batch_timeout_ms,
                             pipelined=cfg.pipelined,
                             decode_workers=cfg.decode_workers,
                             queue_depth=cfg.queue_depth,
                             tracer=tracer,
                             supervise=cfg.supervise,
                             failure_threshold=cfg.failure_threshold,
                             probe_interval_s=cfg.probe_interval_s,
                             latency_factor=cfg.latency_factor,
                             breaker_failure_threshold=cfg
                             .breaker_failure_threshold,
                             breaker_reset_s=cfg.breaker_reset_s,
                             sink_buffer_batches=cfg
                             .sink_buffer_batches,
                             slo=cfg.build_slo(),
                             engine_id=engine_id,
                             claim_min_idle_s=cfg.claim_min_idle_s,
                             claim_interval_s=cfg.claim_interval_s,
                             heartbeat_interval_s=cfg
                             .heartbeat_interval_s,
                             batch_policy=cfg.batch_policy,
                             deadline_ms=cfg.deadline_ms,
                             batch_margin_ms=cfg.batch_margin_ms,
                             admission_tiers=cfg.admission_tiers,
                             admission_field=cfg.admission_field,
                             shed_backlog=cfg.shed_backlog,
                             partitions=cfg.partitions,
                             reshard=cfg.reshard,
                             partition_lease_ttl_s=cfg
                             .partition_lease_ttl_s,
                             trace_sample=cfg.trace_sample,
                             trace_buffer_spans=cfg.trace_buffer_spans,
                             trace_export_interval_s=cfg
                             .trace_export_interval_s,
                             fleet_metrics_interval_s=cfg
                             .fleet_metrics_interval_s).start()
    if cfg.partitions > 1:
        print(f"partitioned request plane: {cfg.partitions} partition "
              f"streams, lease ttl {cfg.partition_lease_ttl_s:g}s "
              f"(owned set rebalances as engines join/leave)",
              flush=True)
    if cfg.batch_policy != "fixed":
        print(f"batching: policy={cfg.batch_policy}"
              + (f" deadline={cfg.deadline_ms:g}ms"
                 if cfg.deadline_ms is not None else
                 (f" deadline={cfg.slo_latency_ms:g}ms (from slo)"
                  if cfg.slo_latency_ms is not None else "")),
              flush=True)
    if cfg.admission_tiers:
        print(f"admission tiers (low->high): "
              f"{','.join(cfg.admission_tiers)} "
              f"(429 at {cfg.admission_max_backlog} backlog, shed at "
              f"{cfg.shed_backlog})", flush=True)
    if engine_id:
        print(f"engine id {engine_id} (fleet member; claim window "
              f"{cfg.claim_min_idle_s:g}s)", flush=True)
    if cfg.trace_sample > 0:
        print(f"fleet trace plane: sampling {cfg.trace_sample:g} of "
              f"requests (export every "
              f"{cfg.trace_export_interval_s:g}s, span ring "
              f"{cfg.trace_buffer_spans})", flush=True)
    rollout_agent = None
    if cfg.rollout_model_dir:
        # versioned rollout (ISSUE 14): this engine follows the
        # gateway controller's directives — hot-swap on command,
        # canary, report the new version in its heartbeat (engine_id
        # presence was enforced before the engine joined the group)
        from analytics_zoo_tpu.serving.rollout import EngineRolloutAgent
        rollout_agent = EngineRolloutAgent(
            serving, broker.clone(), stream=cfg.stream,
            poll_interval_s=cfg.rollout_poll_interval_s,
            drain_timeout_s=cfg.rollout_drain_timeout_s,
            canary_timeout_s=cfg.rollout_canary_timeout_s,
            golden_tolerance=cfg.rollout_golden_tolerance).start()
        print(f"rollout agent watching directives for "
              f"{cfg.rollout_model_dir} (poll "
              f"{cfg.rollout_poll_interval_s:g}s)", flush=True)
    if frontend is not None:
        frontend._srv.serving = serving
        if rollout_agent is not None:
            frontend.set_rollout(rollout_agent)
    if serving.slo is not None:
        obj = serving.slo.objectives
        parts = []
        if obj.latency_ms is not None:
            parts.append(f"latency p{obj.latency_quantile * 100:g}"
                         f"<={obj.latency_ms:g}ms")
        if obj.availability is not None:
            parts.append(f"availability>={obj.availability:g}")
        print(f"slo: {' '.join(parts)} over {obj.window_s:g}s "
              "(watch slo_burn_rate; /healthz aggregates)", flush=True)
    print("cluster serving started", flush=True)

    def shutdown():
        if rollout_agent is not None:
            rollout_agent.stop()
        if frontend:
            frontend.stop()
        serving.stop()
        print(json.dumps(serving.metrics()), flush=True)
        if tracer is not None and cfg.trace_path:
            tracer.write_chrome_trace(cfg.trace_path)
            print(f"chrome trace written to {cfg.trace_path} "
                  "(open in ui.perfetto.dev)", flush=True)

    return _run_until_signal(shutdown)


def _start_generative(cfg, broker, frontend) -> int:
    """Decode-mode tail of `cmd_start`: build + warm the generative
    executables, start the continuous-batching engine, serve until
    signalled. Warmup pre-compiles every (prompt bucket, kv bucket)
    program so no XLA compile ever lands on the request path."""
    from analytics_zoo_tpu.serving.decode import DecodeServing, _pow2_ladder
    model, inst = cfg.build_generative_model()
    kv_buckets = cfg.decode_kv_buckets or _pow2_ladder(
        8, cfg.decode_max_kv_len)
    prompt_buckets = cfg.decode_prompt_buckets or _pow2_ladder(
        4, max(4, cfg.decode_max_kv_len // 2))
    if cfg.decode_paged:
        bl = cfg.decode_block_len
        table_len = cfg.decode_max_kv_len // bl
        kv_blocks = cfg.decode_kv_blocks or (
            cfg.decode_slots * table_len + 1)
        if cfg.decode_prefill_chunk:
            chunk_buckets = [b for b in prompt_buckets
                             if b <= cfg.decode_prefill_chunk] \
                or [prompt_buckets[0]]
        else:
            chunk_buckets = list(prompt_buckets)
        model.warmup_generative_paged(
            inst.init_kv_blocks, num_blocks=kv_blocks, block_len=bl,
            lanes=cfg.decode_slots, table_len=table_len,
            chunk_buckets=chunk_buckets, kv_buckets=kv_buckets)
    else:
        model.warmup_generative(inst.init_kv, slots=cfg.decode_slots,
                                max_kv_len=cfg.decode_max_kv_len,
                                prompt_buckets=prompt_buckets,
                                kv_buckets=kv_buckets)
    print(f"generative warmup: {json.dumps(model.warmup_report)}",
          flush=True)
    if model.compile_cache is not None:
        src = model.warmup_source
        s = model.compile_cache.stats()
        print("compile cache: "
              f"{sum(1 for v in src.values() if v == 'cached')} warmed "
              f"from disk, "
              f"{sum(1 for v in src.values() if v == 'compiled')} "
              f"compiled fresh ({s['entries']} entries, {s['bytes']} "
              f"bytes in {s['path']})", flush=True)
    serving = DecodeServing(
        model, inst.init_kv, broker=broker, stream=cfg.stream,
        slots=cfg.decode_slots, max_kv_len=cfg.decode_max_kv_len,
        kv_buckets=kv_buckets, prompt_buckets=prompt_buckets,
        max_new_default=cfg.decode_max_new_tokens,
        eos_id=cfg.decode_eos_id, deadline_ms=cfg.deadline_ms,
        max_prefills_per_step=cfg.decode_max_prefills,
        max_waiting=cfg.decode_max_waiting,
        engine_id=cfg.resolve_engine_id(),
        paged=cfg.decode_paged,
        init_kv_blocks=getattr(inst, "init_kv_blocks", None),
        block_len=cfg.decode_block_len,
        kv_blocks=cfg.decode_kv_blocks,
        prefill_chunk=cfg.decode_prefill_chunk,
        prefix_cache=cfg.decode_prefix_cache,
        prefix_cache_blocks=cfg.decode_prefix_cache_blocks,
        # crash safety (ISSUE 20): claim/resume a dead peer's in-flight
        # generative records (resume: false opts out), heartbeat for the
        # peers' stall detection, watchdog + preemption + writeback
        # buffering knobs
        claim_min_idle_s=(cfg.claim_min_idle_s
                          if cfg.decode_resume else None),
        claim_interval_s=cfg.claim_interval_s,
        heartbeat_interval_s=cfg.heartbeat_interval_s,
        max_seq_wall_s=cfg.decode_max_seq_wall_s,
        preempt_max=cfg.decode_preempt_max,
        writeback_buffer_rows=cfg.decode_writeback_buffer).start()
    if cfg.decode_paged:
        print(f"decode engine {serving.engine_id} (paged): "
              f"{serving.kv_blocks} KV blocks x {cfg.decode_block_len} "
              f"tokens, {cfg.decode_slots} lanes, kv buckets "
              f"{kv_buckets}, chunk buckets {serving.chunk_buckets}, "
              f"prefix cache "
              f"{'on' if cfg.decode_prefix_cache else 'off'}", flush=True)
    else:
        print(f"decode engine {serving.engine_id}: {cfg.decode_slots} KV "
              f"slots x {cfg.decode_max_kv_len} positions, kv buckets "
              f"{kv_buckets}, prompt buckets {prompt_buckets}", flush=True)
    print("cluster serving started (generative)", flush=True)

    def shutdown():
        if frontend:
            frontend.stop()
        serving.stop()
        print(json.dumps(serving.stats), flush=True)

    return _run_until_signal(shutdown)


def _run_until_signal(stop_fn) -> int:
    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    while not stop:
        time.sleep(0.5)
    stop_fn()
    return 0


def cmd_gateway(args) -> int:
    """Engine-less fleet gateway (ISSUE 10): an HTTP frontend that
    tracks engine heartbeats on the broker and answers `/healthz` /
    `/metrics` for the whole fleet — run it on the edge while N
    `start --engine-id auto` engine processes drain the stream.

    `--autoscale` (ISSUE 11) additionally runs a `FleetAutoscaler`
    here: the gateway watches backlog depth and heartbeat-reported SLO
    burn and spawns/retires `start --engine-id auto` engine processes
    (children of this gateway) between `--min-engines` and
    `--max-engines`, with hysteresis so a spike can't flap the fleet.
    Retirement is a clean SIGTERM — the engine deregisters and drains,
    and the claim sweep moves anything left to peers. Requires
    `--engine-config`, the serving config the spawned engines run."""
    import subprocess

    from analytics_zoo_tpu.serving.broker import connect_broker
    from analytics_zoo_tpu.serving.config import ServingConfig
    from analytics_zoo_tpu.serving.http_frontend import FrontEnd
    if args.engine_ttl <= 0:
        # same contract as the params path (_validate_fleet): a zero
        # TTL flaps every beating engine dead — fail at launch
        raise SystemExit(
            f"--engine-ttl {args.engine_ttl:g} must be > 0")
    if args.leader_ttl <= 0:
        raise SystemExit(
            f"--leader-ttl {args.leader_ttl:g} must be > 0")
    if args.partitions is not None:
        from analytics_zoo_tpu.serving.partitions import \
            validate_partitions
        try:
            validate_partitions(args.partitions)
        except ValueError as e:
            raise SystemExit(f"--partitions: {e}")
    engine_cfg = ServingConfig.load(args.engine_config) \
        if args.engine_config else None
    admission = None
    admission_header = "X-Priority"
    broker = connect_broker(args.broker)
    if args.admission_tiers:
        # explicit CLI tiers win over the config block
        from analytics_zoo_tpu.serving.elastic import AdmissionController
        tiers = [t.strip() for t in args.admission_tiers.split(",")
                 if t.strip()]
        admission = AdmissionController(
            broker.clone(), args.stream, tiers,
            max_backlog=args.admission_max_backlog)
    elif engine_cfg is not None and engine_cfg.admission_tiers:
        # the engine config's params.admission block IS the fleet's
        # admission policy — the gateway must enforce the same tiers
        # the engines schedule/shed by, or the documented early 429
        # silently never engages. Sampled on THIS gateway's --stream
        # (the stream the fleet actually drains).
        from analytics_zoo_tpu.serving.elastic import AdmissionController
        admission = AdmissionController(
            broker.clone(), args.stream, engine_cfg.admission_tiers,
            max_backlog=engine_cfg.admission_max_backlog)
    if engine_cfg is not None:
        admission_header = engine_cfg.admission_header
    partitions = args.partitions if args.partitions is not None else (
        engine_cfg.partitions if engine_cfg else 1)
    gateway_id = args.gateway_id
    if gateway_id and gateway_id.lower() == "auto":
        import os as _os
        import uuid as _uuid
        gateway_id = f"gateway-{_os.getpid()}-{_uuid.uuid4().hex[:6]}"
    trace_sample = args.trace_sample if args.trace_sample is not None \
        else (engine_cfg.trace_sample if engine_cfg else 0.0)
    frontend = FrontEnd(
        broker, None, host=args.host,
        port=args.port, fleet_stream=args.stream,
        engine_ttl_s=args.engine_ttl,
        tokens_per_second=args.tokens_per_second,
        admission=admission,
        admission_header=admission_header,
        partitions=partitions,
        gateway_id=gateway_id,
        leader_ttl_s=args.leader_ttl,
        trace_sample=trace_sample,
        trace_buffer_spans=(engine_cfg.trace_buffer_spans
                            if engine_cfg else 20000),
        trace_export_interval_s=(engine_cfg.trace_export_interval_s
                                 if engine_cfg else 0.5),
        # streaming continuity (ISSUE 20): the gateway relays SSE for a
        # generative fleet — keepalives + heartbeat-aware stall cutoff
        stream_keepalive_s=(engine_cfg.decode_keepalive_s
                            if engine_cfg else None),
        stream_stall_timeout_s=(args.engine_ttl * 2
                                if engine_cfg is not None
                                and engine_cfg.generative
                                else None)).start()
    print(f"fleet gateway on :{frontend.port} "
          f"(stream {args.stream}, engine ttl {args.engine_ttl:g}s)",
          flush=True)
    if trace_sample > 0:
        print(f"fleet trace plane: sampling {trace_sample:g} of "
              "requests; GET /trace/<request_id> serves merged "
              "cross-process timelines", flush=True)
    if gateway_id:
        print(f"gateway replica {gateway_id} (leader lease ttl "
              f"{args.leader_ttl:g}s; control loops act only while "
              "this replica leads)", flush=True)
    rollout = None
    # versioned rollout (ISSUE 14): the controller converges the fleet
    # onto the newest PUBLISHED checkpoint version, one engine at a
    # time (POST /rollout pins a version; GET /rollout/status watches).
    # The engine config's params.rollout block seeds the knobs — ONE
    # block drives both sides of the protocol — and explicit gateway
    # flags override.
    rollout_dir = args.rollout_dir or (
        engine_cfg.rollout_model_dir if engine_cfg else None)
    if rollout_dir:
        rollout_interval = args.rollout_interval if args.rollout_interval \
            is not None else (engine_cfg.rollout_poll_interval_s
                              if engine_cfg else 1.0)
        rollout_timeout = args.rollout_engine_timeout \
            if args.rollout_engine_timeout is not None else (
                engine_cfg.rollout_engine_timeout_s if engine_cfg
                else 60.0)
        if rollout_timeout <= 0 or rollout_interval <= 0:
            raise SystemExit("--rollout-interval and "
                             "--rollout-engine-timeout must be > 0")
        from analytics_zoo_tpu.serving.rollout import RolloutController
        rollout = RolloutController(
            broker.clone(), args.stream, rollout_dir,
            frontend.fleet,
            poll_interval_s=rollout_interval,
            engine_timeout_s=rollout_timeout,
            # replicated gateway (ISSUE 16): every replica accepts
            # POST /rollout (the pin persists in the control hash) but
            # only the leader's loop directs engines
            leader_fn=frontend.is_leader).start()
        frontend.set_rollout(rollout)
        print(f"rollout controller watching {rollout_dir} "
              f"(poll {rollout_interval:g}s, engine timeout "
              f"{rollout_timeout:g}s)", flush=True)
    import threading

    scaler = None
    children = []
    retired = []        # SIGTERMed, still draining: shutdown reaps them
    stopping = threading.Event()
    if args.autoscale:
        if engine_cfg is None:
            raise SystemExit("--autoscale needs --engine-config (the "
                             "serving config spawned engines run)")
        # config knobs (params.autoscale) seed the defaults; explicit
        # gateway flags override
        knobs = dict(engine_cfg.autoscale or {})
        knobs["min_engines"] = args.min_engines \
            if args.min_engines is not None \
            else knobs.get("min_engines", 1)
        knobs["max_engines"] = args.max_engines \
            if args.max_engines is not None \
            else knobs.get("max_engines", 4)

        def spawn():
            if stopping.is_set():
                # a tick wedged in broker I/O can outlive the 5 s join
                # in scaler.stop() and fire after shutdown reaped the
                # children — it must not orphan a fresh engine
                return None
            children.append(subprocess.Popen(
                [sys.executable, "-m", "analytics_zoo_tpu.serving.cli",
                 "start", "--config", args.engine_config,
                 "--engine-id", "auto"]))
            return children[-1]

        def retire() -> bool:
            # newest live child first: LIFO keeps long-lived engines'
            # warm OS caches; a clean SIGTERM drains + deregisters.
            # The retiree moves to `retired` (not dropped): shutdown
            # must still wait on — and, if it wedges draining, kill —
            # every child this gateway ever spawned
            for p in reversed(children):
                if p.poll() is None:
                    p.terminate()
                    children.remove(p)
                    retired.append(p)
                    return True
            return False

        from analytics_zoo_tpu.serving.fleet import FleetAutoscaler
        scaler = FleetAutoscaler(
            frontend.fleet, broker.clone(), args.stream, spawn, retire,
            # an admission-enabled gateway already samples the stream
            # depth on its own cadence: share the probe instead of
            # running a second poller against the same stream (and
            # flapping the shared serving_backlog_depth gauge)
            backlog_fn=admission.backlog if admission is not None
            else None,
            # follower replicas observe but never spawn/retire — two
            # autoscalers holding min_engines would double-provision
            leader_fn=frontend.is_leader,
            **knobs).start()
        print(f"autoscaler: engines [{scaler.min_engines}, "
              f"{scaler.max_engines}], backlog "
              f"{scaler.backlog_low:g}/{scaler.backlog_high:g} per "
              f"engine, burn>={scaler.burn_high:g} scales up", flush=True)

    def shutdown():
        stopping.set()
        if rollout is not None:
            rollout.stop()
        if scaler is not None:
            scaler.stop()
        for p in children:
            if p.poll() is None:
                p.terminate()
        for p in children + retired:
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()
        frontend.stop()

    return _run_until_signal(shutdown)


def cmd_broker(args) -> int:
    from analytics_zoo_tpu.serving.broker import TCPBrokerServer
    srv = TCPBrokerServer(host=args.host, port=args.port).start()
    print(f"broker listening on {srv.host}:{srv.port}", flush=True)
    return _run_until_signal(srv.stop)


def cmd_redis(args) -> int:
    """Standalone RESP2 stream/hash server (`redis://` brokers connect to
    it with the real wire protocol; swap in a production Redis freely)."""
    from analytics_zoo_tpu.serving.redis_server import MiniRedisServer
    srv = MiniRedisServer(host=args.host, port=args.port).start()
    print(f"mini-redis listening on {srv.url}", flush=True)
    return _run_until_signal(srv.stop)


def cmd_metrics(args) -> int:
    import urllib.request
    url = args.url
    if not url.startswith(("http://", "https://")):
        raise SystemExit(
            f"metrics is served by the HTTP frontend; expected an http(s) "
            f"URL (host:http_port), got {url!r}")
    # --prometheus negotiates the text exposition (what a scraper sees);
    # default stays the JSON timer snapshot
    headers = {"Accept": "text/plain"} if getattr(
        args, "prometheus", False) else {}
    req = urllib.request.Request(url.rstrip("/") + "/metrics",
                                 headers=headers)
    print(urllib.request.urlopen(req, timeout=10).read().decode())
    return 0


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser(prog="analytics-zoo-serving")
    sub = p.add_subparsers(dest="cmd", required=True)
    ps = sub.add_parser("start", help="run the serving loop")
    ps.add_argument("--config", required=True)
    ps.add_argument("--num-replicas", default=None,
                    help="override params.num_replicas: an integer, or "
                         "'auto' for one replica per local device")
    ps.add_argument("--placement", choices=["replicated", "sharded"],
                    default=None,
                    help="override params.placement")
    ps.add_argument("--mesh", default=None,
                    help="override params.mesh: the sharded placement's "
                         'device-mesh factorization, e.g. '
                         '"data=1,fsdp=2,tensor=4" (-1 infers one axis; '
                         "a tensor extent > 1 engages column/row-"
                         "parallel placement for bigger-than-one-chip "
                         "models)")
    ps.add_argument("--compile-cache-dir", default=None,
                    help="override params.compile_cache_dir: persistent "
                         "AOT executable cache directory (warm restarts "
                         "skip XLA compilation)")
    ps.add_argument("--engine-id", default=None,
                    help="fleet mode: this engine's identity as one of "
                         "N co-consumers ('auto' generates a unique id; "
                         "enables heartbeats + the claim sweep)")
    ps.add_argument("--partitions", type=int, default=None,
                    help="override params.partitions: split the request "
                         "stream into N hash-keyed partition streams "
                         "leased across the fleet (needs --engine-id; "
                         "1 = the legacy single stream)")
    ps.add_argument("--reshard", action="store_true",
                    help="acknowledge a partition-count change against "
                         "a live fleet's broker meta (in-flight records "
                         "on the old layout may strand until every "
                         "engine restarts on the new count)")
    ps.set_defaults(fn=cmd_start)
    pg = sub.add_parser("gateway", help="run an engine-less fleet "
                                        "gateway frontend")
    pg.add_argument("--broker", default="memory",
                    help="broker url the fleet shares "
                         "(tcp://h:p | redis://h:p)")
    pg.add_argument("--host", default="0.0.0.0")
    pg.add_argument("--port", type=int, default=10020)
    pg.add_argument("--stream", default="serving_stream")
    pg.add_argument("--engine-ttl", type=float, default=6.0,
                    help="seconds without a heartbeat before an engine "
                         "counts dead")
    pg.add_argument("--tokens-per-second", type=float, default=None)
    pg.add_argument("--autoscale", action="store_true",
                    help="run the SLO-driven engine autoscaler on this "
                         "gateway (spawns/retires 'start --engine-id "
                         "auto' children; needs --engine-config)")
    pg.add_argument("--engine-config", default=None,
                    help="serving config the autoscaler's spawned "
                         "engines run (its params.autoscale block "
                         "seeds the scaler's thresholds)")
    pg.add_argument("--min-engines", type=int, default=None,
                    help="autoscaler floor (default: config, else 1)")
    pg.add_argument("--max-engines", type=int, default=None,
                    help="autoscaler ceiling (default: config, else 4)")
    pg.add_argument("--admission-tiers", default=None,
                    help="comma-joined priority tiers, lowest first "
                         "(enables tiered 429 admission on /predict)")
    pg.add_argument("--admission-max-backlog", type=int, default=512,
                    help="backlog at which even the top tier gets 429s")
    pg.add_argument("--rollout-dir", default=None,
                    help="run the versioned-rollout controller on this "
                         "gateway, watching this checkpoint root for "
                         "PUBLISHED versions (default: the engine "
                         "config's params.rollout.model_dir — one "
                         "block drives both sides)")
    pg.add_argument("--rollout-interval", type=float, default=None,
                    help="rollout controller poll cadence in seconds "
                         "(default: engine config "
                         "params.rollout.poll_interval_s, else 1)")
    pg.add_argument("--rollout-engine-timeout", type=float, default=None,
                    help="seconds an alive engine may take to convert "
                         "before it is skipped as a straggler "
                         "(default: engine config "
                         "params.rollout.engine_timeout_s, else 60)")
    pg.add_argument("--partitions", type=int, default=None,
                    help="hash-route /predict enqueues across N "
                         "partition streams — must match the engines' "
                         "params.partitions (default: engine config, "
                         "else 1)")
    pg.add_argument("--gateway-id", default=None,
                    help="run as one REPLICA of a replicated gateway "
                         "('auto' generates an id): a leader lease on "
                         "the broker elects which replica's control "
                         "loops act; every replica serves reads and "
                         "accepts POST /rollout")
    pg.add_argument("--leader-ttl", type=float, default=3.0,
                    help="seconds without a renewal before the gateway "
                         "leader lease is up for takeover")
    pg.add_argument("--trace-sample", type=float, default=None,
                    help="fleet trace plane (ISSUE 17): head-sampling "
                         "rate in [0, 1] for cross-process request "
                         "traces (default: the engine config's "
                         "params.trace_sample, else 0 = off); "
                         "GET /trace/<request_id> works regardless")
    pg.set_defaults(fn=cmd_gateway)
    pb = sub.add_parser("broker", help="run a standalone TCP broker")
    pb.add_argument("--host", default="0.0.0.0")
    pb.add_argument("--port", type=int, default=6379)
    pb.set_defaults(fn=cmd_broker)
    pr = sub.add_parser("redis", help="run the in-package RESP2 server")
    pr.add_argument("--host", default="0.0.0.0")
    pr.add_argument("--port", type=int, default=6379)
    pr.set_defaults(fn=cmd_redis)
    pm = sub.add_parser("metrics", help="fetch frontend metrics")
    pm.add_argument("--url", required=True)
    pm.add_argument("--prometheus", action="store_true",
                    help="request Prometheus text exposition "
                         "(Accept: text/plain)")
    pm.set_defaults(fn=cmd_metrics)
    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
