"""Cluster Serving CLI — `cluster-serving-start/stop/cli` analogue
(`scripts/cluster-serving/`).

    python -m analytics_zoo_tpu.serving.cli start --config config.yaml
    python -m analytics_zoo_tpu.serving.cli broker --port 6380
    python -m analytics_zoo_tpu.serving.cli metrics --url http://host:http_port

`start` runs the serving loop (and HTTP frontend when http_port is set) in
the foreground; `broker` runs a standalone TCP broker so clients on other
hosts/processes can enqueue (the image has no Redis server)."""

from __future__ import annotations

import argparse
import json
import logging
import signal
import sys
import time


def cmd_start(args) -> int:
    from analytics_zoo_tpu.serving.config import ServingConfig
    from analytics_zoo_tpu.serving.http_frontend import FrontEnd
    from analytics_zoo_tpu.serving.server import ClusterServing
    from analytics_zoo_tpu.serving.broker import connect_broker
    replicas = getattr(args, "num_replicas", None)
    if replicas is not None:
        try:
            replicas = int(replicas)
        except ValueError:
            pass                    # 'auto' (load() validates spellings)
    # overrides go INTO load(): validation must see the effective values,
    # or a config authored for a bigger host could never be rescued here
    cfg = ServingConfig.load(args.config, num_replicas=replicas,
                             placement=getattr(args, "placement", None),
                             compile_cache_dir=getattr(
                                 args, "compile_cache_dir", None))
    if getattr(args, "engine_id", None):
        # fleet override (ISSUE 10): each process in a scale-out gets
        # its own identity at launch ("auto" generates one)
        cfg.engine_id = args.engine_id
        cfg._validate_fleet()
    engine_id = cfg.resolve_engine_id()
    if cfg.model_encrypted and cfg.http_port is None:
        raise SystemExit(
            "secure.model_encrypted needs http_port: the secret/salt "
            "arrive via the frontend's POST /model-secure")
    broker = connect_broker(cfg.broker_url)
    frontend = None
    if cfg.http_port is not None:
        # frontend first: with model_encrypted, build_model blocks until
        # someone POSTs the secret/salt to /model-secure
        frontend = FrontEnd(
            broker, None, port=cfg.http_port,
            tokens_per_second=cfg.tokens_per_second,
            token_acquire_timeout_ms=cfg.token_acquire_timeout_ms,
            tls_certfile=cfg.tls_certfile,
            tls_keyfile=cfg.tls_keyfile,
            profile_dir=cfg.profile_dir,
            profile_max_artifacts=cfg.profile_max_artifacts,
            profile_enabled=cfg.profile_enabled,
            # fleet mode: the frontend doubles as the fleet gateway
            # (engine heartbeats -> /healthz + serving_engines_* gauges)
            fleet_stream=cfg.stream if engine_id else None,
            engine_ttl_s=cfg.engine_ttl_s).start()
        scheme = "https" if frontend.tls else "http"
        print(f"{scheme} frontend on :{frontend.port}", flush=True)
    model = cfg.build_model(broker=broker)
    print(f"placement={model.placement} replicas={model.num_replicas} "
          f"devices={len(model.devices)}", flush=True)
    if cfg.warmup_shapes:
        # pre-compile every REACHABLE shape bucket BEFORE the stream
        # opens: no XLA compile ever lands on a request. The reader never
        # hands dispatch more than batch_size records, so buckets past
        # the one covering batch_size would pay compile time (and cached
        # executable memory) for batches that cannot occur
        import numpy as np

        from analytics_zoo_tpu.serving.inference_model import _next_bucket
        dtype = np.dtype(cfg.warmup_dtype)
        cap = _next_bucket(cfg.batch_size, model.buckets)
        buckets = [b for b in model.buckets if b <= cap]
        for shape in cfg.warmup_shapes:
            model.warmup(np.zeros(tuple(shape), dtype), buckets=buckets)
        print(f"warmed {len(model.warmed_buckets)} shape buckets: "
              f"{json.dumps(model.warmup_report)}", flush=True)
        if model.compile_cache is not None:
            # what this restart actually paid: per-(replica, bucket)
            # cache hits vs fresh compiles, plus the dir's state
            src = model.warmup_source
            s = model.compile_cache.stats()
            print("compile cache: "
                  f"{sum(1 for v in src.values() if v == 'cached')} "
                  "warmed from disk, "
                  f"{sum(1 for v in src.values() if v == 'compiled')} "
                  f"compiled fresh ({s['entries']} entries, "
                  f"{s['bytes']} bytes in {s['path']})", flush=True)
    tracer = None
    if cfg.trace or cfg.trace_path:
        from analytics_zoo_tpu.observability import Tracer
        tracer = Tracer()
    serving = ClusterServing(model, broker, stream=cfg.stream,
                             batch_size=cfg.batch_size,
                             batch_timeout_ms=cfg.batch_timeout_ms,
                             pipelined=cfg.pipelined,
                             decode_workers=cfg.decode_workers,
                             queue_depth=cfg.queue_depth,
                             tracer=tracer,
                             supervise=cfg.supervise,
                             failure_threshold=cfg.failure_threshold,
                             probe_interval_s=cfg.probe_interval_s,
                             latency_factor=cfg.latency_factor,
                             breaker_failure_threshold=cfg
                             .breaker_failure_threshold,
                             breaker_reset_s=cfg.breaker_reset_s,
                             sink_buffer_batches=cfg
                             .sink_buffer_batches,
                             slo=cfg.build_slo(),
                             engine_id=engine_id,
                             claim_min_idle_s=cfg.claim_min_idle_s,
                             claim_interval_s=cfg.claim_interval_s,
                             heartbeat_interval_s=cfg
                             .heartbeat_interval_s).start()
    if engine_id:
        print(f"engine id {engine_id} (fleet member; claim window "
              f"{cfg.claim_min_idle_s:g}s)", flush=True)
    if frontend is not None:
        frontend._srv.serving = serving
    if serving.slo is not None:
        obj = serving.slo.objectives
        parts = []
        if obj.latency_ms is not None:
            parts.append(f"latency p{obj.latency_quantile * 100:g}"
                         f"<={obj.latency_ms:g}ms")
        if obj.availability is not None:
            parts.append(f"availability>={obj.availability:g}")
        print(f"slo: {' '.join(parts)} over {obj.window_s:g}s "
              "(watch slo_burn_rate; /healthz aggregates)", flush=True)
    print("cluster serving started", flush=True)

    def shutdown():
        if frontend:
            frontend.stop()
        serving.stop()
        print(json.dumps(serving.metrics()), flush=True)
        if tracer is not None and cfg.trace_path:
            tracer.write_chrome_trace(cfg.trace_path)
            print(f"chrome trace written to {cfg.trace_path} "
                  "(open in ui.perfetto.dev)", flush=True)

    return _run_until_signal(shutdown)


def _run_until_signal(stop_fn) -> int:
    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    while not stop:
        time.sleep(0.5)
    stop_fn()
    return 0


def cmd_gateway(args) -> int:
    """Engine-less fleet gateway (ISSUE 10): an HTTP frontend that
    tracks engine heartbeats on the broker and answers `/healthz` /
    `/metrics` for the whole fleet — run it on the edge while N
    `start --engine-id auto` engine processes drain the stream."""
    from analytics_zoo_tpu.serving.broker import connect_broker
    from analytics_zoo_tpu.serving.http_frontend import FrontEnd
    if args.engine_ttl <= 0:
        # same contract as the params path (_validate_fleet): a zero
        # TTL flaps every beating engine dead — fail at launch
        raise SystemExit(
            f"--engine-ttl {args.engine_ttl:g} must be > 0")
    frontend = FrontEnd(
        connect_broker(args.broker), None, host=args.host,
        port=args.port, fleet_stream=args.stream,
        engine_ttl_s=args.engine_ttl,
        tokens_per_second=args.tokens_per_second).start()
    print(f"fleet gateway on :{frontend.port} "
          f"(stream {args.stream}, engine ttl {args.engine_ttl:g}s)",
          flush=True)
    return _run_until_signal(frontend.stop)


def cmd_broker(args) -> int:
    from analytics_zoo_tpu.serving.broker import TCPBrokerServer
    srv = TCPBrokerServer(host=args.host, port=args.port).start()
    print(f"broker listening on {srv.host}:{srv.port}", flush=True)
    return _run_until_signal(srv.stop)


def cmd_redis(args) -> int:
    """Standalone RESP2 stream/hash server (`redis://` brokers connect to
    it with the real wire protocol; swap in a production Redis freely)."""
    from analytics_zoo_tpu.serving.redis_server import MiniRedisServer
    srv = MiniRedisServer(host=args.host, port=args.port).start()
    print(f"mini-redis listening on {srv.url}", flush=True)
    return _run_until_signal(srv.stop)


def cmd_metrics(args) -> int:
    import urllib.request
    url = args.url
    if not url.startswith(("http://", "https://")):
        raise SystemExit(
            f"metrics is served by the HTTP frontend; expected an http(s) "
            f"URL (host:http_port), got {url!r}")
    # --prometheus negotiates the text exposition (what a scraper sees);
    # default stays the JSON timer snapshot
    headers = {"Accept": "text/plain"} if getattr(
        args, "prometheus", False) else {}
    req = urllib.request.Request(url.rstrip("/") + "/metrics",
                                 headers=headers)
    print(urllib.request.urlopen(req, timeout=10).read().decode())
    return 0


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser(prog="analytics-zoo-serving")
    sub = p.add_subparsers(dest="cmd", required=True)
    ps = sub.add_parser("start", help="run the serving loop")
    ps.add_argument("--config", required=True)
    ps.add_argument("--num-replicas", default=None,
                    help="override params.num_replicas: an integer, or "
                         "'auto' for one replica per local device")
    ps.add_argument("--placement", choices=["replicated", "sharded"],
                    default=None,
                    help="override params.placement")
    ps.add_argument("--compile-cache-dir", default=None,
                    help="override params.compile_cache_dir: persistent "
                         "AOT executable cache directory (warm restarts "
                         "skip XLA compilation)")
    ps.add_argument("--engine-id", default=None,
                    help="fleet mode: this engine's identity as one of "
                         "N co-consumers ('auto' generates a unique id; "
                         "enables heartbeats + the claim sweep)")
    ps.set_defaults(fn=cmd_start)
    pg = sub.add_parser("gateway", help="run an engine-less fleet "
                                        "gateway frontend")
    pg.add_argument("--broker", default="memory",
                    help="broker url the fleet shares "
                         "(tcp://h:p | redis://h:p)")
    pg.add_argument("--host", default="0.0.0.0")
    pg.add_argument("--port", type=int, default=10020)
    pg.add_argument("--stream", default="serving_stream")
    pg.add_argument("--engine-ttl", type=float, default=6.0,
                    help="seconds without a heartbeat before an engine "
                         "counts dead")
    pg.add_argument("--tokens-per-second", type=float, default=None)
    pg.set_defaults(fn=cmd_gateway)
    pb = sub.add_parser("broker", help="run a standalone TCP broker")
    pb.add_argument("--host", default="0.0.0.0")
    pb.add_argument("--port", type=int, default=6379)
    pb.set_defaults(fn=cmd_broker)
    pr = sub.add_parser("redis", help="run the in-package RESP2 server")
    pr.add_argument("--host", default="0.0.0.0")
    pr.add_argument("--port", type=int, default=6379)
    pr.set_defaults(fn=cmd_redis)
    pm = sub.add_parser("metrics", help="fetch frontend metrics")
    pm.add_argument("--url", required=True)
    pm.add_argument("--prometheus", action="store_true",
                    help="request Prometheus text exposition "
                         "(Accept: text/plain)")
    pm.set_defaults(fn=cmd_metrics)
    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
