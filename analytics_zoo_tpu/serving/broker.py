"""Queue brokers — the serving data plane.

The reference's data plane is a Redis stream with consumer groups
(`FlinkRedisSource.scala:66-87` xgroupCreate/xreadGroup, results HSET back,
`FlinkRedisSink.scala:67`). Same contract here — `xadd` records, `read_group`
batches with at-least-once redelivery via pending-ack, `hset`/`hget` results —
over three interchangeable transports:

- MemoryBroker: in-process (single-host serving, tests).
- TCPBroker(Server): stdlib-socket line protocol so clients in other
  processes/hosts can enqueue (this image has no redis server/client).
- RedisBroker: speaks RESP2 to a real Redis over a stdlib-socket client
  (no redis-py dependency — the image has none); keys/streams named as
  the reference (`serving_stream`, result hashes).
"""

from __future__ import annotations

import base64
import json
import socket
import socketserver
import threading
import time
import uuid
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np


def encode_ndarray(arr: np.ndarray) -> Dict:
    """b64 ndarray encoding, the client protocol of `serving/client.py:114`
    (reference uses b64 of arrow/raw bytes; raw bytes here)."""
    arr = np.ascontiguousarray(arr)
    return {"b64": base64.b64encode(arr.tobytes()).decode("ascii"),
            "dtype": str(arr.dtype), "shape": list(arr.shape)}


def decode_ndarray(blob: Dict) -> np.ndarray:
    data = base64.b64decode(blob["b64"])
    return np.frombuffer(data, dtype=np.dtype(blob["dtype"])).reshape(
        blob["shape"]).copy()


class Broker:
    """Stream + result-hash contract."""

    def clone(self) -> "Broker":
        """A connection suitable for a SECOND serving thread. Pipelined
        serving reads (blocking XREADGROUP) and writes results from
        different stages concurrently; on a single-socket transport the
        reader would hold the connection lock for its whole block window
        and starve the sink. Default: share (in-process brokers take the
        lock per-op; TCPBroker sockets are per-thread already)."""
        return self

    def xadd(self, stream: str, record: Dict) -> str:
        raise NotImplementedError

    def xadd_many(self, entries: List[Tuple[str, Dict]]) -> List[str]:
        """Batched enqueue — the ingest analogue of the sink's fused
        `writeback`: append a whole burst of (stream, record) pairs in
        ONE broker interaction (a pipelined multi-XADD on Redis, one
        lock acquisition on MemoryBroker, one RPC on TCPBroker) and
        return the record ids in order. Entries may target DIFFERENT
        streams — a hash-partitioned burst fans out across partition
        streams inside the same round trip, so the frontend→broker hop
        costs one RTT per coalesced flush instead of one per record.
        Default loops `xadd` for brokers without a cheaper path."""
        return [self.xadd(stream, record) for stream, record in entries]

    def read_group(self, stream: str, group: str, consumer: str,
                   count: int, block_ms: int = 100
                   ) -> List[Tuple[str, Dict]]:
        raise NotImplementedError

    def ack(self, stream: str, group: str, ids: List[str]) -> None:
        raise NotImplementedError

    def claim_stale(self, stream: str, group: str, consumer: str,
                    min_idle_ms: int, count: int
                    ) -> List[Tuple[str, Dict]]:
        """Claim pending (delivered-but-unacked) entries that have sat
        idle for at least `min_idle_ms` — a dead consumer's in-flight
        work — and hand them to `consumer` (XAUTOCLAIM on Redis). The
        fleet's claim sweep: a killed engine's batches redeliver to a
        live peer instead of rotting in the pending list. Claimed
        entries restart their idle clock, so concurrent sweepers from
        several engines split the backlog rather than all claiming the
        same records."""
        raise NotImplementedError

    def pending_count(self, stream: str, group: str) -> int:
        """Entries delivered to the group but not yet acked (XPENDING
        summary count) — what a crashed consumer may still owe."""
        raise NotImplementedError

    def stream_depth(self, stream: str) -> int:
        """Entries still in the stream (XLEN). The sink XDELs on ack, so
        this is the live backlog: records enqueued but not yet committed
        (undelivered + in-flight). The elastic layer's one load signal —
        the admission controller's 429 threshold, the adaptive batcher's
        light/heavy-load switch, and the autoscaler's scale trigger all
        read it (ISSUE 11)."""
        raise NotImplementedError

    def hset(self, key: str, field: str, value: str) -> int:
        """Returns the number of NEW fields created (0 when `field`
        already existed — Redis HSET semantics). The sink uses this to
        keep redelivered records from double-counting as served."""
        raise NotImplementedError

    def hset_many(self, key: str, mapping: Dict[str, str]) -> int:
        """Batched result writeback: ONE round trip for a whole batch of
        (field, value) pairs (`HSET key f1 v1 f2 v2 ...` on Redis) instead
        of one per record — the pipelined sink stage's write path.
        Returns the number of NEW fields created (overwrites of an
        already-written result — a redelivered record — don't count).
        Default loops hset for brokers without a cheaper path."""
        added = 0
        for field, value in mapping.items():
            added += self.hset(key, field, value) or 0
        return added

    def writeback(self, key: str, mapping: Dict[str, str], stream: str,
                  group: str, ids: List[str]) -> int:
        """The sink's whole batch commit — result HSET + XACK/XDEL — as
        ONE broker interaction (RESP-pipelined on Redis, a single lock
        acquisition on MemoryBroker, one RPC on TCPBroker). The sink
        pays one round-trip latency per batch instead of three; under a
        loaded host (or a real network) those round trips are what cap
        sink throughput. Returns the number of NEW result fields, like
        `hset_many` (the idempotent-writeback dedup). Default chains
        the two calls for brokers without a fused path."""
        added = self.hset_many(key, mapping)
        self.ack(stream, group, ids)
        return added

    def hget(self, key: str, field: str) -> Optional[str]:
        raise NotImplementedError

    def hmget(self, key: str, fields: List[str]) -> List[Optional[str]]:
        """Batched field read (HMGET): one round trip answers a whole
        poll's worth of result lookups — the client's fused
        enqueue+poll path reads every outstanding uri per sweep with
        one command instead of one HGET each. Missing fields come back
        as None, position-matched to `fields`. Default loops `hget`
        for brokers without a cheaper path."""
        return [self.hget(key, field) for field in fields]

    def hgetall(self, key: str) -> Dict[str, str]:
        raise NotImplementedError

    def hlen(self, key: str) -> int:
        """Field count (HLEN) — how result-drain progress is polled
        without serializing the whole hash over the wire each check.
        Default falls back to hgetall for brokers without a cheap path."""
        return len(self.hgetall(key))

    def hdel(self, key: str, field: str) -> None:
        raise NotImplementedError

    def hdel_many(self, key: str, fields) -> None:
        """Batched delete (variadic HDEL): result-drain loops
        (`OutputQueue.dequeue`) clear a whole poll's worth of fields in
        one round trip."""
        for field in fields:
            self.hdel(key, field)


class MemoryBroker(Broker):
    def __init__(self, redeliver_after_s: float = 30.0):
        self._lock = threading.Condition()
        self._streams: Dict[str, OrderedDict] = {}
        # pending entry ledger (the PEL): rid -> (consumer, delivered_at)
        # per (stream, group) — the consumer attribution is what lets a
        # claim sweep take over a DEAD peer's entries specifically
        self._pending: Dict[Tuple[str, str],
                            Dict[str, Tuple[str, float]]] = {}
        self._hashes: Dict[str, Dict[str, str]] = {}
        self._seq = 0
        self.redeliver_after_s = redeliver_after_s

    def xadd(self, stream, record):
        with self._lock:
            self._seq += 1
            rid = f"{int(time.time() * 1000)}-{self._seq}"
            self._streams.setdefault(stream, OrderedDict())[rid] = record
            self._lock.notify_all()
            return rid

    def xadd_many(self, entries):
        with self._lock:  # one lock acquisition for the whole burst
            rids = []
            for stream, record in entries:
                self._seq += 1
                rid = f"{int(time.time() * 1000)}-{self._seq}"
                self._streams.setdefault(stream, OrderedDict())[rid] = \
                    record
                rids.append(rid)
            if rids:
                self._lock.notify_all()
            return rids

    def read_group(self, stream, group, consumer, count, block_ms=100):
        deadline = time.time() + block_ms / 1000.0
        with self._lock:
            while True:
                out = []
                s = self._streams.get(stream, OrderedDict())
                pend = self._pending.setdefault((stream, group), {})
                now = time.time()
                for rid, rec in s.items():
                    if len(out) >= count:
                        break
                    taken = pend.get(rid)
                    # undelivered, or delivered-but-unacked past the
                    # redelivery window (consumer died: at-least-once)
                    if taken is None \
                            or now - taken[1] > self.redeliver_after_s:
                        pend[rid] = (consumer, now)
                        out.append((rid, rec))
                if out or time.time() >= deadline:
                    return out
                self._lock.wait(timeout=max(deadline - time.time(), 0.001))

    def ack(self, stream, group, ids):
        with self._lock:
            s = self._streams.get(stream, OrderedDict())
            pend = self._pending.get((stream, group), {})
            for rid in ids:
                s.pop(rid, None)
                pend.pop(rid, None)

    def writeback(self, key, mapping, stream, group, ids):
        with self._lock:   # one acquisition for write + ack
            h = self._hashes.setdefault(key, {})
            added = sum(1 for f in mapping if f not in h)
            h.update(mapping)
            s = self._streams.get(stream, OrderedDict())
            pend = self._pending.get((stream, group), {})
            for rid in ids:
                s.pop(rid, None)
                pend.pop(rid, None)
            self._lock.notify_all()
            return added

    def claim_stale(self, stream, group, consumer, min_idle_ms, count):
        with self._lock:
            s = self._streams.get(stream, OrderedDict())
            pend = self._pending.setdefault((stream, group), {})
            now = time.time()
            out = []
            for rid, (_owner, delivered) in list(pend.items()):
                if len(out) >= count:
                    break
                if (now - delivered) * 1000.0 < min_idle_ms:
                    continue
                rec = s.get(rid)
                if rec is None:
                    # acked-and-trimmed elsewhere: drop the stale PEL row
                    pend.pop(rid, None)
                    continue
                pend[rid] = (consumer, now)   # idle clock restarts
                out.append((rid, rec))
            return out

    def pending_count(self, stream, group):
        with self._lock:
            return len(self._pending.get((stream, group), {}))

    def stream_depth(self, stream):
        with self._lock:
            return len(self._streams.get(stream, ()))

    def hset(self, key, field, value):
        with self._lock:
            h = self._hashes.setdefault(key, {})
            added = 0 if field in h else 1
            h[field] = value
            self._lock.notify_all()
            return added

    def hset_many(self, key, mapping):
        with self._lock:  # one lock acquisition for the whole batch
            h = self._hashes.setdefault(key, {})
            added = sum(1 for f in mapping if f not in h)
            h.update(mapping)
            self._lock.notify_all()
            return added

    def hget(self, key, field):
        with self._lock:
            return self._hashes.get(key, {}).get(field)

    def hmget(self, key, fields):
        with self._lock:
            h = self._hashes.get(key, {})
            return [h.get(field) for field in fields]

    def hgetall(self, key):
        with self._lock:
            return dict(self._hashes.get(key, {}))

    def hlen(self, key):
        with self._lock:
            return len(self._hashes.get(key, {}))

    def hdel(self, key, field):
        with self._lock:
            self._hashes.get(key, {}).pop(field, None)

    def hdel_many(self, key, fields):
        with self._lock:
            h = self._hashes.get(key, {})
            for field in fields:
                h.pop(field, None)


# ---------------------------------------------------------------------------
# TCP transport: newline-delimited JSON RPC onto a shared MemoryBroker
# ---------------------------------------------------------------------------
class _Handler(socketserver.StreamRequestHandler):
    # see _RESPHandler in redis_server.py: Nagle + delayed ACK stalls
    # small back-to-back reply writes ~40 ms each on pipelined batches
    disable_nagle_algorithm = True

    def handle(self):
        while True:
            line = self.rfile.readline()
            if not line:
                return
            try:
                req = json.loads(line)
                fn = getattr(self.server.broker, req["op"])
                result = fn(*req.get("args", []))
                resp = {"ok": True, "result": result}
            except Exception as e:  # noqa: BLE001 — serve must not die
                resp = {"ok": False, "error": f"{type(e).__name__}: {e}"}
            self.wfile.write((json.dumps(resp) + "\n").encode())
            self.wfile.flush()


class TCPBrokerServer:
    """Serve a MemoryBroker over TCP (the image has no Redis server)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 broker: Optional[MemoryBroker] = None):
        self.broker = broker or MemoryBroker()
        self._srv = socketserver.ThreadingTCPServer(
            (host, port), _Handler, bind_and_activate=True)
        self._srv.daemon_threads = True
        self._srv.broker = self.broker
        self.host, self.port = self._srv.server_address
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)

    def start(self) -> "TCPBrokerServer":
        self._thread.start()
        return self

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()


class TCPBroker(Broker):
    """Client for TCPBrokerServer; one socket per thread."""

    def __init__(self, host: str = "127.0.0.1", port: int = 6379):
        self.host, self.port = host, port
        self._local = threading.local()

    def _conn(self):
        if getattr(self._local, "sock", None) is None:
            sock = socket.create_connection((self.host, self.port), timeout=30)
            # the client half of the Nagle/delayed-ACK fix (see _Handler)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._local.sock = sock
            self._local.rfile = sock.makefile("rb")
        return self._local.sock, self._local.rfile

    def _call(self, op: str, *args):
        try:
            sock, rfile = self._conn()
            sock.sendall((json.dumps({"op": op, "args": list(args)}) + "\n")
                         .encode())
            resp = json.loads(rfile.readline())
        except Exception:
            # drop the (possibly dead) cached socket so the next call on
            # this thread reconnects instead of reusing a poisoned one
            sock = getattr(self._local, "sock", None)
            if sock is not None:
                try:
                    sock.close()
                finally:
                    self._local.sock = None
            raise
        if not resp.get("ok"):
            raise RuntimeError(f"broker error: {resp.get('error')}")
        result = resp["result"]
        if op in ("read_group", "claim_stale") and result is not None:
            result = [tuple(item) for item in result]
        return result

    def xadd(self, stream, record):
        return self._call("xadd", stream, record)

    def xadd_many(self, entries):
        # one RPC round trip for the whole burst
        return self._call("xadd_many",
                          [[stream, record] for stream, record in entries])

    def read_group(self, stream, group, consumer, count, block_ms=100):
        return self._call("read_group", stream, group, consumer, count,
                          block_ms)

    def ack(self, stream, group, ids):
        return self._call("ack", stream, group, ids)

    def claim_stale(self, stream, group, consumer, min_idle_ms, count):
        return self._call("claim_stale", stream, group, consumer,
                          min_idle_ms, count)

    def pending_count(self, stream, group):
        return self._call("pending_count", stream, group)

    def stream_depth(self, stream):
        return self._call("stream_depth", stream)

    def hset(self, key, field, value):
        return self._call("hset", key, field, value)

    def hset_many(self, key, mapping):
        # one RPC round trip for the whole batch
        return self._call("hset_many", key, mapping)

    def writeback(self, key, mapping, stream, group, ids):
        # fused write + ack: one RPC instead of two
        return self._call("writeback", key, mapping, stream, group, ids)

    def hget(self, key, field):
        return self._call("hget", key, field)

    def hmget(self, key, fields):
        return self._call("hmget", key, list(fields))

    def hgetall(self, key):
        return self._call("hgetall", key)

    def hlen(self, key):
        return self._call("hlen", key)

    def hdel(self, key, field):
        return self._call("hdel", key, field)

    def hdel_many(self, key, fields):
        return self._call("hdel_many", key, list(fields))


class RESPError(RuntimeError):
    """A Redis `-ERR ...` reply."""


class _RESPClient:
    """Minimal RESP2 client over a stdlib socket: sends command arrays,
    parses simple strings / errors / integers / bulk strings / arrays
    (everything the stream + hash commands return). Thread-safe via one
    lock per connection, matching the reference's one-Jedis-per-operator
    usage."""

    def __init__(self, host: str, port: int, timeout_s: float = 10.0):
        self._host, self._port = host, port
        self._timeout_s = timeout_s
        self._sock = None
        self._buf = None
        self._lock = threading.Lock()
        self._connect()

    def _connect(self):
        self._sock = socket.create_connection(
            (self._host, self._port), timeout=self._timeout_s)
        # a pipelined request body can span segments; Nagle would hold
        # the tail waiting on the server's delayed ACK (~40 ms) — the
        # server side sets disable_nagle_algorithm for its replies
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._buf = self._sock.makefile("rb")

    def _close_locked(self):
        """Close without taking the lock — only from inside command()."""
        try:
            if self._buf is not None:
                self._buf.close()
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        self._sock = self._buf = None

    def close(self):
        # taking the lock serializes against an in-flight command; nulling
        # _sock mid-command would raise AttributeError in the other thread
        with self._lock:
            self._close_locked()

    def command(self, *args, timeout_s: Optional[float] = None):
        """Encode `args` as a RESP array of bulk strings; return the
        decoded reply (str for simple/bulk, int, list, or None).
        `timeout_s` overrides the connection default for this command
        (None keeps the default; pass float('inf')-like large values for
        BLOCK 0). A timed-out command closes the connection — the late
        reply would otherwise desynchronize every later command."""
        out = [b"*%d\r\n" % len(args)]
        for a in args:
            data = a if isinstance(a, bytes) else str(a).encode()
            out.append(b"$%d\r\n%s\r\n" % (len(data), data))
        with self._lock:
            if self._sock is None:
                # a previous timeout/failure closed the connection —
                # reconnect so one transient Redis stall doesn't
                # permanently kill a long-running serving loop
                self._connect()
            if timeout_s is not None:
                self._sock.settimeout(timeout_s)
            try:
                self._sock.sendall(b"".join(out))
                return self._read_reply()
            except socket.timeout:
                self._close_locked()
                raise ConnectionError(
                    "redis command timed out; connection closed to avoid "
                    "reply desynchronization (next command reconnects)")
            except (ConnectionError, OSError):
                self._close_locked()
                raise
            finally:
                if timeout_s is not None and self._sock is not None:
                    try:
                        self._sock.settimeout(self._timeout_s)
                    except OSError:
                        pass

    def pipeline(self, *cmds):
        """Send several commands in ONE write and read all replies —
        RESP pipelining. One network round trip (and, against a loaded
        server host, one scheduling wakeup) instead of len(cmds). Every
        reply is read even when an earlier one is an error, keeping the
        connection synchronized; the first error then raises."""
        out = []
        for args in cmds:
            out.append(b"*%d\r\n" % len(args))
            for a in args:
                data = a if isinstance(a, bytes) else str(a).encode()
                out.append(b"$%d\r\n%s\r\n" % (len(data), data))
        with self._lock:
            if self._sock is None:
                self._connect()
            try:
                self._sock.sendall(b"".join(out))
                replies, err = [], None
                for _ in cmds:
                    try:
                        replies.append(self._read_reply())
                    except RESPError as e:
                        replies.append(e)
                        err = err or e
                if err is not None:
                    raise err
                return replies
            except socket.timeout:
                self._close_locked()
                raise ConnectionError(
                    "redis pipeline timed out; connection closed to "
                    "avoid reply desynchronization (next command "
                    "reconnects)")
            except (ConnectionError, OSError):
                self._close_locked()
                raise

    def _read_line(self) -> bytes:
        line = self._buf.readline()
        if not line.endswith(b"\r\n"):
            raise ConnectionError("redis connection closed mid-reply")
        return line[:-2]

    def _read_reply(self):
        line = self._read_line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest.decode()
        if kind == b"-":
            raise RESPError(rest.decode())
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n == -1:
                return None
            data = self._buf.read(n + 2)
            if len(data) < n + 2:
                raise ConnectionError("redis connection closed mid-bulk")
            return data[:-2].decode()
        if kind == b"*":
            n = int(rest)
            if n == -1:
                return None
            return [self._read_reply() for _ in range(n)]
        raise ValueError(f"Unsupported RESP type byte {kind!r}")


class RedisBroker(Broker):
    """Real Redis backend, reference-faithful command set
    (`FlinkRedisSource.scala:66-87`): XGROUP CREATE ... MKSTREAM, blocking
    XREADGROUP with `>`, XACK+XDEL on ack, HSET/HGET results."""

    def __init__(self, host: str = "localhost", port: int = 6379):
        self.host, self.port = host, port
        self._r = _RESPClient(host, port)
        self._groups_made = set()

    def clone(self):
        # fresh socket: a blocking XREADGROUP on this connection must not
        # serialize the clone's HSET/XACK behind its block window
        return RedisBroker(self.host, self.port)

    def close(self):
        self._r.close()

    def xadd(self, stream, record):
        return self._r.command("XADD", stream, "*", "json",
                               json.dumps(record))

    def xadd_many(self, entries):
        # ONE pipelined round trip appends the whole burst — the ingest
        # analogue of the sink's fused writeback. Entries may span
        # partition streams; Redis executes the XADDs in order, so the
        # returned ids are position-matched to the input
        entries = list(entries)
        if not entries:
            return []
        replies = self._r.pipeline(
            *(("XADD", stream, "*", "json", json.dumps(record))
              for stream, record in entries))
        return list(replies)

    def _ensure_group(self, stream, group):
        if (stream, group) in self._groups_made:
            return
        try:
            self._r.command("XGROUP", "CREATE", stream, group, "0",
                            "MKSTREAM")
        except RESPError as e:
            if "BUSYGROUP" not in str(e):
                raise
        self._groups_made.add((stream, group))

    def read_group(self, stream, group, consumer, count, block_ms=100):
        self._ensure_group(stream, group)
        if block_ms <= 0:
            # block_ms<=0 means NON-blocking here (the decode loop
            # polls between steps with live sequences seated) — omit
            # BLOCK entirely: passing "BLOCK 0" upstream means block
            # FOREVER and would wedge a live engine loop behind an
            # empty stream
            resp = self._r.command(
                "XREADGROUP", "GROUP", group, consumer, "COUNT", count,
                "STREAMS", stream, ">")
        else:
            # socket deadline must outlast the server-side BLOCK window
            resp = self._r.command(
                "XREADGROUP", "GROUP", group, consumer, "COUNT", count,
                "BLOCK", block_ms, "STREAMS", stream, ">",
                timeout_s=block_ms / 1000.0 + 10.0)
        out = []
        for _, entries in resp or []:
            for rid, fields in entries:
                kv = dict(zip(fields[::2], fields[1::2]))
                out.append((rid, json.loads(kv["json"])))
        return out

    def ack(self, stream, group, ids):
        if ids:
            self._r.command("XACK", stream, group, *ids)
            self._r.command("XDEL", stream, *ids)

    def claim_stale(self, stream, group, consumer, min_idle_ms, count):
        """XAUTOCLAIM (Redis >= 6.2): atomically scan the group's PEL
        and claim entries idle past `min_idle_ms` for this consumer.
        Reply is [next-cursor, entries] (7.0 appends a deleted-ids
        array; ignored). Entries whose record was trimmed come back
        nil and are skipped."""
        self._ensure_group(stream, group)
        resp = self._r.command(
            "XAUTOCLAIM", stream, group, consumer, int(min_idle_ms),
            "0-0", "COUNT", count)
        entries = resp[1] if isinstance(resp, list) and len(resp) > 1 \
            else []
        out = []
        for item in entries or []:
            if not item:
                continue
            rid, fields = item
            kv = dict(zip(fields[::2], fields[1::2]))
            if "json" in kv:
                out.append((rid, json.loads(kv["json"])))
        return out

    def pending_count(self, stream, group):
        self._ensure_group(stream, group)
        # XPENDING summary form: [count, min-id, max-id, consumers]
        resp = self._r.command("XPENDING", stream, group)
        return int(resp[0]) if isinstance(resp, list) and resp else 0

    def stream_depth(self, stream):
        return int(self._r.command("XLEN", stream) or 0)

    def hset(self, key, field, value):
        return self._r.command("HSET", key, field, value)

    def hset_many(self, key, mapping):
        if not mapping:
            return 0
        # variadic HSET (Redis >= 4): one command, one round trip;
        # the integer reply counts NEW fields (overwrites excluded)
        flat = []
        for field, value in mapping.items():
            flat.extend((field, value))
        return self._r.command("HSET", key, *flat)

    def writeback(self, key, mapping, stream, group, ids):
        # ONE pipelined round trip commits the whole batch: HSET the
        # results, XACK + XDEL the stream entries. The sink's commit
        # latency drops from 3 RTTs to 1 — on a busy host each RTT also
        # costs a server-thread scheduling wakeup, which is what caps a
        # fleet's per-engine sink throughput
        cmds = []
        if mapping:
            flat = []
            for field, value in mapping.items():
                flat.extend((field, value))
            cmds.append(("HSET", key, *flat))
        if ids:
            self._ensure_group(stream, group)
            cmds.append(("XACK", stream, group, *ids))
            cmds.append(("XDEL", stream, *ids))
        if not cmds:
            return 0
        replies = self._r.pipeline(*cmds)
        return int(replies[0]) if mapping else 0

    def hget(self, key, field):
        return self._r.command("HGET", key, field)

    def hmget(self, key, fields):
        fields = list(fields)
        if not fields:
            return []
        return list(self._r.command("HMGET", key, *fields) or
                    [None] * len(fields))

    def hgetall(self, key):
        flat = self._r.command("HGETALL", key) or []
        return dict(zip(flat[::2], flat[1::2]))

    def hlen(self, key):
        return int(self._r.command("HLEN", key) or 0)

    def hdel(self, key, field):
        self._r.command("HDEL", key, field)

    def hdel_many(self, key, fields):
        fields = list(fields)
        if fields:
            self._r.command("HDEL", key, *fields)


def connect_broker(url: Optional[str] = None) -> Broker:
    """"memory", "tcp://host:port", or "redis://host:port"; default memory."""
    if url in (None, "", "memory"):
        return MemoryBroker()
    if url.startswith("tcp://"):
        host, _, port = url[6:].partition(":")
        return TCPBroker(host or "127.0.0.1", int(port or 6379))
    if url.startswith("redis://"):
        host, _, port = url[8:].partition(":")
        return RedisBroker(host or "localhost", int(port or 6379))
    raise ValueError(f"Unsupported broker url: {url}")


def new_consumer_name() -> str:
    return f"consumer-{uuid.uuid4().hex[:8]}"
