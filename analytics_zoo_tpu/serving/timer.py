"""Per-stage serving timers (`serving/engine/Timer.scala:33-100`): running
min/max/avg and top-N slowest, printed per batch window; plus a metrics
snapshot for the HTTP `/metrics` route (`http/FrontEndApp.scala:131,241`).

Percentiles come from a streaming log-bucketed histogram (O(1) memory,
O(1) record): sample durations land in geometrically-spaced buckets
spanning 1 µs .. ~5 min, and p50/p95/p99 interpolate within the bucket
that crosses the target rank. Relative error is bounded by the bucket
growth factor (~9%), which is plenty for tail-latency dashboards."""

from __future__ import annotations

import heapq
import math
import threading
import time
from typing import Dict, List

# Histogram geometry: bucket i covers [BASE*GROWTH^i, BASE*GROWTH^(i+1)).
# BASE=1µs, GROWTH=1.2 → 107 buckets reach ~300 s; under/overflows clamp.
_HIST_BASE = 1e-6
_HIST_GROWTH = 1.2
_HIST_LOG_GROWTH = math.log(_HIST_GROWTH)
_HIST_BUCKETS = 107


def _bucket_index(seconds: float) -> int:
    if seconds <= _HIST_BASE:
        return 0
    i = int(math.log(seconds / _HIST_BASE) / _HIST_LOG_GROWTH)
    return min(i, _HIST_BUCKETS - 1)


class Timer:
    def __init__(self, name: str, top_n: int = 10):
        self.name = name
        self.top_n = top_n
        self._lock = threading.Lock()
        self.reset()

    def reset(self):
        with getattr(self, "_lock", threading.Lock()):
            self.count = 0
            self.total = 0.0
            self.min = float("inf")
            self.max = 0.0
            self._top: List[float] = []
            self._hist = [0] * _HIST_BUCKETS

    def record(self, seconds: float):
        with self._lock:
            self.count += 1
            self.total += seconds
            self.min = min(self.min, seconds)
            self.max = max(self.max, seconds)
            self._hist[_bucket_index(seconds)] += 1
            if len(self._top) < self.top_n:
                heapq.heappush(self._top, seconds)
            else:
                heapq.heappushpop(self._top, seconds)

    def _percentile_locked(self, q: float) -> float:
        """Histogram percentile: find the bucket crossing rank q*count and
        interpolate linearly inside it; clamp to the observed min/max so
        bucket-edge estimates never exceed reality."""
        if not self.count:
            return 0.0
        target = q * self.count
        seen = 0
        for i, c in enumerate(self._hist):
            if not c:
                continue
            if seen + c >= target:
                lo = _HIST_BASE * (_HIST_GROWTH ** i)
                hi = lo * _HIST_GROWTH
                frac = (target - seen) / c
                est = lo + (hi - lo) * frac
                return min(max(est, self.min), self.max)
            seen += c
        return self.max

    def timing(self):
        """Context manager: `with timer.timing(): ...`"""
        return _Span(self)

    @property
    def avg(self) -> float:
        return self.total / self.count if self.count else 0.0

    def percentile(self, q: float) -> float:
        """Seconds at quantile q in [0, 1] from the streaming histogram."""
        with self._lock:
            return self._percentile_locked(q)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {
                "name": self.name,
                "count": self.count,
                "avg_ms": round(self.avg * 1e3, 3),
                "min_ms": round(self.min * 1e3, 3) if self.count else 0.0,
                "max_ms": round(self.max * 1e3, 3),
                "p50_ms": round(self._percentile_locked(0.50) * 1e3, 3),
                "p95_ms": round(self._percentile_locked(0.95) * 1e3, 3),
                "p99_ms": round(self._percentile_locked(0.99) * 1e3, 3),
                "top": sorted((round(t * 1e3, 3) for t in self._top),
                              reverse=True),
            }

    def __repr__(self):
        s = self.snapshot()
        return (f"Timer({self.name}: n={s['count']} avg={s['avg_ms']}ms "
                f"min={s['min_ms']}ms max={s['max_ms']}ms)")


class _Span:
    def __init__(self, timer: Timer):
        self.timer = timer

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.timer.record(time.perf_counter() - self.t0)
        return False
