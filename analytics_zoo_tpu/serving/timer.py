"""Per-stage serving timers (`serving/engine/Timer.scala:33-100`): running
min/max/avg and top-N slowest, printed per batch window; plus a metrics
snapshot for the HTTP `/metrics` route (`http/FrontEndApp.scala:131,241`)."""

from __future__ import annotations

import heapq
import threading
import time
from typing import Dict, List


class Timer:
    def __init__(self, name: str, top_n: int = 10):
        self.name = name
        self.top_n = top_n
        self._lock = threading.Lock()
        self.reset()

    def reset(self):
        with getattr(self, "_lock", threading.Lock()):
            self.count = 0
            self.total = 0.0
            self.min = float("inf")
            self.max = 0.0
            self._top: List[float] = []

    def record(self, seconds: float):
        with self._lock:
            self.count += 1
            self.total += seconds
            self.min = min(self.min, seconds)
            self.max = max(self.max, seconds)
            if len(self._top) < self.top_n:
                heapq.heappush(self._top, seconds)
            else:
                heapq.heappushpop(self._top, seconds)

    def timing(self):
        """Context manager: `with timer.timing(): ...`"""
        return _Span(self)

    @property
    def avg(self) -> float:
        return self.total / self.count if self.count else 0.0

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {
                "name": self.name,
                "count": self.count,
                "avg_ms": round(self.avg * 1e3, 3),
                "min_ms": round(self.min * 1e3, 3) if self.count else 0.0,
                "max_ms": round(self.max * 1e3, 3),
                "top": sorted((round(t * 1e3, 3) for t in self._top),
                              reverse=True),
            }

    def __repr__(self):
        s = self.snapshot()
        return (f"Timer({self.name}: n={s['count']} avg={s['avg_ms']}ms "
                f"min={s['min_ms']}ms max={s['max_ms']}ms)")


class _Span:
    def __init__(self, timer: Timer):
        self.timer = timer

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.timer.record(time.perf_counter() - self.t0)
        return False
