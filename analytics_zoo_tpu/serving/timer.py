"""Per-stage serving timers (`serving/engine/Timer.scala:33-100`): running
min/max/avg and top-N slowest, printed per batch window; plus a metrics
snapshot for the HTTP `/metrics` route (`http/FrontEndApp.scala:131,241`).

Percentiles come from a streaming log-bucketed histogram (O(1) memory,
O(1) record): sample durations land in geometrically-spaced buckets
spanning 1 µs .. ~5 min, and p50/p95/p99 interpolate within the bucket
that crosses the target rank. Relative error is bounded by the bucket
growth factor (~9%), which is plenty for tail-latency dashboards.

The histogram itself lives in `observability/registry.py` (`LogHistogram`
— this is where it was proven, then generalized); Timer keeps only the
top-N heap and the lock on top. Observers (`add_observer`) let a Timer
mirror every recorded duration into a registry `Histogram`, which is how
the serving pipeline's per-stage timers feed the process-wide
`MetricsRegistry` without double bookkeeping at the call sites.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Callable, Dict, List, Optional

from analytics_zoo_tpu.observability.registry import LogHistogram

# Timer records SECONDS: base=1µs, growth=1.2 → 107 buckets reach ~300 s.
_HIST_BASE = 1e-6
_HIST_GROWTH = 1.2
_HIST_BUCKETS = 107


class Timer:
    def __init__(self, name: str, top_n: int = 10,
                 observer: Optional[Callable[[float], None]] = None):
        self.name = name
        self.top_n = top_n
        # the lock MUST exist before reset() runs: the old getattr
        # fallback locked a throwaway Lock on first call, leaving that
        # reset racy against a concurrent record()
        self._lock = threading.Lock()
        self._observers: List[Callable[[float], None]] = (
            [observer] if observer is not None else [])
        self.reset()

    def add_observer(self, fn: Callable[[float], None]) -> "Timer":
        """Mirror every recorded duration (seconds) into `fn` — e.g. a
        registry histogram's observe. Called outside this Timer's lock."""
        self._observers.append(fn)
        return self

    def reset(self):
        with self._lock:
            self._top: List[float] = []
            self._hist = LogHistogram(base=_HIST_BASE, growth=_HIST_GROWTH,
                                      n_buckets=_HIST_BUCKETS)

    def record(self, seconds: float):
        with self._lock:
            self._hist.observe(seconds)
            if len(self._top) < self.top_n:
                heapq.heappush(self._top, seconds)
            else:
                heapq.heappushpop(self._top, seconds)
        for fn in self._observers:
            fn(seconds)

    def timing(self):
        """Context manager: `with timer.timing(): ...`"""
        return _Span(self)

    # -- accessors (all lock-guarded reads of the shared histogram) --------
    @property
    def count(self) -> int:
        with self._lock:
            return self._hist.count

    @property
    def total(self) -> float:
        with self._lock:
            return self._hist.total

    @property
    def min(self) -> float:
        with self._lock:
            return self._hist.vmin

    @property
    def max(self) -> float:
        with self._lock:
            return self._hist.vmax

    @property
    def avg(self) -> float:
        with self._lock:
            return self._hist.mean

    def percentile(self, q: float) -> float:
        """Seconds at quantile q in [0, 1] from the streaming histogram."""
        with self._lock:
            return self._hist.percentile(q)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            h = self._hist
            return {
                "name": self.name,
                "count": h.count,
                "avg_ms": round(h.mean * 1e3, 3),
                "min_ms": round(h.vmin * 1e3, 3) if h.count else 0.0,
                "max_ms": round(h.vmax * 1e3, 3),
                "p50_ms": round(h.percentile(0.50) * 1e3, 3),
                "p95_ms": round(h.percentile(0.95) * 1e3, 3),
                "p99_ms": round(h.percentile(0.99) * 1e3, 3),
                "top": sorted((round(t * 1e3, 3) for t in self._top),
                              reverse=True),
            }

    def __repr__(self):
        s = self.snapshot()
        return (f"Timer({self.name}: n={s['count']} avg={s['avg_ms']}ms "
                f"min={s['min_ms']}ms max={s['max_ms']}ms)")


class _Span:
    def __init__(self, timer: Timer):
        self.timer = timer

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.timer.record(time.perf_counter() - self.t0)
        return False
