"""Continuous-batching decode engine — pooled KV slots, per-step planning.

`ClusterServing` serves fixed-shape forwards: plan ONE dispatch, run it,
write it back. Autoregressive generation breaks that shape — a request
is now a prompt plus up to `max_new` dependent steps, and padding every
sequence to the longest (then restarting the batch when all finish) is
the pad-to-max baseline vLLM/Orca showed 2-10x worse than iteration-
level scheduling. This module is that discipline on the existing rails:

- ``KVSlotPool`` — the KV cache is pre-allocated ONCE as
  ``[slots, heads, max_kv_len, head_dim]`` device buffers (one k/v pair
  per layer, built by the model's ``init_kv``). A sequence leases a
  slot row at admission and releases it at its final token — no
  allocation, no reshape, no copy ever happens on the request path.
  The ``serving_kv_slots_in_use`` gauge IS the admission signal: free
  slots are the only capacity that matters in decode mode.
- ``DecodeScheduler`` — generalizes the adaptive batch controller's
  "plan one dispatch" to "plan EVERY step": at each step boundary
  finished sequences free slots, queued prompts join (continuous
  batching), and prefill admissions are budgeted under the same
  deadline math — a prefill stalls every in-flight sequence for its
  duration, so the scheduler admits only as many prompts per step as
  the deadline budget covers (per-bucket EWMA costs, the PR 11 model,
  one per phase).
- ``DecodeServing`` — the engine loop: intake from the serving stream
  (same record protocol — field ``t`` is the int32 prompt, plus
  ``max_new``/``eos``/``stream``), prefill admitted prompts one at a
  time, then ONE batched decode step for every leased slot at the kv
  bucket covering the longest live sequence. Steps run on the AOT
  executables `warmup_generative` pre-compiled — 0 XLA compiles on the
  request path, the same contract the forward path enforces.

Token streaming rides the existing result hash: each generated token of
a ``stream``-flagged request is written as a row ``<uri>#<index>``
(JSON ``{"i", "t", "ms"}``), and the FINAL row is the plain ``uri``
field holding the standard b64 ndarray of all generated ids (plus a
``gen`` summary) — so the non-streaming client path (exact-uri HMGET)
is oblivious to the extra rows, completion is the presence of the exact
uri field, and `OutputQueue.stream_tokens` polls rows incrementally.
Final rows commit through the fused ``writeback`` (HSET+ACK) like the
forward sink; a step's token rows and finals share ONE broker
interaction (`_flush`).

PAGED MODE (ISSUE 19). With ``paged=True`` the stripe pool is replaced
by `KVBlockPool` + per-sequence block tables (`serving/paged_kv.py`):
``slots`` becomes the fixed DECODE LANE count (the static step batch
shape) while capacity is bounded by live tokens in the block pool —
short sequences no longer reserve `max_kv_len` stripes. A `PrefixCache`
lets prompts sharing an instruction prefix adopt cached blocks copy-
free (skipping that span of prefill), and `prefill_chunk` splits long
prompts into bounded chunks interleaved between decode steps so one
giant prompt can't stall every live sequence for its full prefill
(`plan_paged_step` budgets chunks and admissions under the same
deadline math). Greedy outputs are bitwise-identical to the contiguous
path — the paged programs run the same numeric ops over relocated
bytes — and the request path still performs 0 XLA compiles
(`warmup_generative_paged` pre-compiles per (chunk bucket, kv bucket)).

CRASH SAFETY (ISSUE 20). Greedy decode is deterministic and every
streamed token is durably HSET per step, which makes a generative
record recoverable the same way the forward plane's records are:

- **Decode-session recovery** — the engine runs the PR 10/15 claim
  sweep over its own stream: a dead peer's pending records are claimed
  after `claim_min_idle_s`, the tokens it already committed are read
  back from the `uri#NNNNNN` rows, and the sequence re-boards with
  prompt ⊕ emitted-so-far as its prefill context — continuing from
  token i+1 with NO re-emit (`_Sequence.presented` suppresses every
  already-durable row), so surviving-engine output is bitwise-identical
  to an uninterrupted run. In paged mode the resume prefill rides the
  prefix cache and chunked prefill (warmed for every (chunk, ctx)
  bucket pair), so resume performs 0 compiles and often 0 KV copies.
- **KV-pressure preemption** — when block reservation fails even after
  cache eviction, the youngest/lowest-tier live sequence is preempted
  back to the waiting queue (blocks released, its full context
  published to the prefix cache so re-admission re-prefills copy-free)
  instead of wedging admission; an anti-thrash bound (`preempt_max`)
  guarantees a sequence preempted N times completes before any new
  admission.
- **Writeback resilience** — the engine broker wears the PR 5
  `ResilientBroker` breaker, and every flush goes through a bounded
  pending buffer: a broker blip buffers token rows (oldest-step shed
  per sequence keeps the final blob authoritative) while decode keeps
  stepping; the buffer drains on recovery. Intake failures pace on the
  stop event — a dead broker never hot-spins or kills the loop.
- **Watchdog** — `max_seq_wall_s` aborts a wedged sequence with an
  explicit NaN-degrade final (answered failure; slot/blocks released)
  so one stuck record can't hold KV forever.

Chaos tests drive these through `common.faults` points
``decode.prefill`` / ``decode.step`` / ``decode.writeback``.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import threading
import time
import uuid
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from analytics_zoo_tpu.common import faults
from analytics_zoo_tpu.serving.broker import (Broker, connect_broker,
                                              encode_ndarray)
from analytics_zoo_tpu.serving.breaker import ResilientBroker
from analytics_zoo_tpu.serving.client import STREAM
from analytics_zoo_tpu.serving.elastic import BucketCostModel
from analytics_zoo_tpu.serving.inference_model import (InferenceModel,
                                                       _next_bucket)
from analytics_zoo_tpu.serving.paged_kv import KVBlockPool, PrefixCache

log = logging.getLogger("analytics_zoo_tpu.serving.decode")

GROUP = "serving_group"


def _pow2_ladder(lo: int, hi: int) -> List[int]:
    out, b = [], 1
    while b < lo:
        b *= 2
    while b < hi:
        out.append(b)
        b *= 2
    out.append(hi)
    return out


def token_row_field(uri: str, index: int) -> str:
    """Result-hash field name of one streamed token row. '#' never
    appears in generated uris (uuid4 / frontend request ids), so the
    exact-uri poll can never collide with a token row."""
    return f"{uri}#{index:06d}"


class KVSlotPool:
    """Fixed pool of KV slots over ONE pre-allocated device buffer set.

    The pytree in ``self.kv`` is threaded functionally through every
    prefill/step call (the engine rebinds it to each call's returned
    tree); the POOL object only tracks which rows are leased. Freed
    rows are not zeroed — attention masks by live length and the next
    prefill into the slot overwrites from position 0."""

    def __init__(self, init_kv: Callable[[int, int], Any], slots: int,
                 max_kv_len: int, registry=None,
                 labels: Optional[Dict[str, str]] = None):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        self.slots = int(slots)
        self.max_kv_len = int(max_kv_len)
        self.kv = init_kv(self.slots, self.max_kv_len)
        self._free = list(range(self.slots - 1, -1, -1))   # lease 0 first
        self._lock = threading.Lock()
        self._labels = dict(labels or {})
        if registry is None:
            from analytics_zoo_tpu.observability.registry import get_registry
            registry = get_registry()
        self._gauge = registry.gauge(
            "serving_kv_slots_in_use",
            "KV-cache slots currently leased to in-flight sequences "
            "(out of the engine's fixed slot pool) — the decode "
            "engine's admission signal")
        self._gauge.set(0.0, **self._labels)

    def lease(self) -> Optional[int]:
        with self._lock:
            if not self._free:
                return None
            slot = self._free.pop()
            self._gauge.set(self.slots - len(self._free), **self._labels)
            return slot

    def release(self, slot: int) -> None:
        with self._lock:
            if slot in self._free or not 0 <= slot < self.slots:
                raise ValueError(f"release of unleased slot {slot}")
            self._free.append(slot)
            self._gauge.set(self.slots - len(self._free), **self._labels)

    @property
    def in_use(self) -> int:
        with self._lock:
            return self.slots - len(self._free)

    @property
    def free_count(self) -> int:
        with self._lock:
            return len(self._free)


@dataclasses.dataclass
class StepPlan:
    """One step's plan: how many waiting prompts board now, and the kv
    bucket the step executable runs at."""
    admit: int
    kv_bucket: int
    budget_ms: Optional[float]
    reason: str


@dataclasses.dataclass
class PagedStepPlan:
    """One PAGED step's plan: how many mid-prefill sequences advance one
    chunk, how many waiting prompts board (and run their first chunk),
    and the kv bucket of the decode step."""
    admit: int
    chunks: int
    kv_bucket: int
    budget_ms: Optional[float]
    reason: str


class DecodeScheduler:
    """Iteration-level planner — `AdaptiveBatchController` generalized
    from "plan one dispatch" to "plan each decode step".

    Two per-bucket EWMA cost models (the PR 11 `BucketCostModel`, one
    labelled phase each) track what a decode step at kv bucket B and a
    prefill at prompt bucket P actually cost on this host. With a
    `deadline_ms`, admissions are budgeted: every prefill delays every
    in-flight sequence's next token by its full cost, so the scheduler
    admits prompts only while (step cost + admitted prefill costs)
    stays inside the deadline — EXCEPT when no sequence is in flight,
    where there is nothing to stall and the pool is the only limit.
    Unknown costs (cold buckets) admit optimistically; the EWMA learns
    from the very first observed step."""

    def __init__(self, kv_buckets: Sequence[int],
                 prompt_buckets: Sequence[int],
                 registry=None, labels: Optional[Dict[str, str]] = None,
                 deadline_ms: Optional[float] = None,
                 margin_ms: float = 2.0, alpha: float = 0.2,
                 max_prefills_per_step: Optional[int] = None,
                 chunk_buckets: Optional[Sequence[int]] = None):
        labels = dict(labels or {})
        self.kv_buckets = sorted(int(b) for b in kv_buckets)
        self.prompt_buckets = sorted(int(b) for b in prompt_buckets)
        self.chunk_buckets = sorted(int(b) for b in chunk_buckets) \
            if chunk_buckets else list(self.prompt_buckets)
        self.deadline_ms = deadline_ms
        self.margin_ms = float(margin_ms)
        self.max_prefills_per_step = max_prefills_per_step
        self.step_cost = BucketCostModel(
            self.kv_buckets, registry, alpha=alpha,
            labels={**labels, "phase": "decode_step"})
        self.prefill_cost = BucketCostModel(
            sorted(set(self.prompt_buckets) | set(self.chunk_buckets)),
            registry, alpha=alpha,
            labels={**labels, "phase": "prefill"})

    def prompt_bucket(self, n: int) -> int:
        return _next_bucket(n, self.prompt_buckets)

    def chunk_bucket(self, n: int) -> int:
        return _next_bucket(n, self.chunk_buckets)

    def kv_bucket_for(self, needed: int) -> int:
        return _next_bucket(needed, self.kv_buckets)

    def plan_step(self, waiting_prompt_lens: Sequence[int],
                  free_slots: int, active_lengths: Sequence[int]
                  ) -> StepPlan:
        """`waiting_prompt_lens`: prompt length per queued request, in
        queue order. `active_lengths`: live KV length (pos + 1 of the
        NEXT step) per in-flight sequence."""
        cap = min(len(waiting_prompt_lens), int(free_slots))
        if self.max_prefills_per_step is not None:
            cap = min(cap, int(self.max_prefills_per_step))
        needed = max(active_lengths) if active_lengths else 1
        budget = None
        reason = "free-slots" if cap else (
            "pool-full" if waiting_prompt_lens else "no-waiting")
        admit = cap
        if cap and active_lengths and self.deadline_ms:
            bucket = self.kv_bucket_for(needed)
            step_ms = self.step_cost.cost_ms(bucket) or 0.0
            budget = self.deadline_ms - self.margin_ms - step_ms
            admit, spent = 0, 0.0
            for n in waiting_prompt_lens[:cap]:
                pb = self.prompt_bucket(n)
                c = self.prefill_cost.cost_ms(pb)
                spent += c if c is not None else 0.0
                if admit and spent > budget:
                    break
                admit += 1
            if admit < cap:
                reason = "deadline"
        for n in waiting_prompt_lens[:admit]:
            needed = max(needed, n + 1)
        return StepPlan(admit=admit,
                        kv_bucket=self.kv_bucket_for(needed),
                        budget_ms=budget, reason=reason)

    def plan_paged_step(self, waiting_prompt_lens: Sequence[int],
                        free_lanes: int,
                        prefilling_remaining: Sequence[int],
                        active_lengths: Sequence[int],
                        chunk_cap: int) -> PagedStepPlan:
        """The paged generalization of `plan_step`: prefill work is now
        CHUNKS (each `<= chunk_cap` tokens), and sequences already mid-
        prefill are budgeted BEFORE new admissions — a half-fed prompt
        holds blocks and a lane, so starving it in favor of fresh
        arrivals only grows held-but-idle memory. At least one chunk
        always advances per step when any prefill is pending (the
        starvation guard); the deadline budget trims everything beyond
        that, exactly like the contiguous planner."""
        cap = min(len(waiting_prompt_lens), int(free_lanes))
        total_cap = len(prefilling_remaining) + cap
        if self.max_prefills_per_step is not None:
            total_cap = min(total_cap,
                            max(1, int(self.max_prefills_per_step)))
        chunks = min(len(prefilling_remaining), total_cap)
        admit = min(cap, total_cap - chunks)
        needed = max(active_lengths) if active_lengths else 1
        budget = None
        reason = "free-lanes" if (admit or chunks) else (
            "pool-full" if waiting_prompt_lens else "no-waiting")
        if (chunks or admit) and active_lengths and self.deadline_ms:
            bucket = self.kv_bucket_for(needed)
            step_ms = self.step_cost.cost_ms(bucket) or 0.0
            budget = self.deadline_ms - self.margin_ms - step_ms
            spent, n_chunks, n_admit = 0.0, 0, 0
            for rem in prefilling_remaining[:chunks]:
                cb = self.chunk_bucket(min(int(rem), int(chunk_cap)))
                c = self.prefill_cost.cost_ms(cb)
                spent += c if c is not None else 0.0
                if n_chunks and spent > budget:
                    break
                n_chunks += 1
            for n in waiting_prompt_lens[:admit]:
                cb = self.chunk_bucket(min(int(n), int(chunk_cap)))
                c = self.prefill_cost.cost_ms(cb)
                spent += c if c is not None else 0.0
                if (n_chunks or n_admit) and spent > budget:
                    break
                n_admit += 1
            if n_chunks < chunks or n_admit < admit:
                reason = "deadline"
            chunks, admit = n_chunks, n_admit
        for n in waiting_prompt_lens[:admit]:
            needed = max(needed, int(n) + 1)
        return PagedStepPlan(admit=admit, chunks=chunks,
                             kv_bucket=self.kv_bucket_for(needed),
                             budget_ms=budget, reason=reason)

    def observe_step(self, kv_bucket: int, ms: float) -> None:
        self.step_cost.observe(kv_bucket, ms)

    def observe_prefill(self, prompt_bucket: int, ms: float) -> None:
        self.prefill_cost.observe(prompt_bucket, ms)


@dataclasses.dataclass
class _Sequence:
    uri: str
    rid: str                       # stream record id (acked at finish)
    prompt: np.ndarray             # int32 prompt ids
    max_new: int
    eos: Optional[int]
    stream: bool
    t_enqueue: float               # perf_counter at intake
    slot: int = -1
    pos: int = 0                   # live KV length
    gen: List[int] = dataclasses.field(default_factory=list)
    t_last: float = 0.0
    rows: int = 0                  # token rows written so far
    ttft_ms: Optional[float] = None
    finish: str = ""
    # paged-mode state (slot doubles as the decode LANE)
    blocks: List[int] = dataclasses.field(default_factory=list)
    cached: int = 0                # prompt tokens adopted from the cache
    filled: int = 0                # prompt tokens already in KV
    # crash-safety state (ISSUE 20)
    tier: Optional[str] = None     # priority class (preemption ranking)
    presented: int = 0             # tokens already durable from a dead
                                   # peer — indices below this never
                                   # re-emit (no rows, no metrics)
    preempts: int = 0              # times preempted (anti-thrash bound)
    resumed: bool = False          # boarded via the claim sweep

    def ctx_len(self) -> int:
        """Prefill-context length: the prompt plus every token already
        generated (resume/preempt re-admission re-prefills both)."""
        return int(self.prompt.size) + len(self.gen)

    def context(self) -> np.ndarray:
        if not self.gen:
            return self.prompt
        return np.concatenate(
            [self.prompt, np.asarray(self.gen, np.int32)])


class DecodeServing:
    """The decode-mode engine. The model must already be
    `load_generative()`-ed and `warmup_generative()`-ed with the SAME
    slots/max_kv_len/bucket ladders — the engine never compiles."""

    def __init__(self, model: InferenceModel,
                 init_kv: Callable[[int, int], Any],
                 broker: Optional[Broker] = None,
                 stream: str = STREAM,
                 slots: int = 8, max_kv_len: int = 128,
                 kv_buckets: Optional[Sequence[int]] = None,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 max_new_default: int = 32,
                 eos_id: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 max_prefills_per_step: Optional[int] = None,
                 max_waiting: int = 256,
                 engine_id: Optional[str] = None,
                 registry=None,
                 idle_block_ms: int = 50,
                 drain_timeout_s: float = 10.0,
                 paged: bool = False,
                 init_kv_blocks: Optional[Callable[[int, int], Any]] = None,
                 block_len: int = 16,
                 kv_blocks: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 prefix_cache: bool = True,
                 prefix_cache_blocks: Optional[int] = None,
                 chunk_buckets: Optional[Sequence[int]] = None,
                 claim_min_idle_s: Optional[float] = None,
                 claim_interval_s: float = 5.0,
                 max_seq_wall_s: Optional[float] = None,
                 preempt_max: int = 3,
                 writeback_buffer_rows: int = 512,
                 heartbeat_interval_s: Optional[float] = None,
                 resilient: bool = True):
        self.model = model
        if registry is None:
            from analytics_zoo_tpu.observability.registry import get_registry
            registry = get_registry()
        inner = broker if isinstance(broker, Broker) \
            else connect_broker(broker)
        if resilient and not isinstance(inner, ResilientBroker):
            # the PR 5 breaker discipline: a broker blip fast-fails
            # instead of stalling every live sequence's next token
            inner = ResilientBroker(inner, role="decode",
                                    registry=registry)
        self.broker = inner
        self.stream = stream
        self.result_key = f"result:{stream}"
        self.max_kv_len = int(max_kv_len)
        self.kv_buckets = sorted(kv_buckets) if kv_buckets \
            else _pow2_ladder(8, self.max_kv_len)
        self.prompt_buckets = sorted(prompt_buckets) if prompt_buckets \
            else _pow2_ladder(4, max(4, self.max_kv_len // 2))
        self.max_new_default = int(max_new_default)
        self.eos_id = eos_id
        self.max_waiting = int(max_waiting)
        self.engine_id = engine_id or f"decode-{uuid.uuid4().hex[:8]}"
        self.consumer = self.engine_id
        self.idle_block_ms = int(idle_block_ms)
        self.drain_timeout_s = float(drain_timeout_s)
        self.claim_min_idle_s = None if claim_min_idle_s is None \
            else float(claim_min_idle_s)
        self.claim_interval_s = float(claim_interval_s)
        self.max_seq_wall_s = None if max_seq_wall_s is None \
            else float(max_seq_wall_s)
        self.preempt_max = max(0, int(preempt_max))
        self.writeback_buffer_rows = max(1, int(writeback_buffer_rows))
        self.heartbeat_interval_s = heartbeat_interval_s
        self._heartbeat = None
        self.registry = registry
        labels = {"engine": self.engine_id}
        self.paged = bool(paged)
        self.block_len = int(block_len)
        self.prefill_chunk = int(prefill_chunk) if prefill_chunk else None
        if self.paged:
            if init_kv_blocks is None:
                raise ValueError("paged mode needs init_kv_blocks")
            if self.max_kv_len % self.block_len:
                raise ValueError(
                    f"max_kv_len {self.max_kv_len} not a multiple of "
                    f"block_len {self.block_len}")
            bad = [b for b in self.kv_buckets if b % self.block_len]
            if bad:
                raise ValueError(
                    f"kv buckets {bad} not multiples of block_len "
                    f"{self.block_len}")
            self.table_len = self.max_kv_len // self.block_len
            # default: byte-parity with the stripe pool it replaces
            # (same KV bytes reachable, + the scratch block)
            self.kv_blocks = int(kv_blocks) if kv_blocks else (
                int(slots) * self.table_len + 1)
            self.lanes = int(slots)
            self._free_lanes = list(range(self.lanes - 1, -1, -1))
            self.pool = None
            self.block_pool = KVBlockPool(
                init_kv_blocks, self.kv_blocks, self.block_len,
                registry=registry, labels=labels)
            self.prefix_cache = PrefixCache(
                self.block_pool, registry=registry, labels=labels,
                max_blocks=prefix_cache_blocks) if prefix_cache else None
            if chunk_buckets:
                self.chunk_buckets = sorted(int(b) for b in chunk_buckets)
            elif self.prefill_chunk:
                self.chunk_buckets = [
                    b for b in self.prompt_buckets
                    if b <= self.prefill_chunk] or [self.prompt_buckets[0]]
            else:
                self.chunk_buckets = list(self.prompt_buckets)
            # a chunk can never exceed the ladder's top bucket
            self.chunk_cap = min(self.prefill_chunk or
                                 self.chunk_buckets[-1],
                                 self.chunk_buckets[-1])
        else:
            self.pool = KVSlotPool(init_kv, slots, self.max_kv_len,
                                   registry=registry, labels=labels)
            self.block_pool = None
            self.prefix_cache = None
            self.chunk_buckets = list(self.prompt_buckets)
            self.chunk_cap = self.chunk_buckets[-1]
        self.scheduler = DecodeScheduler(
            self.kv_buckets, self.prompt_buckets, registry=registry,
            labels=labels, deadline_ms=deadline_ms,
            max_prefills_per_step=max_prefills_per_step,
            chunk_buckets=self.chunk_buckets)
        self._chunks_total = registry.counter(
            "serving_prefill_chunks_total",
            "prefill chunks executed by the paged decode engine (a "
            "prompt split across N chunks counts N) — chunking is what "
            "bounds ITL while long prompts join")
        self._tokens_total = registry.counter(
            "serving_tokens_total",
            "generated tokens written back by the decode engine")
        self._ttft_hist = registry.histogram(
            "serving_ttft_ms",
            "time to first token: record enqueue to the first generated "
            "token's writeback (prefill queue + prefill + first argmax) "
            "— the generative SLO's latency input")
        self._itl_hist = registry.histogram(
            "serving_itl_ms",
            "inter-token latency between consecutive generated tokens "
            "of one sequence — the streaming smoothness SLO input")
        self._resumes_total = registry.counter(
            "serving_decode_resumes_total",
            "generative decode sessions resumed from a dead peer's "
            "durable token rows (claim sweep + deterministic greedy "
            "re-prefill of prompt + emitted-so-far)")
        self._preempt_total = registry.counter(
            "serving_preemptions_total",
            "live sequences preempted back to the waiting queue under "
            "KV block pressure — blocks released, context published to "
            "the prefix cache so re-admission re-prefills copy-free")
        self._aborts_total = registry.counter(
            "serving_sequence_aborts_total",
            "sequences force-finished by the engine, by reason: wall = "
            "per-sequence watchdog expired (NaN-degrade final), "
            "blocks-full = KV pool exhausted beyond preemption's reach "
            "(answered with the tokens generated so far)")
        self._replays_total = registry.counter(
            "serving_token_replays_total",
            "token rows replayed instead of served fresh — surface="
            "engine: deterministic re-decode of already-durable tokens "
            "when a resume context outruns the prefill ladder; surface="
            "frontend: rows re-sent to a reconnecting SSE client "
            "honoring Last-Event-ID")
        self._claimed_total = registry.counter(
            "serving_claimed_records_total",
            "stale pending records claimed from dead consumers and "
            "re-dispatched by this engine")
        self._waiting: deque = deque()
        self._prefilling: deque = deque()           # paged: mid-prompt
        self._active: Dict[int, _Sequence] = {}     # slot/lane -> sequence
        self._stop = threading.Event()
        self._drain_deadline: Optional[float] = None
        self._thread: Optional[threading.Thread] = None
        # writeback pending buffer (flushed as ONE broker interaction;
        # retained across a broker outage so decode keeps stepping)
        self._pending_rows: Dict[str, str] = {}
        self._pending_finals: Dict[str, str] = {}
        self._pending_acks: List[str] = []
        self._flush_down = False
        self._intake_down = False
        self._next_claim = time.monotonic() + self.claim_interval_s
        # record ids this engine itself holds un-acked — the claim
        # sweep must never reclaim them (a decode longer than
        # claim_min_idle_s would otherwise fork itself)
        self._inflight: set = set()
        self.stats: Dict[str, int] = {
            "steps": 0, "slot_steps_active": 0, "slot_steps_total": 0,
            "tokens": 0, "prefills": 0, "finished": 0, "shed": 0,
            "failed": 0, "prefill_chunks": 0, "prefix_hit_tokens": 0,
            "resumed": 0, "recovered_tokens": 0, "replayed_tokens": 0,
            "preempted": 0, "aborted": 0, "duplicates": 0,
            "rows_shed": 0}

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "DecodeServing":
        self._stop.clear()
        self._drain_deadline = None
        if self.heartbeat_interval_s and self._heartbeat is None:
            # own broker connection: the engine loop may sit in an
            # XREADGROUP block window; a heartbeat must never queue
            # behind it (a stalled beat reads fleet-wide as a death)
            from analytics_zoo_tpu.serving.fleet import HeartbeatPublisher
            self._heartbeat = HeartbeatPublisher(
                self.broker.clone(), self.stream, self.engine_id,
                payload_fn=lambda: {
                    "ready": True, "role": "decode",
                    "records_served": self.stats["finished"],
                    "tokens": self.stats["tokens"]},
                interval_s=self.heartbeat_interval_s,
                registry=self.registry)
            self._heartbeat.start()
        self._thread = threading.Thread(target=self.run,
                                        name="decode-engine", daemon=True)
        self._thread.start()
        return self

    def stop(self, drain: bool = True):
        """Stop the loop; with `drain` (default) keep stepping until
        every in-flight sequence finishes or `drain_timeout_s` runs
        out. Un-drained records redeliver to a peer (at-least-once)."""
        self._drain_deadline = time.monotonic() + (
            self.drain_timeout_s if drain else 0.0)
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=self.drain_timeout_s + 10.0)
        self._thread = None
        if self._heartbeat is not None:
            self._heartbeat.stop(deregister=True)
            self._heartbeat = None

    def is_alive(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    # -- record intake -----------------------------------------------------
    def _parse_record(self, rid, rec) -> Optional[_Sequence]:
        from analytics_zoo_tpu.serving.pre_post import decode_record_field
        data = rec["data"]
        raw = data["t"] if "t" in data else data[next(iter(data))]
        prompt = np.asarray(decode_record_field(raw)).astype(np.int32)
        prompt = prompt.reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if prompt.size + 1 > self.max_kv_len:
            raise ValueError(
                f"prompt length {prompt.size} leaves no room to "
                f"generate under max_kv_len={self.max_kv_len}")
        if not self.paged and prompt.size > self.prompt_buckets[-1]:
            # the contiguous prefill executable pads to a prompt
            # bucket; a prompt beyond the ladder has no executable —
            # degrade the record instead of crashing the loop
            raise ValueError(
                f"prompt length {prompt.size} exceeds the prefill "
                f"ladder (max prompt bucket {self.prompt_buckets[-1]})")
        max_new = int(data.get("max_new", self.max_new_default))
        # a sequence can never outgrow its slot row
        max_new = max(1, min(max_new, self.max_kv_len - prompt.size))
        eos = data.get("eos", self.eos_id)
        tier = rec.get("tier") if isinstance(rec, dict) else None
        return _Sequence(
            uri=rec["uri"], rid=rid, prompt=prompt, max_new=max_new,
            eos=None if eos is None else int(eos),
            stream=str(data.get("stream", "")) in ("1", "true", "True"),
            t_enqueue=time.perf_counter(),
            tier=None if tier is None else str(tier))

    def _free_capacity(self) -> int:
        return len(self._free_lanes) if self.paged \
            else self.pool.free_count

    def _intake(self):
        if self._stop.is_set():
            return
        self._claim_sweep()
        idle = (not self._active and not self._waiting
                and not self._prefilling)
        count = max(1, self._free_capacity() + self.max_waiting
                    - len(self._waiting))
        try:
            records = self.broker.read_group(
                self.stream, GROUP, self.consumer, count,
                block_ms=self.idle_block_ms if idle else 0)
        except (ConnectionError, OSError) as e:
            if not self._intake_down:
                self._intake_down = True
                log.warning("decode intake unavailable "
                            "(decode keeps stepping): %s", e)
            if not self._active and not self._prefilling:
                # idle + dead broker: timed pause so the loop can't
                # hot-spin; with live sequences, keep stepping at full
                # speed — the breaker makes the failed read instant
                self._stop.wait(self.idle_block_ms / 1e3)
            return
        if self._intake_down:
            self._intake_down = False
            log.info("decode intake recovered")
        for rid, rec in records:
            self._inflight.add(rid)
            try:
                self._waiting.append(self._parse_record(rid, rec))
            except Exception as e:  # noqa: BLE001 — degrade per record
                uri = rec.get("uri", str(rid)) if isinstance(rec, dict) \
                    else str(rid)
                log.warning("decode intake failure for %s: %s", uri, e)
                self.stats["failed"] += 1
                self._queue_final(uri, "NaN", rid)
        # overload: answer the newest arrivals with SHED (the oldest
        # queued are closest to boarding — shedding them wastes wait).
        # Resumed sequences are exempt: a dead peer already accepted
        # (and partially decoded) them, so a claim sweep that lands on
        # a full queue must not convert recovery into rejection —
        # the queue briefly exceeds max_waiting instead
        while len(self._waiting) > self.max_waiting:
            seq = next((s for s in reversed(self._waiting)
                        if not s.resumed), None)
            if seq is None:
                break
            self._waiting.remove(seq)
            self.stats["shed"] += 1
            self._queue_final(seq.uri, "SHED", seq.rid)
        if self._pending_finals or self._pending_acks:
            self._flush_pending()

    # -- decode-session recovery (ISSUE 20 tentpole, part 1) ---------------
    def _claim_sweep(self):
        """Adopt a dead peer's pending generative records — the PR
        10/15 claim discipline on the decode stream. `claim_min_idle_s`
        guards live peers (their PEL entries stay young while they
        step); the in-flight filter stops this engine from reclaiming
        records it itself holds (one decode can out-idle the min-idle
        window: idle is measured from DELIVERY, and rows don't reset
        it); each claimed record resumes from its durable token rows."""
        if self.claim_min_idle_s is None or self._stop.is_set():
            return
        now = time.monotonic()
        if now < self._next_claim:
            return
        self._next_claim = now + self.claim_interval_s
        try:
            claimed = self.broker.claim_stale(
                self.stream, GROUP, self.consumer,
                int(self.claim_min_idle_s * 1000),
                max(1, self._free_capacity() + 4))
        except NotImplementedError:
            self.claim_min_idle_s = None   # transport can't claim
            return
        except Exception as e:  # noqa: BLE001 — sweep is best-effort
            log.warning("decode claim sweep failed: %s", e)
            return
        claimed = [(rid, rec) for rid, rec in claimed
                   if rid not in self._inflight]
        if not claimed:
            return
        self._claimed_total.inc(len(claimed), engine=self.engine_id)
        log.info("decode engine %s claimed %d stale record(s)",
                 self.engine_id, len(claimed))
        for rid, rec in claimed:
            self._recover_record(rid, rec)
        if self._pending_finals or self._pending_acks:
            self._flush_pending()

    def _recover_record(self, rid, rec):
        """Board one claimed record, resuming from whatever the dead
        peer durably committed. Greedy decode is deterministic, so
        re-prefilling prompt ⊕ emitted-so-far continues the EXACT
        sequence from token i+1; `presented` pins the already-durable
        prefix so nothing re-emits."""
        try:
            seq = self._parse_record(rid, rec)
        except Exception as e:  # noqa: BLE001 — degrade per record
            uri = rec.get("uri", str(rid)) if isinstance(rec, dict) \
                else str(rid)
            log.warning("decode claim parse failure for %s: %s", uri, e)
            self.stats["failed"] += 1
            self._queue_final(uri, "NaN", rid)
            return
        self._inflight.add(rid)
        try:
            (final,) = self.broker.hmget(self.result_key, [seq.uri])
            recovered: List[int] = []
            if final is None:
                while True:
                    fields = [token_row_field(seq.uri,
                                              len(recovered) + j)
                              for j in range(16)]
                    raws = self.broker.hmget(self.result_key, fields)
                    for raw in raws:
                        if raw is None:
                            break
                        recovered.append(int(json.loads(raw)["t"]))
                    if any(r is None for r in raws):
                        break
        except (ConnectionError, OSError) as e:
            # can't read the durable state — hand the record back to a
            # future sweep rather than risk re-emitting rows
            self._inflight.discard(rid)
            log.warning("decode recovery read failed for %s: %s",
                        seq.uri, e)
            return
        if final is not None:
            # the peer committed the final but its ack was lost (or the
            # record was re-enqueued): idempotent — ack, never redo
            self.stats["duplicates"] += 1
            self._pending_acks.append(rid)
            return
        k = len(recovered)
        seq.gen = list(recovered)
        seq.presented = k
        seq.rows = k if seq.stream else 0
        seq.resumed = True
        self.stats["resumed"] += 1
        self.stats["recovered_tokens"] += k
        self._resumes_total.inc(engine=self.engine_id)
        # finals commit in the SAME fused writeback as their finishing
        # row, so rows-without-final implies unfinished — re-derive the
        # finish anyway as defense against a torn transport
        if k and seq.eos is not None and recovered[-1] == seq.eos:
            seq.finish = "eos"
        elif k >= seq.max_new:
            seq.finish = "length"
        elif k and int(seq.prompt.size) + k - 1 >= self.max_kv_len:
            seq.finish = "kv-full"
        if seq.finish:
            self.stats["finished"] += 1
            self._queue_final(seq.uri, self._final_blob(seq), rid)
            return
        log.info("decode engine %s resuming %s at token %d",
                 self.engine_id, seq.uri, k)
        self._waiting.appendleft(seq)   # it already earned its wait

    # -- token emission ----------------------------------------------------
    def _emit(self, seq: _Sequence, token: int, now: float,
              token_rows: Dict[str, str]):
        idx = len(seq.gen)
        seq.gen.append(int(token))
        if seq.eos is not None and int(token) == seq.eos:
            seq.finish = "eos"
        elif len(seq.gen) >= seq.max_new:
            seq.finish = "length"
        elif seq.pos >= self.max_kv_len:
            seq.finish = "kv-full"
        if idx < seq.presented:
            # replaying an already-durable token (recovery fallback
            # re-decode): the row is committed, the peer observed its
            # latency — nothing to write, count, or observe
            return
        if seq.ttft_ms is None:
            # first token THIS engine produced; for a resumed sequence
            # this is the resume latency (claim to first fresh token)
            seq.ttft_ms = (now - seq.t_enqueue) * 1e3
            self._ttft_hist.observe(seq.ttft_ms, engine=self.engine_id)
        else:
            self._itl_hist.observe((now - seq.t_last) * 1e3,
                                   engine=self.engine_id)
        seq.t_last = now
        if seq.stream:
            token_rows[token_row_field(seq.uri, idx)] = json.dumps(
                {"i": idx, "t": int(token),
                 "ms": round((now - seq.t_enqueue) * 1e3, 3)})
            seq.rows = idx + 1
        self.stats["tokens"] += 1

    def _final_blob(self, seq: _Sequence) -> str:
        blob = encode_ndarray(np.asarray(seq.gen, np.int32))
        blob["gen"] = {"n": len(seq.gen), "rows": seq.rows,
                       "finish": seq.finish,
                       "ttft_ms": round(seq.ttft_ms or 0.0, 3)}
        return json.dumps(blob)

    # -- the step loop -----------------------------------------------------
    def _run_step(self):
        plan = self.scheduler.plan_step(
            [s.ctx_len() for s in self._waiting],
            self.pool.free_count,
            [s.pos + 1 for s in self._active.values()])
        token_rows: Dict[str, str] = {}
        finished: List[_Sequence] = []
        for _ in range(plan.admit):
            seq = self._waiting.popleft()
            slot = self.pool.lease()
            if slot is None:       # raced with nothing — defensive only
                self._waiting.appendleft(seq)
                break
            ctx = seq.context()
            if int(ctx.size) > self.prompt_buckets[-1]:
                # a resume context can outrun the warmed prefill ladder
                # (the original prompt never does — parse rejects it):
                # replay the whole decode from the prompt instead.
                # Greedy is deterministic, and `presented` suppresses
                # every already-durable row on the way back up.
                if seq.gen:
                    self._replays_total.inc(len(seq.gen),
                                            engine=self.engine_id,
                                            surface="engine")
                    self.stats["replayed_tokens"] += len(seq.gen)
                seq.gen = []
                ctx = seq.prompt
            pb = self.scheduler.prompt_bucket(int(ctx.size))
            padded = np.zeros(pb, np.int32)
            padded[:ctx.size] = ctx
            t0 = time.perf_counter()
            faults.fire("decode.prefill", engine=self.engine_id,
                        uri=seq.uri)
            self.pool.kv, logits = self.model.generative_prefill(
                self.pool.kv, padded, int(ctx.size), slot)
            first = int(np.asarray(logits).argmax())   # forces the sync
            dt = time.perf_counter() - t0
            self.scheduler.observe_prefill(pb, dt * 1e3)
            self.model.account_generative("prefill", pb, dt)
            seq.slot, seq.pos = slot, int(ctx.size)
            self._active[slot] = seq
            self.stats["prefills"] += 1
            self._emit(seq, first, time.perf_counter(), token_rows)
            if seq.finish:
                finished.append(seq)
        for seq in finished:       # finished straight out of prefill
            del self._active[seq.slot]
        if self._active:
            faults.fire("decode.step", engine=self.engine_id)
            slots_arr = np.zeros(self.pool.slots, np.int32)
            pos_arr = np.zeros(self.pool.slots, np.int32)
            for slot, seq in self._active.items():
                slots_arr[slot] = seq.gen[-1]
                pos_arr[slot] = seq.pos
            bucket = self.scheduler.kv_bucket_for(
                max(s.pos + 1 for s in self._active.values()))
            t0 = time.perf_counter()
            self.pool.kv, logits = self.model.generative_step(
                self.pool.kv, slots_arr, pos_arr, bucket)
            nxt = np.asarray(logits).argmax(axis=-1)   # forces the sync
            dt = time.perf_counter() - t0
            self.scheduler.observe_step(bucket, dt * 1e3)
            self.model.account_generative("step", bucket, dt)
            now = time.perf_counter()
            self.stats["steps"] += 1
            self.stats["slot_steps_total"] += self.pool.slots
            self.stats["slot_steps_active"] += len(self._active)
            for slot, seq in list(self._active.items()):
                seq.pos += 1
                self._emit(seq, int(nxt[slot]), now, token_rows)
                if seq.finish:
                    finished.append(seq)
                    del self._active[slot]
        self._flush(token_rows, finished)
        for seq in finished:
            self.pool.release(seq.slot)

    def _flush(self, token_rows: Dict[str, str],
               finished: List[_Sequence]):
        """ONE broker interaction per step: every sequence's token rows
        AND any finals land in the same fused ``writeback`` (HSET +
        XACK), so a step's host-side bookkeeping cost is flat in the
        number of tokens emitted — the per-row HSET the BENCH_r10
        narrative measured is gone. Steps with no finals stay a single
        ``hset_many``; the shared HSET keeps the final-commits-with-rows
        ordering (a streaming client can never see the final field
        before the rows it summarizes).

        Everything routes through the PENDING BUFFER: on a broker
        failure the step's rows/finals/acks are retained (bounded per
        sequence) and the decode loop keeps stepping — the next flush
        attempt drains the backlog in the same single interaction."""
        for s in finished:
            self._queue_final(s.uri, self._final_blob(s), s.rid)
        self._queue_rows(token_rows)
        self._flush_pending()
        self.stats["finished"] += len(finished)

    def _queue_final(self, uri: str, blob: str, rid) -> None:
        self._pending_finals[uri] = blob
        self._pending_acks.append(rid)

    def _queue_rows(self, token_rows: Dict[str, str]) -> None:
        if not token_rows:
            return
        self._pending_rows.update(token_rows)
        for uri in {f.rsplit("#", 1)[0] for f in token_rows}:
            pre = uri + "#"
            fields = sorted(f for f in self._pending_rows
                            if f.startswith(pre))
            over = len(fields) - self.writeback_buffer_rows
            if over > 0:
                # oldest-step shed: early rows go first; the final blob
                # stays authoritative for the whole sequence, and the
                # streaming client's final drain fills any gap from it
                for f in fields[:over]:
                    del self._pending_rows[f]
                self.stats["rows_shed"] += over

    def _flush_pending(self) -> bool:
        """Attempt ONE fused send of everything buffered. Returns False
        (keeping the buffer) on a broker failure — the caller's loop
        retries next iteration; logs once per outage."""
        if not (self._pending_rows or self._pending_finals
                or self._pending_acks):
            return True
        try:
            faults.fire("decode.writeback", engine=self.engine_id)
            mapping = {**self._pending_rows, **self._pending_finals}
            if self._pending_acks:
                if mapping:
                    self.broker.writeback(self.result_key, mapping,
                                          self.stream, GROUP,
                                          list(self._pending_acks))
                else:
                    self.broker.ack(self.stream, GROUP,
                                    list(self._pending_acks))
            else:
                self.broker.hset_many(self.result_key, mapping)
        except (ConnectionError, OSError) as e:
            if not self._flush_down:
                self._flush_down = True
                log.warning(
                    "decode writeback unavailable — buffering (%d rows,"
                    " %d finals, %d acks): %s", len(self._pending_rows),
                    len(self._pending_finals), len(self._pending_acks),
                    e)
            return False
        if self._flush_down:
            self._flush_down = False
            log.info("decode writeback recovered — flushed %d rows, "
                     "%d finals, %d acks", len(self._pending_rows),
                     len(self._pending_finals), len(self._pending_acks))
        self._inflight.difference_update(self._pending_acks)
        self._pending_rows.clear()
        self._pending_finals.clear()
        self._pending_acks.clear()
        return True

    @property
    def _pending(self) -> bool:
        return bool(self._pending_rows or self._pending_finals
                    or self._pending_acks)

    # -- per-sequence watchdog (ISSUE 20 satellite) ------------------------
    def _watchdog(self):
        """Abort any sequence older than `max_seq_wall_s` with an
        explicit NaN-degrade final: an answered failure that releases
        its slot/blocks, instead of a wedged record holding KV forever.
        Covers stuck steps too — a stalled prefill/step/flush surfaces
        here the moment the loop breathes again."""
        if self.max_seq_wall_s is None:
            return
        now = time.perf_counter()
        doomed: List[_Sequence] = []
        for seq in list(self._active.values()):
            if now - seq.t_enqueue > self.max_seq_wall_s:
                del self._active[seq.slot]
                if self.paged:
                    self._release_paged(seq)
                else:
                    self.pool.release(seq.slot)
                    seq.slot = -1
                doomed.append(seq)
        for dq in (self._prefilling, self._waiting):
            for seq in [s for s in dq
                        if now - s.t_enqueue > self.max_seq_wall_s]:
                dq.remove(seq)
                if self.paged:
                    self._release_paged(seq)
                doomed.append(seq)
        for seq in doomed:
            log.warning("decode watchdog aborting %s after %.1fs "
                        "(%d tokens generated)", seq.uri,
                        now - seq.t_enqueue, len(seq.gen))
            self._aborts_total.inc(engine=self.engine_id, reason="wall")
            self.stats["aborted"] += 1
            self._queue_final(seq.uri, "NaN", seq.rid)
        if doomed:
            self._flush_pending()

    # -- the paged step loop (ISSUE 19) ------------------------------------
    def _alloc_block(self) -> Optional[int]:
        """One pool block, evicting cold cached prefixes if needed."""
        b = self.block_pool.alloc()
        if b is None and self.prefix_cache is not None:
            self.prefix_cache.evict_for(1)
            b = self.block_pool.alloc()
        return b

    def _release_paged(self, seq: _Sequence):
        for b in seq.blocks:
            self.block_pool.release(b)
        seq.blocks = []
        if seq.slot >= 0:
            self._free_lanes.append(seq.slot)
            seq.slot = -1

    def _admit_paged(self, seq: _Sequence) -> bool:
        """Lease a lane and the context's blocks; adopt every fully-
        matching prefix-cache block copy-free (that span of prefill is
        skipped). The CONTEXT is prompt ⊕ generated-so-far — for a
        fresh sequence that's just the prompt, while a resumed or
        preempted sequence re-boards with its own published prefix
        (usually a full cache hit, making resume/re-admission nearly
        copy-free). On block exhaustion everything is rolled back and
        the caller requeues the sequence — admission is all-or-nothing."""
        bl = self.block_len
        ctx = seq.context()
        adopted = self.prefix_cache.match(ctx.tolist()) \
            if self.prefix_cache is not None else []
        cached = len(adopted) * bl
        need = -(-(int(ctx.size) - cached) // bl)
        got: List[int] = []
        for _ in range(need):
            b = self._alloc_block()
            if b is None:
                for x in got + adopted:
                    self.block_pool.release(x)
                return False
            got.append(b)
        if not self._free_lanes:      # raced with nothing — defensive
            for x in got + adopted:
                self.block_pool.release(x)
            return False
        seq.slot = self._free_lanes.pop()
        seq.blocks = adopted + got
        seq.cached = seq.filled = cached
        if cached:
            self.stats["prefix_hit_tokens"] += cached
        return True

    def _prefill_chunk_step(self, seq: _Sequence,
                            token_rows: Dict[str, str]) -> bool:
        """Run ONE chunk of `seq`'s remaining CONTEXT (prompt, plus any
        tokens recovered/kept across a resume or preemption) through
        the warmed paged-prefill executable for its (chunk bucket,
        context bucket). The final chunk produces the next generated
        token and publishes the context's full blocks to the prefix
        cache — a full block is immutable from here on (decode writes
        land strictly beyond it), so publishing generated spans is as
        safe as publishing prompt spans and makes the NEXT resume or
        re-admission of this very sequence copy-free."""
        bl = self.block_len
        ctx = seq.context()
        remaining = int(ctx.size) - seq.filled
        chunk = min(remaining, self.chunk_cap)
        cb = self.scheduler.chunk_bucket(chunk)
        padded = np.zeros(cb, np.int32)
        padded[:chunk] = ctx[seq.filled:seq.filled + chunk]
        kvb = 0 if seq.filled == 0 \
            else self.scheduler.kv_bucket_for(seq.filled)
        table = np.zeros(self.table_len, np.int32)
        table[:len(seq.blocks)] = seq.blocks
        t0 = time.perf_counter()
        faults.fire("decode.prefill", engine=self.engine_id,
                    uri=seq.uri)
        self.block_pool.kv, logits = self.model.generative_prefill_paged(
            self.block_pool.kv, padded, table, seq.filled, chunk, kvb)
        done = seq.filled + chunk >= int(ctx.size)
        logits_h = np.asarray(logits)      # forces the sync
        dt = time.perf_counter() - t0
        self.scheduler.observe_prefill(cb, dt * 1e3)
        self.model.account_generative("paged_prefill", (cb, kvb), dt)
        self._chunks_total.inc(engine=self.engine_id)
        self.stats["prefill_chunks"] += 1
        seq.filled += chunk
        if done:
            seq.pos = int(ctx.size)
            self.stats["prefills"] += 1
            if self.prefix_cache is not None:
                n_full = int(ctx.size) // bl
                if n_full:
                    self.prefix_cache.insert(ctx.tolist(),
                                             seq.blocks[:n_full])
            self._emit(seq, int(logits_h.argmax()),
                       time.perf_counter(), token_rows)
        return done

    def _ensure_block(self, seq: _Sequence) -> bool:
        """Grow the sequence's table to cover its next write position
        (block-by-block, the paged discipline's whole point)."""
        while seq.pos // self.block_len >= len(seq.blocks):
            b = self._alloc_block()
            if b is None:
                return False
            seq.blocks.append(b)
        return True

    def _settle_prefill(self, seq: _Sequence, done: bool,
                        finished: List[_Sequence]):
        # `done` comes from the chunk step itself: the final chunk's
        # emit grows ctx_len() by one, so comparing filled against it
        # here would misread a completed prefill as still in flight
        if not done:
            self._prefilling.append(seq)
        elif seq.finish:
            finished.append(seq)
        else:
            self._active[seq.slot] = seq

    # -- KV-pressure preemption (ISSUE 20 tentpole, part 2) ----------------
    def _preempt_victim(self, exclude: Optional[_Sequence] = None
                        ) -> Optional[_Sequence]:
        """The live sequence that loses the least by being backed out:
        untiered before tiered, then the youngest arrival. Sequences at
        the anti-thrash bound are never victims — after `preempt_max`
        preemptions a sequence runs to completion."""
        cands = [s for s in self._active.values()
                 if s is not exclude and s.preempts < self.preempt_max]
        if not cands:
            return None
        return min(cands, key=lambda s: (s.tier is not None,
                                         -s.t_enqueue))

    def _preempt(self, seq: _Sequence):
        """Back one live sequence out to the waiting queue under KV
        pressure. Its full context blocks are published to the prefix
        cache FIRST (they're fully written and immutable — decode was
        writing beyond them), so its re-admission adopts them copy-free
        while the pool reclaims them via normal cache eviction if the
        pressure persists. Requeued at the FRONT: it already earned its
        wait, and its generated tokens ride along (`gen` is kept, so
        the re-admission prefill continues at the exact next token)."""
        if self.prefix_cache is not None and seq.blocks:
            n_full = min(seq.pos // self.block_len, len(seq.blocks))
            if n_full:
                self.prefix_cache.insert(seq.context().tolist(),
                                         seq.blocks[:n_full])
        self._release_paged(seq)
        seq.filled = seq.cached = 0
        seq.pos = 0
        seq.preempts += 1
        self._preempt_total.inc(engine=self.engine_id)
        self.stats["preempted"] += 1
        log.info("decode engine %s preempted %s (%d tokens kept, "
                 "preempt %d/%d)", self.engine_id, seq.uri,
                 len(seq.gen), seq.preempts, self.preempt_max)
        self._waiting.appendleft(seq)

    def _run_paged_step(self):
        plan = self.scheduler.plan_paged_step(
            [s.ctx_len() for s in self._waiting],
            len(self._free_lanes),
            [s.ctx_len() - s.filled for s in self._prefilling],
            [s.pos + 1 for s in self._active.values()],
            self.chunk_cap)
        token_rows: Dict[str, str] = {}
        finished: List[_Sequence] = []
        # mid-prefill sequences advance first (they hold blocks + lanes)
        for _ in range(plan.chunks):
            seq = self._prefilling.popleft()
            done = self._prefill_chunk_step(seq, token_rows)
            self._settle_prefill(seq, done, finished)
        # anti-thrash gate: while any waiting sequence has hit the
        # preemption bound, ONLY such sequences may board — they run
        # to completion before fresh admissions compete for blocks
        thrash_waiting = any(s.preempts >= self.preempt_max
                             for s in self._waiting)
        for _ in range(plan.admit):
            seq = self._waiting.popleft()
            if (thrash_waiting and self.preempt_max
                    and seq.preempts < self.preempt_max):
                self._waiting.appendleft(seq)
                break
            if not self._admit_paged(seq):
                # admission-time preemption: only a strictly younger
                # victim may be displaced (never trade places with an
                # older sequence — that's how admission livelocks)
                victim = self._preempt_victim()
                admitted = False
                if victim is not None \
                        and victim.t_enqueue > seq.t_enqueue:
                    del self._active[victim.slot]
                    self._preempt(victim)
                    admitted = self._admit_paged(seq)
                if not admitted:
                    if (victim is None and not self._active
                            and not self._prefilling):
                        # nothing live will ever free more blocks:
                        # this context alone outgrows the pool —
                        # answer with what it has instead of an
                        # admission deadlock
                        seq.finish = "blocks-full"
                        self._aborts_total.inc(engine=self.engine_id,
                                               reason="blocks-full")
                        self.stats["aborted"] += 1
                        finished.append(seq)
                        continue
                    self._waiting.appendleft(seq)
                    break
            done = self._prefill_chunk_step(seq, token_rows)
            self._settle_prefill(seq, done, finished)
        if self._active:
            # a lane whose next write position has no block left (pool
            # exhausted even after cache eviction) preempts the
            # youngest/lowest-tier live sequence instead of wedging;
            # only when every live sequence is at the thrash bound does
            # it answer with what it generated (blocks-full)
            for lane, seq in list(self._active.items()):
                if self._active.get(lane) is not seq:
                    continue           # already preempted as a victim
                while not self._ensure_block(seq):
                    victim = self._preempt_victim()
                    if victim is None:
                        seq.finish = "blocks-full"
                        self._aborts_total.inc(engine=self.engine_id,
                                               reason="blocks-full")
                        self.stats["aborted"] += 1
                        finished.append(seq)
                        del self._active[lane]
                        break
                    del self._active[victim.slot]
                    self._preempt(victim)
                    if victim is seq:
                        break
        if self._active:
            faults.fire("decode.step", engine=self.engine_id)
            tokens_arr = np.zeros(self.lanes, np.int32)
            pos_arr = np.zeros(self.lanes, np.int32)
            tables = np.zeros((self.lanes, self.table_len), np.int32)
            for lane, seq in self._active.items():
                tokens_arr[lane] = seq.gen[-1]
                pos_arr[lane] = seq.pos
                tables[lane, :len(seq.blocks)] = seq.blocks
            bucket = self.scheduler.kv_bucket_for(
                max(s.pos + 1 for s in self._active.values()))
            t0 = time.perf_counter()
            self.block_pool.kv, logits = self.model.generative_step_paged(
                self.block_pool.kv, tokens_arr, pos_arr, tables, bucket)
            nxt = np.asarray(logits).argmax(axis=-1)   # forces the sync
            dt = time.perf_counter() - t0
            self.scheduler.observe_step(bucket, dt * 1e3)
            self.model.account_generative("paged_step", bucket, dt)
            now = time.perf_counter()
            self.stats["steps"] += 1
            self.stats["slot_steps_total"] += self.lanes
            self.stats["slot_steps_active"] += len(self._active)
            for lane, seq in list(self._active.items()):
                seq.pos += 1
                self._emit(seq, int(nxt[lane]), now, token_rows)
                if seq.finish:
                    finished.append(seq)
                    del self._active[lane]
        self._flush(token_rows, finished)
        for seq in finished:
            self._release_paged(seq)

    def run(self):
        """The engine loop (inline-callable for tests; `start()` wraps
        it in a thread). Every iteration: watchdog → intake (claim
        sweep rides along) → plan → prefill admissions → one batched
        decode step → writebacks (buffered across broker outages)."""
        emitted_before = self.stats["tokens"]
        step = self._run_paged_step if self.paged else self._run_step
        while True:
            if self._stop.is_set():
                drained = (not self._active and not self._waiting
                           and not self._prefilling and not self._pending)
                if drained or (self._drain_deadline is not None
                               and time.monotonic() > self._drain_deadline):
                    break
            self._watchdog()
            self._intake()
            before = self.stats["tokens"]
            step()
            delta = self.stats["tokens"] - before
            if delta:
                self._tokens_total.inc(delta, engine=self.engine_id)
            if self._pending:
                # a failed flush left rows/finals buffered: retry each
                # iteration (the idle intake block paces this loop)
                self._flush_pending()
        if self._pending:
            self._flush_pending()     # one last drain attempt
        if self._pending:
            log.warning("decode engine %s stopping with %d rows / %d "
                        "finals unflushed (records will redeliver)",
                        self.engine_id, len(self._pending_rows),
                        len(self._pending_finals))
        if self.stats["tokens"] != emitted_before:
            log.info("decode engine %s: %s", self.engine_id, self.stats)

    def utilization(self) -> float:
        """Useful slot-steps over total slot-steps — the bench's
        headline ratio vs the pad-to-max baseline."""
        total = self.stats["slot_steps_total"]
        return self.stats["slot_steps_active"] / total if total else 0.0
