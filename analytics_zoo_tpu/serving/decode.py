"""Continuous-batching decode engine — pooled KV slots, per-step planning.

`ClusterServing` serves fixed-shape forwards: plan ONE dispatch, run it,
write it back. Autoregressive generation breaks that shape — a request
is now a prompt plus up to `max_new` dependent steps, and padding every
sequence to the longest (then restarting the batch when all finish) is
the pad-to-max baseline vLLM/Orca showed 2-10x worse than iteration-
level scheduling. This module is that discipline on the existing rails:

- ``KVSlotPool`` — the KV cache is pre-allocated ONCE as
  ``[slots, heads, max_kv_len, head_dim]`` device buffers (one k/v pair
  per layer, built by the model's ``init_kv``). A sequence leases a
  slot row at admission and releases it at its final token — no
  allocation, no reshape, no copy ever happens on the request path.
  The ``serving_kv_slots_in_use`` gauge IS the admission signal: free
  slots are the only capacity that matters in decode mode.
- ``DecodeScheduler`` — generalizes the adaptive batch controller's
  "plan one dispatch" to "plan EVERY step": at each step boundary
  finished sequences free slots, queued prompts join (continuous
  batching), and prefill admissions are budgeted under the same
  deadline math — a prefill stalls every in-flight sequence for its
  duration, so the scheduler admits only as many prompts per step as
  the deadline budget covers (per-bucket EWMA costs, the PR 11 model,
  one per phase).
- ``DecodeServing`` — the engine loop: intake from the serving stream
  (same record protocol — field ``t`` is the int32 prompt, plus
  ``max_new``/``eos``/``stream``), prefill admitted prompts one at a
  time, then ONE batched decode step for every leased slot at the kv
  bucket covering the longest live sequence. Steps run on the AOT
  executables `warmup_generative` pre-compiled — 0 XLA compiles on the
  request path, the same contract the forward path enforces.

Token streaming rides the existing result hash: each generated token of
a ``stream``-flagged request is written as a row ``<uri>#<index>``
(JSON ``{"i", "t", "ms"}``), and the FINAL row is the plain ``uri``
field holding the standard b64 ndarray of all generated ids (plus a
``gen`` summary) — so the non-streaming client path (exact-uri HMGET)
is oblivious to the extra rows, completion is the presence of the exact
uri field, and `OutputQueue.stream_tokens` polls rows incrementally.
Final rows commit through the fused ``writeback`` (HSET+ACK) like the
forward sink; a step's token rows and finals share ONE broker
interaction (`_flush`).

PAGED MODE (ISSUE 19). With ``paged=True`` the stripe pool is replaced
by `KVBlockPool` + per-sequence block tables (`serving/paged_kv.py`):
``slots`` becomes the fixed DECODE LANE count (the static step batch
shape) while capacity is bounded by live tokens in the block pool —
short sequences no longer reserve `max_kv_len` stripes. A `PrefixCache`
lets prompts sharing an instruction prefix adopt cached blocks copy-
free (skipping that span of prefill), and `prefill_chunk` splits long
prompts into bounded chunks interleaved between decode steps so one
giant prompt can't stall every live sequence for its full prefill
(`plan_paged_step` budgets chunks and admissions under the same
deadline math). Greedy outputs are bitwise-identical to the contiguous
path — the paged programs run the same numeric ops over relocated
bytes — and the request path still performs 0 XLA compiles
(`warmup_generative_paged` pre-compiles per (chunk bucket, kv bucket)).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import threading
import time
import uuid
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from analytics_zoo_tpu.serving.broker import (Broker, connect_broker,
                                              encode_ndarray)
from analytics_zoo_tpu.serving.client import STREAM
from analytics_zoo_tpu.serving.elastic import BucketCostModel
from analytics_zoo_tpu.serving.inference_model import (InferenceModel,
                                                       _next_bucket)
from analytics_zoo_tpu.serving.paged_kv import KVBlockPool, PrefixCache

log = logging.getLogger("analytics_zoo_tpu.serving.decode")

GROUP = "serving_group"


def _pow2_ladder(lo: int, hi: int) -> List[int]:
    out, b = [], 1
    while b < lo:
        b *= 2
    while b < hi:
        out.append(b)
        b *= 2
    out.append(hi)
    return out


def token_row_field(uri: str, index: int) -> str:
    """Result-hash field name of one streamed token row. '#' never
    appears in generated uris (uuid4 / frontend request ids), so the
    exact-uri poll can never collide with a token row."""
    return f"{uri}#{index:06d}"


class KVSlotPool:
    """Fixed pool of KV slots over ONE pre-allocated device buffer set.

    The pytree in ``self.kv`` is threaded functionally through every
    prefill/step call (the engine rebinds it to each call's returned
    tree); the POOL object only tracks which rows are leased. Freed
    rows are not zeroed — attention masks by live length and the next
    prefill into the slot overwrites from position 0."""

    def __init__(self, init_kv: Callable[[int, int], Any], slots: int,
                 max_kv_len: int, registry=None,
                 labels: Optional[Dict[str, str]] = None):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        self.slots = int(slots)
        self.max_kv_len = int(max_kv_len)
        self.kv = init_kv(self.slots, self.max_kv_len)
        self._free = list(range(self.slots - 1, -1, -1))   # lease 0 first
        self._lock = threading.Lock()
        self._labels = dict(labels or {})
        if registry is None:
            from analytics_zoo_tpu.observability.registry import get_registry
            registry = get_registry()
        self._gauge = registry.gauge(
            "serving_kv_slots_in_use",
            "KV-cache slots currently leased to in-flight sequences "
            "(out of the engine's fixed slot pool) — the decode "
            "engine's admission signal")
        self._gauge.set(0.0, **self._labels)

    def lease(self) -> Optional[int]:
        with self._lock:
            if not self._free:
                return None
            slot = self._free.pop()
            self._gauge.set(self.slots - len(self._free), **self._labels)
            return slot

    def release(self, slot: int) -> None:
        with self._lock:
            if slot in self._free or not 0 <= slot < self.slots:
                raise ValueError(f"release of unleased slot {slot}")
            self._free.append(slot)
            self._gauge.set(self.slots - len(self._free), **self._labels)

    @property
    def in_use(self) -> int:
        with self._lock:
            return self.slots - len(self._free)

    @property
    def free_count(self) -> int:
        with self._lock:
            return len(self._free)


@dataclasses.dataclass
class StepPlan:
    """One step's plan: how many waiting prompts board now, and the kv
    bucket the step executable runs at."""
    admit: int
    kv_bucket: int
    budget_ms: Optional[float]
    reason: str


@dataclasses.dataclass
class PagedStepPlan:
    """One PAGED step's plan: how many mid-prefill sequences advance one
    chunk, how many waiting prompts board (and run their first chunk),
    and the kv bucket of the decode step."""
    admit: int
    chunks: int
    kv_bucket: int
    budget_ms: Optional[float]
    reason: str


class DecodeScheduler:
    """Iteration-level planner — `AdaptiveBatchController` generalized
    from "plan one dispatch" to "plan each decode step".

    Two per-bucket EWMA cost models (the PR 11 `BucketCostModel`, one
    labelled phase each) track what a decode step at kv bucket B and a
    prefill at prompt bucket P actually cost on this host. With a
    `deadline_ms`, admissions are budgeted: every prefill delays every
    in-flight sequence's next token by its full cost, so the scheduler
    admits prompts only while (step cost + admitted prefill costs)
    stays inside the deadline — EXCEPT when no sequence is in flight,
    where there is nothing to stall and the pool is the only limit.
    Unknown costs (cold buckets) admit optimistically; the EWMA learns
    from the very first observed step."""

    def __init__(self, kv_buckets: Sequence[int],
                 prompt_buckets: Sequence[int],
                 registry=None, labels: Optional[Dict[str, str]] = None,
                 deadline_ms: Optional[float] = None,
                 margin_ms: float = 2.0, alpha: float = 0.2,
                 max_prefills_per_step: Optional[int] = None,
                 chunk_buckets: Optional[Sequence[int]] = None):
        labels = dict(labels or {})
        self.kv_buckets = sorted(int(b) for b in kv_buckets)
        self.prompt_buckets = sorted(int(b) for b in prompt_buckets)
        self.chunk_buckets = sorted(int(b) for b in chunk_buckets) \
            if chunk_buckets else list(self.prompt_buckets)
        self.deadline_ms = deadline_ms
        self.margin_ms = float(margin_ms)
        self.max_prefills_per_step = max_prefills_per_step
        self.step_cost = BucketCostModel(
            self.kv_buckets, registry, alpha=alpha,
            labels={**labels, "phase": "decode_step"})
        self.prefill_cost = BucketCostModel(
            sorted(set(self.prompt_buckets) | set(self.chunk_buckets)),
            registry, alpha=alpha,
            labels={**labels, "phase": "prefill"})

    def prompt_bucket(self, n: int) -> int:
        return _next_bucket(n, self.prompt_buckets)

    def chunk_bucket(self, n: int) -> int:
        return _next_bucket(n, self.chunk_buckets)

    def kv_bucket_for(self, needed: int) -> int:
        return _next_bucket(needed, self.kv_buckets)

    def plan_step(self, waiting_prompt_lens: Sequence[int],
                  free_slots: int, active_lengths: Sequence[int]
                  ) -> StepPlan:
        """`waiting_prompt_lens`: prompt length per queued request, in
        queue order. `active_lengths`: live KV length (pos + 1 of the
        NEXT step) per in-flight sequence."""
        cap = min(len(waiting_prompt_lens), int(free_slots))
        if self.max_prefills_per_step is not None:
            cap = min(cap, int(self.max_prefills_per_step))
        needed = max(active_lengths) if active_lengths else 1
        budget = None
        reason = "free-slots" if cap else (
            "pool-full" if waiting_prompt_lens else "no-waiting")
        admit = cap
        if cap and active_lengths and self.deadline_ms:
            bucket = self.kv_bucket_for(needed)
            step_ms = self.step_cost.cost_ms(bucket) or 0.0
            budget = self.deadline_ms - self.margin_ms - step_ms
            admit, spent = 0, 0.0
            for n in waiting_prompt_lens[:cap]:
                pb = self.prompt_bucket(n)
                c = self.prefill_cost.cost_ms(pb)
                spent += c if c is not None else 0.0
                if admit and spent > budget:
                    break
                admit += 1
            if admit < cap:
                reason = "deadline"
        for n in waiting_prompt_lens[:admit]:
            needed = max(needed, n + 1)
        return StepPlan(admit=admit,
                        kv_bucket=self.kv_bucket_for(needed),
                        budget_ms=budget, reason=reason)

    def plan_paged_step(self, waiting_prompt_lens: Sequence[int],
                        free_lanes: int,
                        prefilling_remaining: Sequence[int],
                        active_lengths: Sequence[int],
                        chunk_cap: int) -> PagedStepPlan:
        """The paged generalization of `plan_step`: prefill work is now
        CHUNKS (each `<= chunk_cap` tokens), and sequences already mid-
        prefill are budgeted BEFORE new admissions — a half-fed prompt
        holds blocks and a lane, so starving it in favor of fresh
        arrivals only grows held-but-idle memory. At least one chunk
        always advances per step when any prefill is pending (the
        starvation guard); the deadline budget trims everything beyond
        that, exactly like the contiguous planner."""
        cap = min(len(waiting_prompt_lens), int(free_lanes))
        total_cap = len(prefilling_remaining) + cap
        if self.max_prefills_per_step is not None:
            total_cap = min(total_cap,
                            max(1, int(self.max_prefills_per_step)))
        chunks = min(len(prefilling_remaining), total_cap)
        admit = min(cap, total_cap - chunks)
        needed = max(active_lengths) if active_lengths else 1
        budget = None
        reason = "free-lanes" if (admit or chunks) else (
            "pool-full" if waiting_prompt_lens else "no-waiting")
        if (chunks or admit) and active_lengths and self.deadline_ms:
            bucket = self.kv_bucket_for(needed)
            step_ms = self.step_cost.cost_ms(bucket) or 0.0
            budget = self.deadline_ms - self.margin_ms - step_ms
            spent, n_chunks, n_admit = 0.0, 0, 0
            for rem in prefilling_remaining[:chunks]:
                cb = self.chunk_bucket(min(int(rem), int(chunk_cap)))
                c = self.prefill_cost.cost_ms(cb)
                spent += c if c is not None else 0.0
                if n_chunks and spent > budget:
                    break
                n_chunks += 1
            for n in waiting_prompt_lens[:admit]:
                cb = self.chunk_bucket(min(int(n), int(chunk_cap)))
                c = self.prefill_cost.cost_ms(cb)
                spent += c if c is not None else 0.0
                if (n_chunks or n_admit) and spent > budget:
                    break
                n_admit += 1
            if n_chunks < chunks or n_admit < admit:
                reason = "deadline"
            chunks, admit = n_chunks, n_admit
        for n in waiting_prompt_lens[:admit]:
            needed = max(needed, int(n) + 1)
        return PagedStepPlan(admit=admit, chunks=chunks,
                             kv_bucket=self.kv_bucket_for(needed),
                             budget_ms=budget, reason=reason)

    def observe_step(self, kv_bucket: int, ms: float) -> None:
        self.step_cost.observe(kv_bucket, ms)

    def observe_prefill(self, prompt_bucket: int, ms: float) -> None:
        self.prefill_cost.observe(prompt_bucket, ms)


@dataclasses.dataclass
class _Sequence:
    uri: str
    rid: str                       # stream record id (acked at finish)
    prompt: np.ndarray             # int32 prompt ids
    max_new: int
    eos: Optional[int]
    stream: bool
    t_enqueue: float               # perf_counter at intake
    slot: int = -1
    pos: int = 0                   # live KV length
    gen: List[int] = dataclasses.field(default_factory=list)
    t_last: float = 0.0
    rows: int = 0                  # token rows written so far
    ttft_ms: Optional[float] = None
    finish: str = ""
    # paged-mode state (slot doubles as the decode LANE)
    blocks: List[int] = dataclasses.field(default_factory=list)
    cached: int = 0                # prompt tokens adopted from the cache
    filled: int = 0                # prompt tokens already in KV


class DecodeServing:
    """The decode-mode engine. The model must already be
    `load_generative()`-ed and `warmup_generative()`-ed with the SAME
    slots/max_kv_len/bucket ladders — the engine never compiles."""

    def __init__(self, model: InferenceModel,
                 init_kv: Callable[[int, int], Any],
                 broker: Optional[Broker] = None,
                 stream: str = STREAM,
                 slots: int = 8, max_kv_len: int = 128,
                 kv_buckets: Optional[Sequence[int]] = None,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 max_new_default: int = 32,
                 eos_id: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 max_prefills_per_step: Optional[int] = None,
                 max_waiting: int = 256,
                 engine_id: Optional[str] = None,
                 registry=None,
                 idle_block_ms: int = 50,
                 drain_timeout_s: float = 10.0,
                 paged: bool = False,
                 init_kv_blocks: Optional[Callable[[int, int], Any]] = None,
                 block_len: int = 16,
                 kv_blocks: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 prefix_cache: bool = True,
                 prefix_cache_blocks: Optional[int] = None,
                 chunk_buckets: Optional[Sequence[int]] = None):
        self.model = model
        self.broker = broker if isinstance(broker, Broker) \
            else connect_broker(broker)
        self.stream = stream
        self.result_key = f"result:{stream}"
        self.max_kv_len = int(max_kv_len)
        self.kv_buckets = sorted(kv_buckets) if kv_buckets \
            else _pow2_ladder(8, self.max_kv_len)
        self.prompt_buckets = sorted(prompt_buckets) if prompt_buckets \
            else _pow2_ladder(4, max(4, self.max_kv_len // 2))
        self.max_new_default = int(max_new_default)
        self.eos_id = eos_id
        self.max_waiting = int(max_waiting)
        self.engine_id = engine_id or f"decode-{uuid.uuid4().hex[:8]}"
        self.consumer = self.engine_id
        self.idle_block_ms = int(idle_block_ms)
        self.drain_timeout_s = float(drain_timeout_s)
        if registry is None:
            from analytics_zoo_tpu.observability.registry import get_registry
            registry = get_registry()
        self.registry = registry
        labels = {"engine": self.engine_id}
        self.paged = bool(paged)
        self.block_len = int(block_len)
        self.prefill_chunk = int(prefill_chunk) if prefill_chunk else None
        if self.paged:
            if init_kv_blocks is None:
                raise ValueError("paged mode needs init_kv_blocks")
            if self.max_kv_len % self.block_len:
                raise ValueError(
                    f"max_kv_len {self.max_kv_len} not a multiple of "
                    f"block_len {self.block_len}")
            bad = [b for b in self.kv_buckets if b % self.block_len]
            if bad:
                raise ValueError(
                    f"kv buckets {bad} not multiples of block_len "
                    f"{self.block_len}")
            self.table_len = self.max_kv_len // self.block_len
            # default: byte-parity with the stripe pool it replaces
            # (same KV bytes reachable, + the scratch block)
            self.kv_blocks = int(kv_blocks) if kv_blocks else (
                int(slots) * self.table_len + 1)
            self.lanes = int(slots)
            self._free_lanes = list(range(self.lanes - 1, -1, -1))
            self.pool = None
            self.block_pool = KVBlockPool(
                init_kv_blocks, self.kv_blocks, self.block_len,
                registry=registry, labels=labels)
            self.prefix_cache = PrefixCache(
                self.block_pool, registry=registry, labels=labels,
                max_blocks=prefix_cache_blocks) if prefix_cache else None
            if chunk_buckets:
                self.chunk_buckets = sorted(int(b) for b in chunk_buckets)
            elif self.prefill_chunk:
                self.chunk_buckets = [
                    b for b in self.prompt_buckets
                    if b <= self.prefill_chunk] or [self.prompt_buckets[0]]
            else:
                self.chunk_buckets = list(self.prompt_buckets)
            # a chunk can never exceed the ladder's top bucket
            self.chunk_cap = min(self.prefill_chunk or
                                 self.chunk_buckets[-1],
                                 self.chunk_buckets[-1])
        else:
            self.pool = KVSlotPool(init_kv, slots, self.max_kv_len,
                                   registry=registry, labels=labels)
            self.block_pool = None
            self.prefix_cache = None
            self.chunk_buckets = list(self.prompt_buckets)
            self.chunk_cap = self.chunk_buckets[-1]
        self.scheduler = DecodeScheduler(
            self.kv_buckets, self.prompt_buckets, registry=registry,
            labels=labels, deadline_ms=deadline_ms,
            max_prefills_per_step=max_prefills_per_step,
            chunk_buckets=self.chunk_buckets)
        self._chunks_total = registry.counter(
            "serving_prefill_chunks_total",
            "prefill chunks executed by the paged decode engine (a "
            "prompt split across N chunks counts N) — chunking is what "
            "bounds ITL while long prompts join")
        self._tokens_total = registry.counter(
            "serving_tokens_total",
            "generated tokens written back by the decode engine")
        self._ttft_hist = registry.histogram(
            "serving_ttft_ms",
            "time to first token: record enqueue to the first generated "
            "token's writeback (prefill queue + prefill + first argmax) "
            "— the generative SLO's latency input")
        self._itl_hist = registry.histogram(
            "serving_itl_ms",
            "inter-token latency between consecutive generated tokens "
            "of one sequence — the streaming smoothness SLO input")
        self._waiting: deque = deque()
        self._prefilling: deque = deque()           # paged: mid-prompt
        self._active: Dict[int, _Sequence] = {}     # slot/lane -> sequence
        self._stop = threading.Event()
        self._drain_deadline: Optional[float] = None
        self._thread: Optional[threading.Thread] = None
        self.stats: Dict[str, int] = {
            "steps": 0, "slot_steps_active": 0, "slot_steps_total": 0,
            "tokens": 0, "prefills": 0, "finished": 0, "shed": 0,
            "failed": 0, "prefill_chunks": 0, "prefix_hit_tokens": 0}

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "DecodeServing":
        self._stop.clear()
        self._drain_deadline = None
        self._thread = threading.Thread(target=self.run,
                                        name="decode-engine", daemon=True)
        self._thread.start()
        return self

    def stop(self, drain: bool = True):
        """Stop the loop; with `drain` (default) keep stepping until
        every in-flight sequence finishes or `drain_timeout_s` runs
        out. Un-drained records redeliver to a peer (at-least-once)."""
        self._drain_deadline = time.monotonic() + (
            self.drain_timeout_s if drain else 0.0)
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=self.drain_timeout_s + 10.0)
        self._thread = None

    def is_alive(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    # -- record intake -----------------------------------------------------
    def _parse_record(self, rid, rec) -> Optional[_Sequence]:
        from analytics_zoo_tpu.serving.pre_post import decode_record_field
        data = rec["data"]
        raw = data["t"] if "t" in data else data[next(iter(data))]
        prompt = np.asarray(decode_record_field(raw)).astype(np.int32)
        prompt = prompt.reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if prompt.size + 1 > self.max_kv_len:
            raise ValueError(
                f"prompt length {prompt.size} leaves no room to "
                f"generate under max_kv_len={self.max_kv_len}")
        max_new = int(data.get("max_new", self.max_new_default))
        # a sequence can never outgrow its slot row
        max_new = max(1, min(max_new, self.max_kv_len - prompt.size))
        eos = data.get("eos", self.eos_id)
        return _Sequence(
            uri=rec["uri"], rid=rid, prompt=prompt, max_new=max_new,
            eos=None if eos is None else int(eos),
            stream=str(data.get("stream", "")) in ("1", "true", "True"),
            t_enqueue=time.perf_counter())

    def _free_capacity(self) -> int:
        return len(self._free_lanes) if self.paged \
            else self.pool.free_count

    def _intake(self):
        if self._stop.is_set():
            return
        idle = (not self._active and not self._waiting
                and not self._prefilling)
        count = max(1, self._free_capacity() + self.max_waiting
                    - len(self._waiting))
        records = self.broker.read_group(
            self.stream, GROUP, self.consumer, count,
            block_ms=self.idle_block_ms if idle else 0)
        failed = []
        for rid, rec in records:
            try:
                self._waiting.append(self._parse_record(rid, rec))
            except Exception as e:  # noqa: BLE001 — degrade per record
                uri = rec.get("uri", str(rid)) if isinstance(rec, dict) \
                    else str(rid)
                log.warning("decode intake failure for %s: %s", uri, e)
                failed.append((rid, uri))
        if failed:
            self.stats["failed"] += len(failed)
            self.broker.writeback(
                self.result_key, {u: "NaN" for _, u in failed},
                self.stream, GROUP, [r for r, _ in failed])
        # overload: answer the newest arrivals with SHED (the oldest
        # queued are closest to boarding — shedding them wastes wait)
        shed = []
        while len(self._waiting) > self.max_waiting:
            shed.append(self._waiting.pop())
        if shed:
            self.stats["shed"] += len(shed)
            self.broker.writeback(
                self.result_key, {s.uri: "SHED" for s in shed},
                self.stream, GROUP, [s.rid for s in shed])

    # -- token emission ----------------------------------------------------
    def _emit(self, seq: _Sequence, token: int, now: float,
              token_rows: Dict[str, str]):
        if not seq.gen:
            seq.ttft_ms = (now - seq.t_enqueue) * 1e3
            self._ttft_hist.observe(seq.ttft_ms, engine=self.engine_id)
        else:
            self._itl_hist.observe((now - seq.t_last) * 1e3,
                                   engine=self.engine_id)
        seq.t_last = now
        seq.gen.append(int(token))
        if seq.stream:
            token_rows[token_row_field(seq.uri, seq.rows)] = json.dumps(
                {"i": seq.rows, "t": int(token),
                 "ms": round((now - seq.t_enqueue) * 1e3, 3)})
            seq.rows += 1
        self.stats["tokens"] += 1
        if seq.eos is not None and int(token) == seq.eos:
            seq.finish = "eos"
        elif len(seq.gen) >= seq.max_new:
            seq.finish = "length"
        elif seq.pos >= self.max_kv_len:
            seq.finish = "kv-full"

    def _final_blob(self, seq: _Sequence) -> str:
        blob = encode_ndarray(np.asarray(seq.gen, np.int32))
        blob["gen"] = {"n": len(seq.gen), "rows": seq.rows,
                       "finish": seq.finish,
                       "ttft_ms": round(seq.ttft_ms or 0.0, 3)}
        return json.dumps(blob)

    # -- the step loop -----------------------------------------------------
    def _run_step(self):
        plan = self.scheduler.plan_step(
            [s.prompt.size for s in self._waiting],
            self.pool.free_count,
            [s.pos + 1 for s in self._active.values()])
        token_rows: Dict[str, str] = {}
        finished: List[_Sequence] = []
        for _ in range(plan.admit):
            seq = self._waiting.popleft()
            slot = self.pool.lease()
            if slot is None:       # raced with nothing — defensive only
                self._waiting.appendleft(seq)
                break
            pb = self.scheduler.prompt_bucket(seq.prompt.size)
            padded = np.zeros(pb, np.int32)
            padded[:seq.prompt.size] = seq.prompt
            t0 = time.perf_counter()
            self.pool.kv, logits = self.model.generative_prefill(
                self.pool.kv, padded, seq.prompt.size, slot)
            first = int(np.asarray(logits).argmax())   # forces the sync
            dt = time.perf_counter() - t0
            self.scheduler.observe_prefill(pb, dt * 1e3)
            self.model.account_generative("prefill", pb, dt)
            seq.slot, seq.pos = slot, int(seq.prompt.size)
            self._active[slot] = seq
            self.stats["prefills"] += 1
            self._emit(seq, first, time.perf_counter(), token_rows)
            if seq.finish:
                finished.append(seq)
        for seq in finished:       # finished straight out of prefill
            del self._active[seq.slot]
        if self._active:
            slots_arr = np.zeros(self.pool.slots, np.int32)
            pos_arr = np.zeros(self.pool.slots, np.int32)
            for slot, seq in self._active.items():
                slots_arr[slot] = seq.gen[-1]
                pos_arr[slot] = seq.pos
            bucket = self.scheduler.kv_bucket_for(
                max(s.pos + 1 for s in self._active.values()))
            t0 = time.perf_counter()
            self.pool.kv, logits = self.model.generative_step(
                self.pool.kv, slots_arr, pos_arr, bucket)
            nxt = np.asarray(logits).argmax(axis=-1)   # forces the sync
            dt = time.perf_counter() - t0
            self.scheduler.observe_step(bucket, dt * 1e3)
            self.model.account_generative("step", bucket, dt)
            now = time.perf_counter()
            self.stats["steps"] += 1
            self.stats["slot_steps_total"] += self.pool.slots
            self.stats["slot_steps_active"] += len(self._active)
            for slot, seq in list(self._active.items()):
                seq.pos += 1
                self._emit(seq, int(nxt[slot]), now, token_rows)
                if seq.finish:
                    finished.append(seq)
                    del self._active[slot]
        self._flush(token_rows, finished)
        for seq in finished:
            self.pool.release(seq.slot)

    def _flush(self, token_rows: Dict[str, str],
               finished: List[_Sequence]):
        """ONE broker interaction per step: every sequence's token rows
        AND any finals land in the same fused ``writeback`` (HSET +
        XACK), so a step's host-side bookkeeping cost is flat in the
        number of tokens emitted — the per-row HSET the BENCH_r10
        narrative measured is gone. Steps with no finals stay a single
        ``hset_many``; the shared HSET keeps the final-commits-with-rows
        ordering (a streaming client can never see the final field
        before the rows it summarizes)."""
        if finished:
            finals = {s.uri: self._final_blob(s) for s in finished}
            self.broker.writeback(
                self.result_key, {**token_rows, **finals},
                self.stream, GROUP, [s.rid for s in finished])
            self.stats["finished"] += len(finished)
        elif token_rows:
            self.broker.hset_many(self.result_key, token_rows)

    # -- the paged step loop (ISSUE 19) ------------------------------------
    def _alloc_block(self) -> Optional[int]:
        """One pool block, evicting cold cached prefixes if needed."""
        b = self.block_pool.alloc()
        if b is None and self.prefix_cache is not None:
            self.prefix_cache.evict_for(1)
            b = self.block_pool.alloc()
        return b

    def _release_paged(self, seq: _Sequence):
        for b in seq.blocks:
            self.block_pool.release(b)
        seq.blocks = []
        if seq.slot >= 0:
            self._free_lanes.append(seq.slot)
            seq.slot = -1

    def _admit_paged(self, seq: _Sequence) -> bool:
        """Lease a lane and the prompt's blocks; adopt every fully-
        matching prefix-cache block copy-free (that span of prefill is
        skipped). On block exhaustion everything is rolled back and the
        caller requeues the sequence — admission is all-or-nothing."""
        bl = self.block_len
        adopted = self.prefix_cache.match(seq.prompt.tolist()) \
            if self.prefix_cache is not None else []
        cached = len(adopted) * bl
        need = -(-(int(seq.prompt.size) - cached) // bl)
        got: List[int] = []
        for _ in range(need):
            b = self._alloc_block()
            if b is None:
                for x in got + adopted:
                    self.block_pool.release(x)
                return False
            got.append(b)
        if not self._free_lanes:      # raced with nothing — defensive
            for x in got + adopted:
                self.block_pool.release(x)
            return False
        seq.slot = self._free_lanes.pop()
        seq.blocks = adopted + got
        seq.cached = seq.filled = cached
        if cached:
            self.stats["prefix_hit_tokens"] += cached
        return True

    def _prefill_chunk_step(self, seq: _Sequence,
                            token_rows: Dict[str, str]):
        """Run ONE chunk of `seq`'s remaining prompt through the warmed
        paged-prefill executable for its (chunk bucket, context bucket).
        The final chunk produces the first generated token and publishes
        the prompt's full blocks to the prefix cache."""
        bl = self.block_len
        remaining = int(seq.prompt.size) - seq.filled
        chunk = min(remaining, self.chunk_cap)
        cb = self.scheduler.chunk_bucket(chunk)
        padded = np.zeros(cb, np.int32)
        padded[:chunk] = seq.prompt[seq.filled:seq.filled + chunk]
        kvb = 0 if seq.filled == 0 \
            else self.scheduler.kv_bucket_for(seq.filled)
        table = np.zeros(self.table_len, np.int32)
        table[:len(seq.blocks)] = seq.blocks
        t0 = time.perf_counter()
        self.block_pool.kv, logits = self.model.generative_prefill_paged(
            self.block_pool.kv, padded, table, seq.filled, chunk, kvb)
        done = seq.filled + chunk >= int(seq.prompt.size)
        logits_h = np.asarray(logits)      # forces the sync
        dt = time.perf_counter() - t0
        self.scheduler.observe_prefill(cb, dt * 1e3)
        self.model.account_generative("paged_prefill", (cb, kvb), dt)
        self._chunks_total.inc(engine=self.engine_id)
        self.stats["prefill_chunks"] += 1
        seq.filled += chunk
        if done:
            seq.pos = int(seq.prompt.size)
            self.stats["prefills"] += 1
            if self.prefix_cache is not None:
                n_full = int(seq.prompt.size) // bl
                if n_full:
                    self.prefix_cache.insert(seq.prompt.tolist(),
                                             seq.blocks[:n_full])
            self._emit(seq, int(logits_h.argmax()),
                       time.perf_counter(), token_rows)

    def _ensure_block(self, seq: _Sequence) -> bool:
        """Grow the sequence's table to cover its next write position
        (block-by-block, the paged discipline's whole point)."""
        while seq.pos // self.block_len >= len(seq.blocks):
            b = self._alloc_block()
            if b is None:
                return False
            seq.blocks.append(b)
        return True

    def _settle_prefill(self, seq: _Sequence,
                        finished: List[_Sequence]):
        if seq.filled < int(seq.prompt.size):
            self._prefilling.append(seq)
        elif seq.finish:
            finished.append(seq)
        else:
            self._active[seq.slot] = seq

    def _run_paged_step(self):
        plan = self.scheduler.plan_paged_step(
            [s.prompt.size for s in self._waiting],
            len(self._free_lanes),
            [int(s.prompt.size) - s.filled for s in self._prefilling],
            [s.pos + 1 for s in self._active.values()],
            self.chunk_cap)
        token_rows: Dict[str, str] = {}
        finished: List[_Sequence] = []
        # mid-prefill sequences advance first (they hold blocks + lanes)
        for _ in range(plan.chunks):
            seq = self._prefilling.popleft()
            self._prefill_chunk_step(seq, token_rows)
            self._settle_prefill(seq, finished)
        for _ in range(plan.admit):
            seq = self._waiting.popleft()
            if not self._admit_paged(seq):
                self._waiting.appendleft(seq)
                break
            self._prefill_chunk_step(seq, token_rows)
            self._settle_prefill(seq, finished)
        if self._active:
            # a lane whose next write position has no block left (pool
            # exhausted even after cache eviction) answers with what it
            # generated rather than holding the lane forever
            for lane, seq in list(self._active.items()):
                if not self._ensure_block(seq):
                    seq.finish = "blocks-full"
                    finished.append(seq)
                    del self._active[lane]
        if self._active:
            tokens_arr = np.zeros(self.lanes, np.int32)
            pos_arr = np.zeros(self.lanes, np.int32)
            tables = np.zeros((self.lanes, self.table_len), np.int32)
            for lane, seq in self._active.items():
                tokens_arr[lane] = seq.gen[-1]
                pos_arr[lane] = seq.pos
                tables[lane, :len(seq.blocks)] = seq.blocks
            bucket = self.scheduler.kv_bucket_for(
                max(s.pos + 1 for s in self._active.values()))
            t0 = time.perf_counter()
            self.block_pool.kv, logits = self.model.generative_step_paged(
                self.block_pool.kv, tokens_arr, pos_arr, tables, bucket)
            nxt = np.asarray(logits).argmax(axis=-1)   # forces the sync
            dt = time.perf_counter() - t0
            self.scheduler.observe_step(bucket, dt * 1e3)
            self.model.account_generative("paged_step", bucket, dt)
            now = time.perf_counter()
            self.stats["steps"] += 1
            self.stats["slot_steps_total"] += self.lanes
            self.stats["slot_steps_active"] += len(self._active)
            for lane, seq in list(self._active.items()):
                seq.pos += 1
                self._emit(seq, int(nxt[lane]), now, token_rows)
                if seq.finish:
                    finished.append(seq)
                    del self._active[lane]
        self._flush(token_rows, finished)
        for seq in finished:
            self._release_paged(seq)

    def run(self):
        """The engine loop (inline-callable for tests; `start()` wraps
        it in a thread). Every iteration: intake → plan → prefill
        admissions → one batched decode step → writebacks."""
        emitted_before = self.stats["tokens"]
        step = self._run_paged_step if self.paged else self._run_step
        while True:
            if self._stop.is_set():
                drained = (not self._active and not self._waiting
                           and not self._prefilling)
                if drained or (self._drain_deadline is not None
                               and time.monotonic() > self._drain_deadline):
                    break
            self._intake()
            before = self.stats["tokens"]
            step()
            delta = self.stats["tokens"] - before
            if delta:
                self._tokens_total.inc(delta, engine=self.engine_id)
        if self.stats["tokens"] != emitted_before:
            log.info("decode engine %s: %s", self.engine_id, self.stats)

    def utilization(self) -> float:
        """Useful slot-steps over total slot-steps — the bench's
        headline ratio vs the pad-to-max baseline."""
        total = self.stats["slot_steps_total"]
        return self.stats["slot_steps_active"] / total if total else 0.0
