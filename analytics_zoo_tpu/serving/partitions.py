"""Partitioned request plane (ISSUE 16): streams, leases, leadership.

One broker stream was the request plane's last single bottleneck: every
record funnelled through one append path and one sink commit path, and
every gateway control loop ran in exactly one process. This module holds
the two primitives that shard and replicate it:

- **Partition routing** — `N` streams named ``<stream>.p<i>``, a record
  landing on the partition its uri hashes to (stable CRC32, so any
  client/gateway/engine computes the same route with no coordination).
  ``partitions=1`` keeps the legacy single-stream name byte-for-byte, so
  default configs behave identically. Results from every partition land
  in the ONE ``result:<stream>`` hash — clients poll one place no matter
  how the request fanned out.

- **`PartitionLeaseTable`** — engines own partition *sets* via lease
  rows in the broker hash ``partitions:<stream>``. Liveness is the
  FleetTracker discipline: a lease is held while its row makes
  PROGRESS (content changes under the observer's own monotonic clock),
  never by comparing cross-host timestamps. Expiry generalizes the PR
  10 claim sweep from records to whole partitions: a dead engine's
  partitions are taken over by live peers after ``ttl_s`` of silence,
  and the taken-over partition's unacked records then redeliver through
  the ordinary claim sweep. Membership rows make newcomers visible
  before they own anything, so incumbents shed down to the fair share
  ``ceil(partitions / members)`` and the fleet rebalances without a
  coordinator. Acquisition is write-then-verify: the broker serializes
  HSETs, so whoever's nonce survives the read-back owns the lease —
  brief dual reads during a race are safe because partitions are
  consumer-group streams (co-consumption was already correct).

- **`GatewayLeaderLease`** — the same write-then-verify lease on one
  ``leader`` row in ``gateway:<stream>``, held by whichever gateway
  replica currently runs the fleet control loops (rollout campaign,
  autoscaler). Every replica serves reads (`/predict`, `/healthz`,
  `/rollout` status) from broker-derived state; killing the leader
  just moves the lease after ``ttl_s`` and the new leader re-derives
  the in-flight rollout from the control hash. The per-gateway
  ``gateway_role`` gauge and ``gateway_leader_changes_total`` counter
  make a failover visible on a scrape.

Registry families: ``serving_partitions_owned`` (per-engine gauge),
``serving_partition_lease_changes_total{event,partition}`` (lease
churn), ``gateway_role`` (1 leader / 0 follower),
``gateway_leader_changes_total``.
"""

from __future__ import annotations

import json
import logging
import math
import threading
import time
import uuid
import zlib
from typing import Dict, List, Optional, Tuple

log = logging.getLogger("analytics_zoo_tpu.serving.partitions")

PARTITIONS_KEY_PREFIX = "partitions:"
GATEWAY_KEY_PREFIX = "gateway:"
MAX_PARTITIONS = 1024


def partitions_key(stream: str) -> str:
    """The broker hash carrying the partition lease table."""
    return PARTITIONS_KEY_PREFIX + stream


def gateway_key(stream: str) -> str:
    """The broker hash carrying the gateway leader lease."""
    return GATEWAY_KEY_PREFIX + stream


def validate_partitions(n) -> int:
    n = int(n)
    if not 1 <= n <= MAX_PARTITIONS:
        raise ValueError(
            f"partitions={n} must be in [1, {MAX_PARTITIONS}]")
    return n


def partition_of(uri: str, partitions: int) -> int:
    """Stable uri -> partition map (CRC32 mod N): every client, gateway
    and engine computes the same route with no shared state. CRC32 is
    deterministic across processes and platforms — `hash()` is salted
    per interpreter and would split one uri across the fleet."""
    if partitions <= 1:
        return 0
    return zlib.crc32(str(uri).encode()) % partitions


def partition_stream(stream: str, index: int, partitions: int) -> str:
    """Partition `index`'s stream name. One partition keeps the legacy
    unsuffixed name so ``partitions=1`` deployments are byte-identical
    with every earlier release (same stream, same PEL, same bench)."""
    if partitions <= 1:
        return stream
    return f"{stream}.p{index}"


def partition_streams(stream: str, partitions: int) -> List[str]:
    return [partition_stream(stream, i, partitions)
            for i in range(max(1, int(partitions)))]


def stream_for(stream: str, uri: str, partitions: int) -> str:
    return partition_stream(stream, partition_of(uri, partitions),
                            partitions)


class _ProgressClock:
    """Content-progress aging, the FleetTracker liveness discipline: a
    row is fresh while its CONTENT keeps changing as observed on THIS
    process's monotonic clock. Cross-host timestamps are never compared
    — a skewed peer that keeps renewing stays alive, a dead one ages
    out no matter what its final timestamp claimed."""

    def __init__(self):
        self._seen: Dict[str, Tuple[str, float]] = {}

    def age(self, field: str, content: Optional[str], now: float) -> float:
        """Seconds since `field`'s content last changed (0.0 on first
        sight or any change). None content forgets the field."""
        if content is None:
            self._seen.pop(field, None)
            return 0.0
        last = self._seen.get(field)
        if last is None or last[0] != content:
            self._seen[field] = (content, now)
            return 0.0
        return now - last[1]

    def forget(self, field: str):
        self._seen.pop(field, None)


class PartitionLeaseTable:
    """One engine's view of (and claim on) the partition lease table.

    The owning engine calls `poll()` from its reader loop (rate-limited
    there, like the claim sweep): each pass renews owned leases,
    refreshes this engine's membership row, takes over expired or
    unclaimed partitions up to the fair share, and sheds surplus ones
    when new members arrive. All broker I/O stays in the caller's
    thread — no thread of its own, nothing to leak on an engine crash
    (the whole point: a crashed engine simply stops renewing).

    Lease row (field ``p<i>``): JSON ``{"owner", "nonce", "ts"}`` — the
    nonce is what write-then-verify compares, ts is a human-debugging
    aid (never compared across hosts). Membership row (field
    ``member:<owner>``): JSON ``{"ts"}`` renewed every poll."""

    def __init__(self, broker, stream: str, partitions: int,
                 owner: str, ttl_s: float = 5.0, registry=None):
        if not owner:
            raise ValueError("partition leases need an owner identity "
                             "(set engine_id)")
        if ttl_s <= 0:
            raise ValueError(f"ttl_s={ttl_s} must be > 0")
        self.broker = broker
        self.stream = stream
        self.partitions = validate_partitions(partitions)
        self.owner = str(owner)
        self.ttl_s = float(ttl_s)
        self.key = partitions_key(stream)
        self._nonce: Dict[int, str] = {}      # partition -> my nonce
        self._clock = _ProgressClock()
        self._lock = threading.Lock()
        if registry is None:
            from analytics_zoo_tpu.observability.registry import get_registry
            registry = get_registry()
        self._owned_gauge = registry.gauge(
            "serving_partitions_owned",
            "partitions this engine currently holds a lease on")
        self._owned_fn = lambda: float(len(self._nonce))
        self._owned_gauge.set_function(self._owned_fn,
                                       engine=self.owner)
        self._changes = registry.counter(
            "serving_partition_lease_changes_total",
            "partition lease transitions (acquired, takeover, released, "
            "lost) by event and partition")

    # -- meta guard (the resharding gate) ----------------------------------
    def ensure_meta(self, reshard: bool = False) -> int:
        """Record (or verify) the stream's partition count in the lease
        table. A mismatch means records already routed under a
        different count are in flight — joining anyway would strand
        every record whose partition nobody reads. Refused unless the
        operator passes the explicit resharding flag, which rewrites
        the meta row and clears stale leases (the operator owns
        draining or migrating the old partitions)."""
        raw = None
        try:
            raw = self.broker.hget(self.key, "meta")
        except Exception:  # noqa: BLE001 — unreadable meta: write ours
            raw = None
        current = None
        if raw:
            try:
                current = int(json.loads(raw).get("partitions"))
            except (TypeError, ValueError, AttributeError):
                current = None
        if current is not None and current != self.partitions:
            if not reshard:
                raise ValueError(
                    f"stream {self.stream!r} is partitioned "
                    f"{current}-way but this process wants "
                    f"{self.partitions}; changing the partition count "
                    "under a live fleet strands in-flight records — "
                    "drain the fleet or pass the explicit resharding "
                    "flag (--reshard / reshard: true)")
            stale = [f for f in self._all_rows()
                     if f.startswith("p") or f.startswith("member:")]
            if stale:
                self.broker.hdel_many(self.key, stale)
            log.warning("resharding %s: %d -> %d partitions (stale "
                        "leases cleared)", self.stream, current,
                        self.partitions)
        self.broker.hset(self.key, "meta",
                         json.dumps({"partitions": self.partitions,
                                     "by": self.owner,
                                     "ts": time.time()}))
        return self.partitions

    def _all_rows(self) -> Dict[str, str]:
        try:
            return self.broker.hgetall(self.key) or {}
        except Exception:  # noqa: BLE001 — caller treats as empty view
            return {}

    # -- the lease pass ----------------------------------------------------
    def poll(self, now: Optional[float] = None) -> List[int]:
        """One lease pass; returns the partitions owned after it. Safe
        to call at any cadence; the engine paces it at ~ttl/3 so a
        lease survives two missed polls before expiring."""
        now = time.monotonic() if now is None else now
        with self._lock:
            rows = self._all_rows()
            members = self._members(rows, now)
            target = max(1, math.ceil(self.partitions / max(len(members),
                                                            1)))
            # membership heartbeat: content must CHANGE each renewal so
            # peers observe progress (ts is the changing payload)
            try:
                self.broker.hset(self.key, f"member:{self.owner}",
                                 json.dumps({"ts": time.time()}))
            except Exception:  # noqa: BLE001 — renewed next poll
                pass
            claimable: List[int] = []
            for p in range(self.partitions):
                field = f"p{p}"
                raw = rows.get(field)
                lease = self._parse(raw)
                if p in self._nonce:
                    if lease is None or \
                            lease.get("nonce") != self._nonce[p]:
                        # overwritten by a peer (race we lost) or
                        # deleted: the broker's serialized row is the
                        # truth — stop reading this partition
                        self._drop(p, "lost")
                        continue
                    self._renew(p)
                    continue
                age = self._clock.age(field, raw, now)
                if lease is None or age > self.ttl_s:
                    claimable.append(p)
            for p in claimable:
                if len(self._nonce) >= target:
                    break
                self._acquire(p, taken_over=bool(rows.get(f"p{p}")))
            # fair-share shed: newcomers showed up in the member rows —
            # release the highest partitions first so the steady-state
            # assignment is contiguous and deterministic
            while len(self._nonce) > target:
                self._release_one(max(self._nonce))
            self._purge_stale_members(rows, now)
            return sorted(self._nonce)

    def _members(self, rows: Dict[str, str], now: float) -> List[str]:
        alive = {self.owner}
        for field, raw in rows.items():
            if not field.startswith("member:"):
                continue
            if self._clock.age(field, raw, now) <= self.ttl_s:
                alive.add(field[len("member:"):])
        return sorted(alive)

    def _purge_stale_members(self, rows: Dict[str, str], now: float):
        # long-dead member rows (10x ttl, the FleetTracker purge
        # discipline) must not shrink everyone's share forever
        dead = [f for f, raw in rows.items()
                if f.startswith("member:")
                and f != f"member:{self.owner}"
                and self._clock.age(f, raw, now) > 10 * self.ttl_s]
        if dead:
            try:
                self.broker.hdel_many(self.key, dead)
            except Exception:  # noqa: BLE001 — purged next poll
                return
            for f in dead:
                self._clock.forget(f)

    @staticmethod
    def _parse(raw: Optional[str]) -> Optional[Dict]:
        if not raw:
            return None
        try:
            d = json.loads(raw)
            return d if isinstance(d, dict) else None
        except (TypeError, ValueError):
            return None

    def _write(self, p: int, nonce: str):
        self.broker.hset(self.key, f"p{p}", json.dumps(
            {"owner": self.owner, "nonce": nonce, "ts": time.time()}))

    def _acquire(self, p: int, taken_over: bool):
        """Write-then-verify: HSETs serialize at the broker, so the
        nonce that survives the read-back owns the lease. Losing the
        race costs one wasted write, never a wrong owner."""
        nonce = uuid.uuid4().hex
        try:
            self._write(p, nonce)
            back = self._parse(self.broker.hget(self.key, f"p{p}"))
        except Exception:  # noqa: BLE001 — retried next poll
            return
        if back is not None and back.get("nonce") == nonce:
            self._nonce[p] = nonce
            event = "takeover" if taken_over else "acquired"
            self._changes.inc(event=event, partition=str(p))
            log.info("engine %s %s partition %d of %s", self.owner,
                     event, p, self.stream)

    def _renew(self, p: int):
        nonce = uuid.uuid4().hex   # content change IS the heartbeat
        try:
            self._write(p, nonce)
            self._nonce[p] = nonce
        except Exception:  # noqa: BLE001 — a missed renewal is
            pass           # absorbed by the ttl (~3 polls per ttl)

    def _drop(self, p: int, event: str):
        self._nonce.pop(p, None)
        self._changes.inc(event=event, partition=str(p))
        log.warning("engine %s %s partition %d of %s", self.owner,
                    event, p, self.stream)

    def _release_one(self, p: int):
        self._nonce.pop(p, None)
        try:
            self.broker.hdel(self.key, f"p{p}")
        except Exception:  # noqa: BLE001 — peers take it over by ttl
            pass
        self._clock.forget(f"p{p}")
        self._changes.inc(event="released", partition=str(p))

    # -- views / teardown --------------------------------------------------
    def owned(self) -> List[int]:
        with self._lock:
            return sorted(self._nonce)

    def owned_streams(self) -> List[str]:
        return [partition_stream(self.stream, p, self.partitions)
                for p in self.owned()]

    def release(self):
        """Clean shutdown: give every lease and the membership row back
        so peers rebalance immediately instead of waiting out the ttl.
        A SIGKILLed engine never runs this — that is the takeover
        path's job."""
        with self._lock:
            for p in list(self._nonce):
                self._release_one(p)
            try:
                self.broker.hdel(self.key, f"member:{self.owner}")
            except Exception:  # noqa: BLE001 — purged by peers at 10x ttl
                pass
        self._owned_gauge.release_function(self._owned_fn, freeze=True)

    def abandon(self):
        """Crash analogue (chaos tests): forget local state WITHOUT
        touching the broker rows — exactly the table a SIGKILLed engine
        leaves behind. Peers take the partitions over by ttl expiry,
        which is the takeover path under test."""
        with self._lock:
            self._nonce.clear()
        self._owned_gauge.release_function(self._owned_fn, freeze=True)


class GatewayLeaderLease:
    """Replicated-gateway leadership: one ``leader`` row in
    ``gateway:<stream>``, held by write-then-verify with progress-based
    expiry (same discipline as the partition leases). The holder runs
    the fleet control loops; every other replica serves reads and
    watches. `start()` paces the lease on a stop-event-timed daemon
    thread; tests drive `poll(now)` directly."""

    def __init__(self, broker, stream: str, gateway_id: str,
                 ttl_s: float = 3.0, registry=None):
        if not gateway_id:
            raise ValueError("a replicated gateway needs a gateway_id")
        if ttl_s <= 0:
            raise ValueError(f"ttl_s={ttl_s} must be > 0")
        self.broker = broker
        self.stream = stream
        self.gateway_id = str(gateway_id)
        self.ttl_s = float(ttl_s)
        self.key = gateway_key(stream)
        self._nonce: Optional[str] = None
        self._clock = _ProgressClock()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if registry is None:
            from analytics_zoo_tpu.observability.registry import get_registry
            registry = get_registry()
        self._role_gauge = registry.gauge(
            "gateway_role",
            "this gateway replica's control-plane role "
            "(1 leader, 0 follower)")
        self._role_fn = lambda: 1.0 if self._nonce is not None else 0.0
        self._role_gauge.set_function(self._role_fn,
                                      gateway=self.gateway_id)
        self._changes = registry.counter(
            "gateway_leader_changes_total",
            "leadership transitions observed by this gateway replica "
            "(elected, lost)")

    # -- lease pass --------------------------------------------------------
    def poll(self, now: Optional[float] = None) -> bool:
        """One leadership pass; returns True while this replica leads."""
        now = time.monotonic() if now is None else now
        with self._lock:
            try:
                raw = self.broker.hget(self.key, "leader")
            except Exception:  # noqa: BLE001 — broker blip: keep the
                # current belief; expiry math resumes next poll
                return self._nonce is not None
            row = self._parse(raw)
            if self._nonce is not None:
                if row is None or row.get("nonce") != self._nonce:
                    # a peer overwrote the row (we were partitioned
                    # away past the ttl): demote immediately
                    self._nonce = None
                    self._changes.inc(event="lost")
                    log.warning("gateway %s lost the leader lease",
                                self.gateway_id)
                else:
                    self._write()
                return self._nonce is not None
            age = self._clock.age("leader", raw, now)
            if row is not None and age <= self.ttl_s:
                return False                     # healthy leader exists
            nonce = uuid.uuid4().hex
            try:
                self._write(nonce)
                back = self._parse(self.broker.hget(self.key, "leader"))
            except Exception:  # noqa: BLE001 — retried next poll
                return False
            if back is not None and back.get("nonce") == nonce:
                self._nonce = nonce
                self._changes.inc(event="elected")
                log.info("gateway %s is now the leader for %s",
                         self.gateway_id, self.stream)
            return self._nonce is not None

    def _write(self, nonce: Optional[str] = None):
        nonce = nonce or uuid.uuid4().hex
        self.broker.hset(self.key, "leader", json.dumps(
            {"gateway": self.gateway_id, "nonce": nonce,
             "ts": time.time()}))
        if self._nonce is not None:
            self._nonce = nonce

    @staticmethod
    def _parse(raw: Optional[str]) -> Optional[Dict]:
        if not raw:
            return None
        try:
            d = json.loads(raw)
            return d if isinstance(d, dict) else None
        except (TypeError, ValueError):
            return None

    def is_leader(self) -> bool:
        return self._nonce is not None

    def leader(self) -> Optional[str]:
        """Who holds the lease right now (broker read; None unknown)."""
        try:
            row = self._parse(self.broker.hget(self.key, "leader"))
        except Exception:  # noqa: BLE001 — unknown during a blip
            return None
        return row.get("gateway") if row else None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "GatewayLeaderLease":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop,
                name=f"gateway-leader-{self.gateway_id}", daemon=True)
            self._thread.start()
        return self

    def _loop(self):
        interval = max(0.05, self.ttl_s / 3.0)
        while not self._stop.wait(interval):
            try:
                self.poll()
            except Exception as e:  # noqa: BLE001 — the lease must live
                log.warning("leader lease poll failed (%s: %s)",
                            type(e).__name__, e)

    def stop(self, release: bool = True):
        """`release=False` is the crash analogue (chaos tests): the row
        stays until a peer's ttl expires it."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        with self._lock:
            if release and self._nonce is not None:
                try:
                    self.broker.hdel(self.key, "leader")
                except Exception:  # noqa: BLE001 — peers expire it
                    pass
            if self._nonce is not None:
                self._nonce = None
        self._role_gauge.release_function(self._role_fn, freeze=True)
