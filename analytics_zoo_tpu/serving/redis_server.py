"""Self-contained RESP2 stream/hash server ("mini redis").

The serving data plane is reference-faithful Redis streams
(`FlinkRedisSource.scala:66-87`), but the deploy image carries no redis
binary — so the framework ships its own small RESP2 server implementing
exactly the command subset the stack uses: XADD / XGROUP CREATE
(MKSTREAM) / XREADGROUP (COUNT, BLOCK, ">") / XACK / XDEL and
HSET/HGET/HGETALL/HDEL. `RedisBroker` (`serving/broker.py`) talks to it
over the real wire protocol, so serving latency can be measured across a
genuine socket hop, and a production Redis can be swapped in with no code
change (same commands, same framing).

Blocking XREADGROUP is implemented with a condition variable: a BLOCK
window parks the reader until XADD signals, instead of busy-polling."""

from __future__ import annotations

import socket
import socketserver
import threading
import time
from typing import Dict, List, Optional, Tuple

from analytics_zoo_tpu.serving.broker import RESPError


class Simple(str):
    """Marker for RESP simple-string replies (+OK). Only command handlers
    construct it — a hash VALUE that happens to equal "OK" stays a plain
    str and is encoded as a bulk string, the type real Redis sends."""


# Marker for the *-1 nil-ARRAY reply (timed-out XREADGROUP). A bare None
# encodes as $-1 nil BULK — what real Redis sends for a missing HGET
# (divergence caught by tests/test_resp2_conformance.py).
NIL_ARRAY = object()


class MiniRedisStore:
    """In-memory streams + hashes with consumer-group semantics: per-group
    last-delivered cursor and pending-entries list (PEL). The PEL keeps
    per-entry consumer attribution and delivery time — what XAUTOCLAIM
    (the fleet's stale-pending claim sweep) and XPENDING read."""

    def __init__(self):
        self.streams: Dict[str, List[Tuple[str, List[str]]]] = {}
        self.groups: Dict[Tuple[str, str], Dict] = {}
        self.hashes: Dict[str, Dict[str, str]] = {}
        self.seq = 0
        self.lock = threading.Lock()
        self.data_ready = threading.Condition(self.lock)

    # -- command dispatch --------------------------------------------------
    def execute(self, args: List[str]):
        cmd = args[0].upper()
        handler = getattr(self, "cmd_" + cmd.lower(), None)
        if handler is None:
            raise RESPError(f"ERR unknown command '{cmd}'")
        if cmd == "XREADGROUP":
            # manages its own locking (may park on the condition)
            return handler(args[1:])
        with self.lock:
            return handler(args[1:])

    def cmd_xadd(self, a):
        stream, rid = a[0], a[1]
        if rid != "*":
            raise RESPError("ERR only auto-generated ids are supported")
        self.seq += 1
        rid = f"{self.seq}-0"
        self.streams.setdefault(stream, []).append((rid, list(a[2:])))
        self.data_ready.notify_all()
        return rid

    def cmd_xgroup(self, a):
        if a[0].upper() != "CREATE":
            raise RESPError("ERR only XGROUP CREATE is supported")
        stream, group = a[1], a[2]
        mkstream = any(str(x).upper() == "MKSTREAM" for x in a[4:])
        if stream not in self.streams:
            if not mkstream:
                raise RESPError("ERR The XGROUP subcommand requires the "
                                "key to exist")
            self.streams[stream] = []
        if (stream, group) in self.groups:
            raise RESPError("BUSYGROUP Consumer Group name already exists")
        # pel: rid -> [consumer, delivered_at_monotonic]
        self.groups[(stream, group)] = {"cursor": 0, "pel": {}}
        return Simple("OK")

    def _pop_new(self, stream: str, group: str, consumer: str,
                 count: int):
        g = self.groups.get((stream, group))
        if g is None:
            raise RESPError("NOGROUP No such consumer group")
        entries = self.streams.get(stream, [])
        new = entries[g["cursor"]:g["cursor"] + count]
        g["cursor"] += len(new)
        now = time.monotonic()
        for rid, _ in new:
            g["pel"][rid] = [consumer, now]
        return new

    def cmd_xreadgroup(self, a):
        if a[0].upper() != "GROUP":
            raise RESPError("ERR XREADGROUP must start with GROUP")
        group, consumer = a[1], a[2]
        opts = [str(x).upper() for x in a[3:]]
        count = int(a[3 + opts.index("COUNT") + 1]) \
            if "COUNT" in opts else 10
        block_ms: Optional[int] = None
        if "BLOCK" in opts:
            block_ms = int(a[3 + opts.index("BLOCK") + 1])
        si = opts.index("STREAMS")
        stream, cursor_id = a[3 + si + 1], a[3 + si + 2]
        if cursor_id != ">":
            raise RESPError("ERR only the new-messages cursor '>' is "
                            "supported")
        deadline = None if block_ms is None else (
            None if block_ms == 0 else time.monotonic() + block_ms / 1e3)
        with self.lock:
            while True:
                new = self._pop_new(stream, group, consumer, count)
                if new:
                    return [[stream,
                             [[rid, fields] for rid, fields in new]]]
                if block_ms is None:
                    return NIL_ARRAY
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return NIL_ARRAY
                if not self.data_ready.wait(remaining):
                    return NIL_ARRAY

    def cmd_xack(self, a):
        stream, group, ids = a[0], a[1], a[2:]
        g = self.groups.get((stream, group))
        n = 0
        for rid in ids:
            if g and g["pel"].pop(rid, None) is not None:
                n += 1
        return n

    def cmd_xautoclaim(self, a):
        """XAUTOCLAIM stream group consumer min-idle-time start [COUNT n]:
        claim PEL entries idle >= min-idle-time for `consumer`, restarting
        their idle clock. Reply is the Redis 6.2 shape: [next-cursor,
        [[rid, fields], ...]] — 7.0's third (deleted-ids) element is
        omitted; the broker client ignores it either way. PEL rows whose
        record was XDEL'd are dropped, as real Redis does."""
        if len(a) < 5:
            raise RESPError(
                "ERR wrong number of arguments for 'xautoclaim' command")
        stream, group, consumer = a[0], a[1], a[2]
        min_idle_ms = int(a[3])
        opts = [str(x).upper() for x in a[5:]]
        count = int(a[5 + opts.index("COUNT") + 1]) \
            if "COUNT" in opts else 100
        g = self.groups.get((stream, group))
        if g is None:
            raise RESPError("NOGROUP No such consumer group")
        by_id = dict(self.streams.get(stream, []))
        now = time.monotonic()
        claimed = []
        for rid, owner in list(g["pel"].items()):
            if len(claimed) >= count:
                break
            if (now - owner[1]) * 1000.0 < min_idle_ms:
                continue
            fields = by_id.get(rid)
            if fields is None:
                g["pel"].pop(rid, None)
                continue
            g["pel"][rid] = [consumer, now]
            claimed.append([rid, list(fields)])
        return ["0-0", claimed]

    def cmd_xpending(self, a):
        """Summary form only: [count, min-id, max-id,
        [[consumer, count-str], ...]]."""
        stream, group = a[0], a[1]
        g = self.groups.get((stream, group))
        if g is None:
            raise RESPError("NOGROUP No such consumer group")
        pel = g["pel"]
        if not pel:
            return [0, None, None, NIL_ARRAY]
        ids = sorted(pel, key=lambda r: tuple(map(int, r.split("-"))))
        per_consumer: Dict[str, int] = {}
        for owner, _ts in pel.values():
            per_consumer[owner] = per_consumer.get(owner, 0) + 1
        return [len(pel), ids[0], ids[-1],
                [[c, str(n)] for c, n in sorted(per_consumer.items())]]

    def cmd_xdel(self, a):
        stream, ids = a[0], set(a[1:])
        entries = self.streams.get(stream, [])
        removed = sum(1 for r, _ in entries if r in ids)
        # group cursors are list positions: removing delivered entries in
        # front of a cursor must pull the cursor back with them
        for (s, _), g in self.groups.items():
            if s == stream:
                g["cursor"] -= sum(1 for r, _ in entries[:g["cursor"]]
                                   if r in ids)
        self.streams[stream] = [(r, f) for r, f in entries if r not in ids]
        return removed

    def cmd_xlen(self, a):
        return len(self.streams.get(a[0], ()))

    def cmd_hset(self, a):
        # variadic since Redis 4: HSET key f1 v1 [f2 v2 ...]
        if len(a) < 3 or len(a) % 2 == 0:
            raise RESPError("ERR wrong number of arguments for 'hset' "
                            "command")
        h = self.hashes.setdefault(a[0], {})
        added = 0
        for f, v in zip(a[1::2], a[2::2]):
            if f not in h:
                added += 1
            h[f] = v
        # real Redis replies with the number of NEW fields added
        return added

    def cmd_hget(self, a):
        return self.hashes.get(a[0], {}).get(a[1])

    def cmd_hmget(self, a):
        # HMGET key f1 [f2 ...]: one array reply, nil per missing field
        if len(a) < 2:
            raise RESPError("ERR wrong number of arguments for 'hmget' "
                            "command")
        h = self.hashes.get(a[0], {})
        return [h.get(f) for f in a[1:]]

    def cmd_hgetall(self, a):
        out: List[str] = []
        for k, v in self.hashes.get(a[0], {}).items():
            out.extend([k, v])
        return out

    def cmd_hlen(self, a):
        return len(self.hashes.get(a[0], {}))

    def cmd_hdel(self, a):
        # variadic like real Redis: HDEL key f1 [f2 ...]
        h = self.hashes.get(a[0], {})
        return sum(1 for f in a[1:] if h.pop(f, None) is not None)

    def cmd_ping(self, a):
        # bare PING -> +PONG simple string; PING msg echoes a bulk string
        return Simple("PONG") if not a else a[0]


class _RESPHandler(socketserver.StreamRequestHandler):
    # replies to a pipelined command batch (xadd_many, hmget) go out as
    # many small writes; with Nagle on, each waits for the client's
    # delayed ACK before the next segment leaves — measured ~40 ms per
    # fused call on loopback, dwarfing the round trip it was fusing away
    disable_nagle_algorithm = True

    def setup(self):
        super().setup()
        conns = getattr(self.server, "live_connections", None)
        if conns is not None:
            with self.server.live_lock:
                conns.add(self.request)

    def finish(self):
        conns = getattr(self.server, "live_connections", None)
        if conns is not None:
            with self.server.live_lock:
                conns.discard(self.request)
        super().finish()

    def handle(self):
        while True:
            try:
                args = self._read_command()
            except (ConnectionError, ValueError):
                return
            if args is None:
                return
            try:
                reply = self.server.store.execute(args)
                self.wfile.write(_encode_reply(reply))
            except RESPError as e:
                self.wfile.write(b"-%s\r\n" % str(e).encode())
            except Exception as e:  # noqa: BLE001 — protocol error reply
                self.wfile.write(b"-ERR %s\r\n" % str(e).encode())

    def _read_command(self):
        line = self.rfile.readline()
        if not line:
            return None
        if line[:1] != b"*":
            raise ValueError(f"expected RESP array, got {line!r}")
        n = int(line[1:-2])
        args = []
        for _ in range(n):
            hdr = self.rfile.readline()
            if hdr[:1] != b"$":
                raise ValueError(f"expected bulk string, got {hdr!r}")
            ln = int(hdr[1:-2])
            args.append(self.rfile.read(ln + 2)[:-2].decode())
        return args


def _encode_reply(v) -> bytes:
    if v is NIL_ARRAY:
        return b"*-1\r\n"
    if v is None:
        return b"$-1\r\n"
    if isinstance(v, int):
        return b":%d\r\n" % v
    if isinstance(v, Simple):
        return b"+%s\r\n" % v.encode()
    if isinstance(v, str):
        data = v.encode()
        return b"$%d\r\n%s\r\n" % (len(data), data)
    if isinstance(v, list):
        return b"*%d\r\n" % len(v) + b"".join(
            _encode_reply(x) for x in v)
    raise TypeError(f"cannot encode {type(v)} as RESP")


class MiniRedisServer:
    """Threaded RESP2 server over a MiniRedisStore.

    >>> srv = MiniRedisServer().start()
    >>> broker = connect_broker(srv.url)     # real socket + wire protocol
    >>> srv.stop()
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 store: Optional[MiniRedisStore] = None):
        self.store = store or MiniRedisStore()

        class _Server(socketserver.ThreadingTCPServer):
            # restart-on-same-port (the client-reconnect contract:
            # a broker that comes back at its old address with its old
            # store) must not trip over TIME_WAIT from the old socket
            allow_reuse_address = True

        self._srv = _Server(
            (host, port), _RESPHandler, bind_and_activate=True)
        self._srv.daemon_threads = True
        self._srv.store = self.store
        # stop() must sever LIVE client connections too, not just the
        # listener: a "restarted broker" whose old sockets keep
        # answering from the old process would make every client-side
        # reconnect test (and real failover) a lie
        self._srv.live_connections = set()
        self._srv.live_lock = threading.Lock()
        self.host, self.port = self._srv.server_address
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)

    @property
    def url(self) -> str:
        return f"redis://{self.host}:{self.port}"

    def start(self) -> "MiniRedisServer":
        self._thread.start()
        return self

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()
        with self._srv.live_lock:
            conns = list(self._srv.live_connections)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
