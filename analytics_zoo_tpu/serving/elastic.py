"""Elastic traffic management (ISSUE 11): the decisions layer.

The reference platform absorbs bursty traffic with Flink backpressure
and dynamic operator parallelism (PAPER.md L0); our fleet was static —
fixed engine count, fixed ``batch_size``/``batch_timeout_ms``, every
request padded to a power-of-two bucket even at 3 rps. This module holds
the three decision makers that replace those constants, each driven by
telemetry the stack already collects:

- **BucketCostModel** — live per-bucket service cost: an EWMA over the
  measured dispatch→materialize time of every batch, mirrored into the
  ``serving_bucket_ms`` histogram (labeled by bucket) and the
  ``serving_bucket_cost_ms`` gauges. The model learns from traffic —
  before a bucket's first observation its cost reads as unknown and
  the controller plans with the nearest smaller bucket's estimate (or
  optimistically with zero; self-heals after one batch). All buckets
  are pre-warmed, so the model compares *costs*, never compile risk.
- **AdaptiveBatchController** — deadline-aware micro-batching: given the
  queued record count, the oldest record's age, and the broker backlog,
  it picks the target bucket and how long the reader may keep
  accumulating. Under light load it stops padding — dispatch the
  smallest bucket that fits, immediately; under heavy load it grows
  toward the throughput-optimal bucket (max records/sec = bucket /
  cost(bucket)) while the deadline budget allows.
- **AdmissionController** — tiered admission at the gateway: priority
  classes (config-declared, lowest first) each own a slice of the
  backlog headroom, so a cheap early 429 + Retry-After lands on the
  batch tier long before the premium tier feels anything — and long
  before the engine-side 503s. The engine's reader reuses the tier
  table to shed lowest-tier records first under overload
  (``ClusterServing`` writes "SHED" results for them, so accepted
  records are answered, never silently dropped).

`FleetAutoscaler` (the third tentpole leg) lives in `serving/fleet.py`
beside the heartbeat machinery it reads.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Sequence, Tuple


class BucketCostModel:
    """EWMA service-time model per batch bucket, fed by the pipeline.

    ``observe(bucket, ms)`` is called by the sink for every materialized
    batch (dispatch→materialize wall time — the cost a queued record
    actually pays once it boards that bucket). ``seed()`` installs a
    one-shot prior for callers that have a trustworthy estimate (tests,
    the bench); the engine deliberately does NOT seed from the warmup
    report — those times include compile/cache-load and would
    overstate cost by orders of magnitude. Thread-safe.
    """

    def __init__(self, buckets: Sequence[int], registry=None,
                 alpha: float = 0.2, labels: Optional[Dict] = None):
        self.buckets = sorted(int(b) for b in buckets)
        self.alpha = float(alpha)
        self._ewma: Dict[int, float] = {}
        self._lock = threading.Lock()
        self._labels = dict(labels or {})
        if registry is None:
            from analytics_zoo_tpu.observability.registry import get_registry
            registry = get_registry()
        self._hist = registry.histogram(
            "serving_bucket_ms",
            "per-bucket batch service time, dispatch to materialize "
            "(the adaptive batcher's live cost model)")
        self._cost_gauge = registry.gauge(
            "serving_bucket_cost_ms",
            "EWMA per-bucket service-cost estimate the adaptive batch "
            "controller plans with")

    def observe(self, bucket: int, ms: float) -> None:
        if ms < 0:
            return
        bucket = int(bucket)
        with self._lock:
            prev = self._ewma.get(bucket)
            cur = ms if prev is None else \
                prev + self.alpha * (ms - prev)
            self._ewma[bucket] = cur
        self._hist.observe(ms, bucket=str(bucket), **self._labels)
        self._cost_gauge.set(cur, bucket=str(bucket), **self._labels)

    def seed(self, bucket: int, ms: float) -> None:
        """Pre-load one bucket's estimate (warmup run time) without
        polluting the histogram — a compile-adjacent first run is a
        prior, not an observation."""
        with self._lock:
            self._ewma.setdefault(int(bucket), float(ms))

    def cost_ms(self, bucket: int) -> Optional[float]:
        with self._lock:
            if bucket in self._ewma:
                return self._ewma[bucket]
            # nearest known smaller bucket is a usable floor (per-batch
            # cost grows with bucket size on every measured model here)
            known = [b for b in self._ewma if b <= bucket]
            return self._ewma[max(known)] if known else None

    def throughput_optimal(self, cap: int) -> Optional[int]:
        """The bucket maximizing records/sec (= bucket / cost) among
        buckets with estimates, bounded by `cap` (the warmed reachable
        range); None until at least two buckets have costs — one point
        says nothing about the shape of the curve."""
        with self._lock:
            known = [(b, c) for b, c in self._ewma.items() if c > 0]
        if len(known) < 2:
            return None
        reachable = [(b, c) for b, c in known if b <= cap]
        if not reachable:
            return None
        return max(reachable, key=lambda bc: bc[0] / bc[1])[0]

    def snapshot(self) -> Dict[int, float]:
        with self._lock:
            return dict(self._ewma)


class BatchPlan:
    """One reader-cycle decision: accumulate toward `target` records for
    at most `wait_ms` more, then dispatch."""

    __slots__ = ("target", "wait_ms", "budget_ms", "reason")

    def __init__(self, target: int, wait_ms: float, budget_ms: float,
                 reason: str):
        self.target = int(target)
        self.wait_ms = max(0.0, float(wait_ms))
        self.budget_ms = float(budget_ms)
        self.reason = reason

    def __repr__(self):
        return (f"BatchPlan(target={self.target}, "
                f"wait_ms={self.wait_ms:.1f}, reason={self.reason!r})")


class AdaptiveBatchController:
    """Deadline-aware micro-batching policy (tentpole a).

    Three policies:

    - ``adaptive`` (default): with a deadline configured, each plan
      spends the oldest queued record's remaining budget —
      ``deadline_ms - age - cost(dispatched bucket) - margin`` — on growing the
      batch toward the throughput-optimal bucket, but ONLY while the
      broker backlog says more records exist to grow with. Light load
      (empty backlog) dispatches the smallest fitting bucket with zero
      added wait. Without a deadline it degrades to exactly the legacy
      fixed policy (wait ``batch_timeout_ms`` toward ``batch_size``),
      so default configs behave byte-identically.
    - ``fixed``: the pre-ISSUE-11 policy, explicit.
    - ``static``: ALWAYS wait the full timeout and pad every dispatch
      to the largest reachable bucket — the strawman the bench's
      light-load A/B measures the adaptive win against.
    """

    POLICIES = ("adaptive", "fixed", "static")

    def __init__(self, buckets: Sequence[int], batch_size: int,
                 batch_timeout_ms: float, policy: str = "adaptive",
                 deadline_ms: Optional[float] = None,
                 margin_ms: float = 2.0,
                 cost_model: Optional[BucketCostModel] = None,
                 registry=None, labels: Optional[Dict] = None):
        if policy not in self.POLICIES:
            raise ValueError(
                f"batch policy {policy!r} is not one of "
                f"{'/'.join(self.POLICIES)}")
        if deadline_ms is not None and deadline_ms <= 0:
            raise ValueError(f"deadline_ms={deadline_ms} must be > 0")
        self.buckets = sorted(int(b) for b in buckets) or [1]
        self.batch_size = int(batch_size)
        self.batch_timeout_ms = float(batch_timeout_ms)
        self.policy = policy
        self.deadline_ms = deadline_ms
        self.margin_ms = float(margin_ms)
        labels = dict(labels or {})
        self.cost = cost_model if cost_model is not None else \
            BucketCostModel(self.buckets, registry=registry,
                            labels=labels)
        # the largest bucket the reader can actually fill: buckets past
        # the one covering batch_size cannot occur (warmup caps there
        # too, so growing past it would COMPILE on the request path)
        self.cap = self._next_bucket(self.batch_size)
        if registry is None:
            from analytics_zoo_tpu.observability.registry import get_registry
            registry = get_registry()
        self._age_hist = registry.histogram(
            "serving_queue_age_ms",
            "age of the oldest queued record at dispatch time (how much "
            "deadline budget batching consumed)")
        self._chosen = registry.counter(
            "serving_chosen_bucket_total",
            "dispatches by the bucket the adaptive controller chose")
        self._labels = labels

    def _next_bucket(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def pad_bucket(self, n: int) -> int:
        """The bucket a decoded group of `n` records pads to: the
        smallest that fits (adaptive/fixed — no-padding-under-light-load
        is the point), or the largest reachable one (static, the bench
        strawman that pads a 1-record batch all the way up)."""
        if self.policy == "static":
            return max(self.cap, self._next_bucket(n))
        return self._next_bucket(n)

    # -- planning ----------------------------------------------------------
    def plan(self, queued: int, oldest_age_ms: float,
             backlog: Optional[int]) -> BatchPlan:
        """Decide target size and further wait for one reader cycle.

        `backlog` counts records waiting BEYOND the ones in hand — the
        engine subtracts its own in-flight records from the stream
        depth before calling (the stream retains a record until sink
        commit, so raw depth would read this engine's own pipeline as
        other people's load and misclassify a light trickle as heavy).
        None = unknown. `oldest_age_ms` is measured from THIS engine's
        first pickup of the oldest record — records carry no enqueue
        timestamp (cross-host clocks are not trusted anywhere in the
        fleet design), so time spent queued in the broker, or idling
        before a claim sweep, is budgeted by the admission layer's
        backlog thresholds rather than this deadline."""
        queued = max(0, int(queued))
        fit = self._next_bucket(max(queued, 1))
        if self.policy == "static":
            # strawman: always fill/pad to the largest reachable bucket
            wait = 0.0 if queued >= self.cap else self.batch_timeout_ms
            return BatchPlan(self.cap, wait, float("inf"), "static")
        if self.policy == "fixed" or self.deadline_ms is None:
            # legacy straggler-sweep semantics, bit-for-bit: one
            # batch_timeout_ms wait toward batch_size when short
            wait = 0.0 if queued >= self.batch_size \
                else self.batch_timeout_ms
            return BatchPlan(self.batch_size, wait, float("inf"),
                             "fixed")
        # adaptive with a deadline: budget is what's left of the oldest
        # record's deadline after the target bucket's estimated service
        # time and a safety margin
        cost = self.cost.cost_ms(fit) or 0.0
        budget = self.deadline_ms - oldest_age_ms - cost - self.margin_ms
        if queued and budget <= 0:
            # already eating into the deadline: dispatch NOW, smallest
            # fitting bucket (never pad up when late)
            return BatchPlan(fit, 0.0, budget, "deadline")
        if backlog is None:
            # UNKNOWN load (transport without XLEN, probe mid-outage):
            # plan conservatively — the legacy straggler-sweep shape,
            # clipped to the remaining budget. Guessing "light" here
            # would dispatch 1-2 record micro-batches for a whole
            # broker blip under genuinely heavy load.
            wait = 0.0 if queued >= self.batch_size else \
                min(max(budget, 0.0), self.batch_timeout_ms)
            return BatchPlan(self.batch_size, wait, budget, "unknown")
        opt = self.cost.throughput_optimal(self.cap)
        heavy = backlog > 0
        if not heavy:
            # light load: nothing else to batch with — the whole
            # anti-padding win is dispatching `fit` immediately instead
            # of waiting out a straggler window for records that are
            # not coming
            return BatchPlan(fit, 0.0, budget, "light")
        target = max(fit, min(opt if opt is not None else self.cap,
                              self.cap))
        # the budget must price the bucket we'd actually DISPATCH: a
        # larger target costs more service time than `fit`, and
        # budgeting with fit's cost would grow into a bucket whose own
        # service time blows the deadline. If the target is
        # unaffordable, dispatch the smallest fit now instead.
        cost_t = self.cost.cost_ms(target)
        budget_t = self.deadline_ms - oldest_age_ms \
            - (cost_t if cost_t is not None else cost) - self.margin_ms
        if queued and budget_t <= 0:
            return BatchPlan(fit, 0.0, budget, "deadline")
        if queued >= target:
            return BatchPlan(target, 0.0, budget_t, "full")
        # grow toward the throughput-optimal bucket, but never spend
        # more than the remaining budget (or the configured timeout —
        # the broker read is the wait, so arrival latency is covered)
        wait = min(budget_t, self.batch_timeout_ms) if queued \
            else min(max(budget_t, 0.0), self.batch_timeout_ms)
        return BatchPlan(target, wait, budget_t, "grow")

    # -- dispatch-side accounting -----------------------------------------
    def record_dispatch(self, bucket: int, oldest_age_ms: float) -> None:
        self._age_hist.observe(max(0.0, oldest_age_ms), **self._labels)
        self._chosen.inc(bucket=str(int(bucket)), **self._labels)

    def observe_service(self, bucket: int, ms: float) -> None:
        self.cost.observe(bucket, ms)


class TierTable:
    """Config-declared priority classes, lowest first. Records carry the
    tier NAME (a header at the gateway, a field on the broker record);
    unknown or missing names map to the lowest tier — a producer that
    never heard of tiers is batch traffic, not premium."""

    def __init__(self, tiers: Sequence[str]):
        names = [str(t) for t in tiers if str(t).strip()]
        if not names:
            raise ValueError("admission tiers must be a non-empty list "
                             "(lowest priority first)")
        if len(set(names)) != len(names):
            raise ValueError(f"admission tiers {names} contain duplicates")
        self.names = names
        self._level = {n: i for i, n in enumerate(names)}

    def level(self, name) -> int:
        if name is None:
            return 0
        return self._level.get(str(name), 0)

    def name(self, level: int) -> str:
        return self.names[max(0, min(level, len(self.names) - 1))]

    @property
    def top(self) -> int:
        return len(self.names) - 1

    def __len__(self):
        return len(self.names)


class AdmissionController:
    """Tiered early admission at the gateway (tentpole c).

    Each tier owns a slice of the backlog headroom: tier level ``l`` of
    ``n`` admits while ``backlog < max_backlog * (l+1) / n``. As load
    climbs, the batch tier starts seeing cheap 429s (with a Retry-After
    sized to the drain horizon) while the premium tier still has its
    full budget; only past ``max_backlog`` does the top tier throttle.
    This runs BEFORE the record touches the broker — the expensive 503
    paths (quarantined pool, dead fleet) stay as the last line.

    Backlog reads are rate-limited and cached, one poll per
    ``poll_min_interval_s`` shared by every concurrent request; an
    unreachable broker admits (the downstream enqueue will surface the
    real error — admission must not add a failure mode).

    Partitioned plane (ISSUE 16): `partitions > 1` makes the backlog
    the SUM across the partition streams (total queued work is what
    admission gates on) and exports each stream's depth as a
    ``serving_partition_depth{partition=}`` series — the per-shard view
    that shows a hot partition or an orphaned one (depth climbing with
    no engine holding its lease) before clients feel it."""

    def __init__(self, broker, stream: str, tiers: Sequence[str],
                 max_backlog: int = 512, registry=None,
                 poll_min_interval_s: float = 0.2,
                 retry_after_s: float = 1.0,
                 partitions: int = 1):
        if max_backlog <= 0:
            raise ValueError(f"max_backlog={max_backlog} must be > 0")
        from analytics_zoo_tpu.serving.partitions import (
            partition_streams, validate_partitions)
        self.broker = broker
        self.stream = stream
        self.partitions = validate_partitions(partitions)
        self._streams = partition_streams(stream, self.partitions)
        self.tiers = tiers if isinstance(tiers, TierTable) \
            else TierTable(tiers)
        self.max_backlog = int(max_backlog)
        self.poll_min_interval_s = float(poll_min_interval_s)
        self.retry_after_s = float(retry_after_s)
        self._lock = threading.Lock()
        self._backlog: Optional[int] = None
        self._last_poll = 0.0
        if registry is None:
            from analytics_zoo_tpu.observability.registry import get_registry
            registry = get_registry()
        self._outcomes = registry.counter(
            "serving_admission_total",
            "admission decisions by outcome (accepted, rejected, shed) "
            "and tier")
        self._backlog_gauge = registry.gauge(
            "serving_backlog_depth",
            "broker stream depth (enqueued records not yet committed) "
            "as last sampled by the elastic layer")
        self._partition_gauge = registry.gauge(
            "serving_partition_depth",
            "per-partition broker stream depth as last sampled by the "
            "elastic layer (series appear only when partitions > 1)")

    def threshold(self, level: int) -> int:
        n = len(self.tiers)
        level = max(0, min(level, n - 1))
        return max(1, int(self.max_backlog * (level + 1) / n))

    def backlog(self) -> Optional[int]:
        now = time.monotonic()
        with self._lock:
            if now - self._last_poll < self.poll_min_interval_s:
                return self._backlog
            self._last_poll = now
        try:
            depths = [int(self.broker.stream_depth(s))
                      for s in self._streams]
            depth = sum(depths)
        except Exception:  # noqa: BLE001 — admission must not add faults
            depth, depths = None, None
        with self._lock:
            self._backlog = depth
        if depth is not None:
            self._backlog_gauge.set(float(depth))
            if self.partitions > 1 and depths is not None:
                for i, d in enumerate(depths):
                    self._partition_gauge.set(float(d),
                                              partition=str(i))
        return depth

    def admit(self, tier_name) -> Tuple[bool, float]:
        """(admitted, retry_after_s). Unknown backlog admits."""
        level = self.tiers.level(tier_name)
        name = self.tiers.name(level)
        depth = self.backlog()
        if depth is not None and depth >= self.threshold(level):
            self._outcomes.inc(outcome="rejected", tier=name)
            return False, self.retry_after_s
        self._outcomes.inc(outcome="accepted", tier=name)
        return True, 0.0
