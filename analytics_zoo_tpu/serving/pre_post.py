"""Serving pre/post processing parity pieces.

Reference: `zoo/.../serving/preprocessing/PreProcessing.scala:127` (base64
image decode, arrow tensor decode), `postprocessing/PostProcessing.scala:174`
(top-N filter over class scores), `arrow/ArrowSerializer.scala:162` (tensor
(data, shape) arrow encoding).

The arrow codec uses pyarrow IPC with a two-column record batch
(data: float32 list, shape: int32 list) — the same logical layout the
reference serializes, readable from any arrow client.
"""

from __future__ import annotations

import base64
from typing import Dict, List, Tuple, Union

import numpy as np


# ---------------------------------------------------------------------------
# Arrow tensor codec (`ArrowSerializer.scala:162`)
# ---------------------------------------------------------------------------
def arrow_encode(arr: np.ndarray) -> bytes:
    import pyarrow as pa
    arr = np.ascontiguousarray(np.asarray(arr, np.float32))
    batch = pa.record_batch(
        [pa.array([arr.reshape(-1)], pa.list_(pa.float32())),
         pa.array([np.asarray(arr.shape, np.int32)],
                  pa.list_(pa.int32()))],
        names=["data", "shape"])
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, batch.schema) as writer:
        writer.write_batch(batch)
    return sink.getvalue().to_pybytes()


def arrow_decode(blob: Union[bytes, str]) -> np.ndarray:
    import pyarrow as pa
    if isinstance(blob, str):
        blob = base64.b64decode(blob)
    with pa.ipc.open_stream(pa.BufferReader(blob)) as reader:
        batch = reader.read_next_batch()
    data = np.asarray(batch.column("data")[0].values, np.float32)
    shape = np.asarray(batch.column("shape")[0].values, np.int32)
    return data.reshape(tuple(shape))


def arrow_encode_b64(arr: np.ndarray) -> str:
    return base64.b64encode(arrow_encode(arr)).decode("ascii")


# ---------------------------------------------------------------------------
# PreProcessing (`PreProcessing.scala:127`)
# ---------------------------------------------------------------------------
def decode_record_field(value) -> np.ndarray:
    """Accept any of the serving payload encodings: the b64 raw codec dict
    (`broker.encode_ndarray`), an arrow blob ({"arrow": b64} dict or raw
    bytes), a b64 JPEG/PNG image ({"image_b64": ...}), or a nested list."""
    from analytics_zoo_tpu.serving.broker import decode_ndarray
    if isinstance(value, dict):
        if "b64" in value:
            return decode_ndarray(value)
        if "arrow" in value:
            return arrow_decode(value["arrow"])
        if "image_b64" in value:
            from analytics_zoo_tpu.data.image import load_image
            raw = base64.b64decode(value["image_b64"])
            return load_image(raw).astype(np.float32)
        raise ValueError(f"Unknown record encoding: {sorted(value)}")
    if isinstance(value, (bytes, bytearray)):
        return arrow_decode(bytes(value))
    return np.asarray(value, np.float32)


def record_meta(value) -> Union[Tuple[Tuple[int, ...], str], None]:
    """(shape, dtype) read off a raw-b64 codec HEADER without touching
    the payload — what lets the decode stage size its batch buffer
    before decoding a single record. None for codecs whose shape only a
    full decode reveals (arrow/image/list), which then take the
    decode-then-copy fallback."""
    if isinstance(value, dict) and "b64" in value:
        # np.dtype(...).str canonicalizes the spelling ('float32' and
        # '<f4' must group into the same batch buffer)
        return (tuple(int(s) for s in value.get("shape", ())),
                np.dtype(value.get("dtype", "float32")).str)
    return None


def decode_record_into(value, out_row: np.ndarray) -> None:
    """Decode a raw-b64 codec record DIRECTLY into `out_row` (one row of
    a preallocated batch buffer): the payload is viewed zero-copy via
    `np.frombuffer` and written ONCE into its final batch slot — the
    per-record `.copy()` of `broker.decode_ndarray` plus the separate
    np.stack pass the dispatch stage used to run both disappear from
    the hot path (ISSUE 9 serving satellite)."""
    data = base64.b64decode(value["b64"])
    view = np.frombuffer(data, dtype=np.dtype(value["dtype"])).reshape(
        value["shape"])
    np.copyto(out_row, view)


# ---------------------------------------------------------------------------
# PostProcessing (`PostProcessing.scala:174`)
# ---------------------------------------------------------------------------
def top_n(pred: np.ndarray, n: int) -> List[Tuple[int, float]]:
    """Top-N (class_index, score) rows, highest first."""
    flat = np.asarray(pred).reshape(-1)
    n = min(n, flat.size)
    idx = np.argpartition(-flat, n - 1)[:n]
    idx = idx[np.argsort(-flat[idx])]
    return [(int(i), float(flat[i])) for i in idx]


def format_top_n(pred: np.ndarray, n: int) -> str:
    """The reference's serving result string: `[class:prob,...]`
    (PostProcessing topN output shape)."""
    rows = top_n(pred, n)
    return "[" + ",".join(f"{i}:{p:.8f}" for i, p in rows) + "]"


def apply_filter(pred: np.ndarray, filter_str: str):
    """Parse and apply a serving filter spec (`topN(5)` supported, matching
    the reference's filter grammar in PostProcessing.scala)."""
    filter_str = filter_str.strip()
    if filter_str.startswith("topN(") and filter_str.endswith(")"):
        n = int(filter_str[len("topN("):-1])
        return format_top_n(pred, n)
    raise ValueError(f"Unsupported serving filter: {filter_str!r}")
