"""Serving client — `InputQueue`/`OutputQueue` (`pyzoo/zoo/serving/client.py`).

Protocol preserved from the reference: `enqueue` XADDs a b64-encoded ndarray
(or image file) to the serving stream (`client.py:114`), `predict` is the
sync round-trip (`client.py:199` via the HTTP frontend there; here it polls
the result hash), `OutputQueue.query/dequeue` read results back
(`client.py:203`). Results arrive as b64 ndarrays or the literal "NaN" for
per-record failures (`ClusterServingInference.scala:71-79` degradation).

Wire-speed ingest (ISSUE 16): with `partitions > 1` every record routes to
the partition stream its uri hashes to (serving/partitions.py — the same
map every gateway and engine computes); results still land in the ONE
``result:<stream>`` hash, so polling is unchanged. The sync paths fuse
their RESP round trips the way PR 10 fused the sink commit: a
`predict_batch` burst is ONE pipelined multi-XADD in, ONE `HMGET` per poll
sweep out (`pipelined=False` keeps the per-record wire pattern as the
bench A/B baseline). `StreamingSession` holds the pattern open across
bursts on one persistent connection. Every broker op retries through a
jittered exponential backoff when the connection drops (a restarted
broker costs the in-flight request a reconnect, not a failure)."""

from __future__ import annotations

import json
import logging
import time
import uuid
from typing import Dict, List, Optional, Union

import numpy as np

from analytics_zoo_tpu.serving.breaker import BackoffPolicy
from analytics_zoo_tpu.serving.broker import (Broker, connect_broker,
                                              decode_ndarray, encode_ndarray)
from analytics_zoo_tpu.serving.partitions import (stream_for,
                                                  validate_partitions)

log = logging.getLogger("analytics_zoo_tpu.serving.client")

STREAM = "serving_stream"          # reference stream name
RESULT_KEY = "result:serving_stream"


class _Reconnecting:
    """Shared retry harness: run a broker op, and on a dropped
    connection (broker restart, network blip) back off with jitter and
    try again instead of failing the caller's in-flight request. The
    transports reconnect lazily — their next command redials — so the
    retry IS the reconnect. Jitter matters: a fleet of clients hitting
    a restarting broker in lockstep is its own outage."""

    def __init__(self, reconnect_attempts: int = 8,
                 backoff: Optional[BackoffPolicy] = None):
        self.reconnect_attempts = max(1, int(reconnect_attempts))
        self.backoff = backoff or BackoffPolicy(initial_s=0.02, max_s=1.0)

    def _call(self, fn, *args, deadline: Optional[float] = None):
        attempt = 0
        while True:
            try:
                return fn(*args)
            except (ConnectionError, OSError) as e:
                attempt += 1
                if attempt >= self.reconnect_attempts:
                    raise
                delay = self.backoff.delay(attempt)
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise
                    delay = min(delay, remaining)
                if attempt == 1:
                    log.warning(
                        "broker call failed (%s: %s); reconnecting with "
                        "backoff", type(e).__name__, e)
                time.sleep(delay)


class InputQueue(_Reconnecting):
    def __init__(self, broker: Union[Broker, str, None] = None,
                 stream: str = STREAM, partitions: int = 1,
                 pipelined: bool = True,
                 reconnect_attempts: int = 8,
                 trace_sample: float = 0.0,
                 trace_parent: Optional[str] = None):
        """`partitions` must match the serving fleet's count — both
        sides compute the same uri hash, so a mismatch strands records
        on streams nobody reads (the engine's lease-table meta guard
        exists to catch exactly that drift at engine startup).
        `pipelined=False` restores the per-record XADD + per-uri HGET
        wire pattern — kept ONLY as the bench_serving ingest A/B
        baseline.

        `trace_sample` > 0 turns on trace-context propagation (ISSUE
        17): every record is stamped with its ingest wall timestamp
        (the record uri IS the trace id), so engines can continue the
        trace with a "wire" span and export it for fleet assembly.
        Sampling itself is decided deterministically from the uri in
        every process — the stamp carries context, not the decision.
        `trace_parent` names the span the engine-side trace should hang
        under (the gateway sets "gateway_request")."""
        super().__init__(reconnect_attempts=reconnect_attempts)
        self.broker = broker if isinstance(broker, Broker) \
            else connect_broker(broker)
        self.stream = stream
        self.partitions = validate_partitions(partitions)
        self.pipelined = pipelined
        if not 0.0 <= float(trace_sample) <= 1.0:
            raise ValueError(
                f"trace_sample must be in [0, 1], got {trace_sample}")
        self.trace_sample = float(trace_sample)
        self.trace_parent = trace_parent
        # per-hop engine timing summaries from the most recent
        # predict_batch (uri -> hop dict), populated by the OutputQueue
        self.last_hops: Dict[str, Dict] = {}

    def _record(self, uri: Optional[str], tier: Optional[str],
                data: Dict) -> tuple:
        uri = uri or uuid.uuid4().hex
        payload: Dict = {}
        for name, value in data.items():
            if isinstance(value, np.ndarray):
                payload[name] = encode_ndarray(value)
            elif name == "image":
                payload[name] = self._encode_image(value)
            else:
                payload[name] = value
        record = {"uri": uri, "data": payload}
        if tier is not None:
            record["tier"] = str(tier)
        if self.trace_sample > 0:
            ctx: Dict = {"ts": time.time()}
            if self.trace_parent:
                ctx["parent"] = self.trace_parent
            record["trace"] = ctx
        return uri, stream_for(self.stream, uri, self.partitions), record

    def enqueue(self, uri: Optional[str] = None, tier: Optional[str] = None,
                **data) -> str:
        """`enqueue("uuid", t=ndarray)` or path/bytes via `image=`.
        `tier` (ISSUE 11) names the record's priority class — the
        engine's tiered scheduler dispatches higher tiers first and
        sheds the lowest tier first under overload; records without one
        rank lowest."""
        uri, stream, record = self._record(uri, tier, data)
        self._call(self.broker.xadd, stream, record)
        return uri

    def enqueue_batch(self, samples, tier: Optional[str] = None,
                      uris: Optional[List[str]] = None) -> List[str]:
        """Batched ingest: the whole burst goes out as ONE pipelined
        multi-XADD (entries spanning partition streams), so N records
        cost one round trip instead of N — the wire-floor win the
        BENCH r09 A/B measures. Falls back to per-record XADDs when
        the queue was built `pipelined=False`."""
        entries, out = [], []
        for i, s in enumerate(samples):
            uri, stream, record = self._record(
                uris[i] if uris else None, tier, {"t": np.asarray(s)})
            entries.append((stream, record))
            out.append(uri)
        if self.pipelined:
            self._call(self.broker.xadd_many, entries)
        else:
            for stream, record in entries:
                self._call(self.broker.xadd, stream, record)
        return out

    @staticmethod
    def _encode_image(value) -> Dict:
        """Image path/bytes -> decoded float ndarray record (the reference
        ships b64 JPEG and decodes OpenCV-side; decode client-side here so
        the server stays shape-generic)."""
        from analytics_zoo_tpu.data.image import load_image
        arr = load_image(value)
        return encode_ndarray(arr.astype(np.float32))

    def predict(self, data: np.ndarray, timeout_s: float = 30.0,
                tier: Optional[str] = None,
                uri: Optional[str] = None) -> np.ndarray:
        """Sync path (`client.py:199`): enqueue then poll the result."""
        return self.predict_batch([np.asarray(data)], timeout_s,
                                  tier=tier,
                                  uris=[uri] if uri else None)[0]

    def predict_batch(self, samples, timeout_s: float = 30.0,
                      tier: Optional[str] = None,
                      uris: Optional[List[str]] = None) -> list:
        """Sync multi-record path: each sample is ONE serving record (the
        per-instance contract of the reference frontend — records batch up
        inside the serving loop, not inside one record). Results return in
        input order; a failed record yields float('nan').

        Deadlines use `time.monotonic()` (a wall-clock step — NTP slew,
        suspend/resume — must not shrink or blow the budget), and idle
        polls back off exponentially from 1 ms to a 50 ms cap instead of
        hammering the broker at a fixed tight interval; any progress
        resets the backoff so a streaming burst is drained promptly.

        Pipelined (default), the burst enqueues as one multi-XADD and
        each poll sweep reads EVERY outstanding uri in one HMGET — the
        round-trip count per poll is 1, not len(missing). The legacy
        per-record pattern survives under `pipelined=False` for the
        bench A/B."""
        deadline = time.monotonic() + timeout_s
        out = OutputQueue(self.broker, self.stream,
                          reconnect_attempts=self.reconnect_attempts)
        if self.pipelined:
            uris = self.enqueue_batch(samples, tier=tier, uris=uris)
        else:
            uris = [self.enqueue(uris[i] if uris else None, tier=tier,
                                 t=np.asarray(s))
                    for i, s in enumerate(samples)]
        results: dict = {}
        backoff = 0.001
        while len(results) < len(uris):
            # deadline checked every pass, progress or not: trickling
            # results must tighten the remaining budget, not renew it
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            progress = False
            missing = [u for u in uris if u not in results]
            if self.pipelined:
                found = out.query_many(missing, delete=True,
                                       deadline=deadline)
                if found:
                    results.update(found)
                    progress = True
            else:
                for uri in missing:
                    res = out.query(uri, delete=True)
                    if res is not None:
                        results[uri] = res
                        progress = True
            if progress:
                backoff = 0.001
                continue
            time.sleep(min(backoff, max(0.0, remaining)))
            backoff = min(backoff * 2, 0.05)
        missing = [u for u in uris if u not in results]
        if missing:
            raise TimeoutError(
                f"No prediction for {len(missing)}/{len(uris)} records "
                f"within {timeout_s}s")
        self.last_hops = dict(out.last_hops)
        return [results[u] for u in uris]

    def stream_session(self, max_inflight: int = 256) -> "StreamingSession":
        """A persistent-connection streaming mode over this queue."""
        return StreamingSession(self, max_inflight=max_inflight)


class StreamingSession:
    """Persistent-connection streaming client (ISSUE 16): many requests
    in flight over ONE broker connection, with the fused wire pattern
    held open across bursts — `submit()` buffers locally, `flush()`
    ships everything buffered as one multi-XADD, `drain()` collects
    outstanding results with one HMGET per poll sweep. Usable as a
    context manager; exiting drains what was submitted.

        with inq.stream_session() as s:
            for x in arrays:
                s.submit(x)
            results = s.drain()          # {uri: ndarray}

    `max_inflight` bounds the unflushed + unanswered window: submit
    past it triggers an implicit flush (backpressure lives at the
    broker, not in this buffer)."""

    def __init__(self, inq: InputQueue, max_inflight: int = 256):
        self.inq = inq
        self.out = OutputQueue(inq.broker, inq.stream,
                               reconnect_attempts=inq.reconnect_attempts)
        self.max_inflight = max(1, int(max_inflight))
        self._buffered: List[tuple] = []     # (stream, record)
        self._outstanding: List[str] = []    # uris awaiting results
        self._order: List[str] = []          # submission order (stable)

    def __enter__(self) -> "StreamingSession":
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self.drain()
        return False

    def submit(self, data, uri: Optional[str] = None,
               tier: Optional[str] = None) -> str:
        uri, stream, record = self.inq._record(
            uri, tier, {"t": np.asarray(data)})
        self._buffered.append((stream, record))
        self._outstanding.append(uri)
        self._order.append(uri)
        if len(self._buffered) >= self.max_inflight:
            self.flush()
        return uri

    def flush(self):
        """Ship the buffered records: one pipelined multi-XADD no
        matter how many partitions the burst fans out across."""
        if not self._buffered:
            return
        entries, self._buffered = self._buffered, []
        self.inq._call(self.inq.broker.xadd_many, entries)

    def drain(self, timeout_s: float = 30.0) -> Dict[str, object]:
        """Flush, then collect every outstanding result (submission
        order). One HMGET round trip per poll sweep regardless of how
        many records are outstanding."""
        self.flush()
        deadline = time.monotonic() + timeout_s
        results: dict = {}
        backoff = 0.001
        while len(results) < len(self._outstanding):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            missing = [u for u in self._outstanding if u not in results]
            found = self.out.query_many(missing, delete=True,
                                        deadline=deadline)
            if found:
                results.update(found)
                backoff = 0.001
                continue
            time.sleep(min(backoff, max(0.0, remaining)))
            backoff = min(backoff * 2, 0.05)
        missing = [u for u in self._outstanding if u not in results]
        if missing:
            raise TimeoutError(
                f"No prediction for {len(missing)}/"
                f"{len(self._outstanding)} streamed records within "
                f"{timeout_s}s")
        ordered = {u: results[u] for u in self._order if u in results}
        self._outstanding = []
        self._order = []
        return ordered


class OutputQueue(_Reconnecting):
    _MAX_HOPS = 1024

    def __init__(self, broker: Union[Broker, str, None] = None,
                 stream: str = STREAM, reconnect_attempts: int = 8):
        super().__init__(reconnect_attempts=reconnect_attempts)
        self.broker = broker if isinstance(broker, Broker) \
            else connect_broker(broker)
        self.stream = stream
        self.result_key = f"result:{stream}"
        # per-hop engine timing summaries (ISSUE 17): when tracing is
        # on, each writeback row carries a compact "hops" dict —
        # stripped from the decoded result and kept here (bounded,
        # most-recent window) so the client can attribute its own e2e
        # latency: e2e minus hops["engine_ms"] = wire + broker time
        self.last_hops: Dict[str, Dict] = {}

    @staticmethod
    def _token_row_fields(uri: str, raw: str) -> List[str]:
        """Token rows a generative final result leaves behind
        (decode-engine streaming, ISSUE 18): the final blob's
        ``gen.rows`` counts its ``<uri>#<index>`` siblings, so a
        deleting poll can clean them up in the same batched HDEL
        instead of leaking them in the result hash."""
        if not raw or raw[0] != "{":
            return []
        try:
            rows = int(json.loads(raw).get("gen", {}).get("rows", 0))
        except Exception:  # noqa: BLE001 — cleanup is best effort
            return []
        from analytics_zoo_tpu.serving.decode import token_row_field
        return [token_row_field(uri, i) for i in range(rows)]

    def query(self, uri: str, delete: bool = False):
        raw = self._call(self.broker.hget, self.result_key, uri)
        if raw is None:
            return None
        if delete:
            self._call(self.broker.hdel_many, self.result_key,
                       [uri] + self._token_row_fields(uri, raw))
        return self._decode(raw, uri=uri)

    def query_many(self, uris, delete: bool = False,
                   deadline: Optional[float] = None) -> Dict[str, object]:
        """Fused poll: ONE HMGET answers every uri in the sweep (the
        read analogue of the batched multi-XADD), plus one batched
        delete for whatever landed. Missing fields simply aren't in
        the returned dict."""
        uris = list(uris)
        if not uris:
            return {}
        raws = self._call(self.broker.hmget, self.result_key, uris,
                          deadline=deadline)
        found = {u: raw for u, raw in zip(uris, raws) if raw is not None}
        if delete and found:
            fields = list(found)
            for u, raw in found.items():
                fields += self._token_row_fields(u, raw)
            self._call(self.broker.hdel_many, self.result_key,
                       fields, deadline=deadline)
        return {u: self._decode(raw, uri=u) for u, raw in found.items()}

    def dequeue(self) -> Dict[str, np.ndarray]:
        """Drain all COMPLETED results (`client.py:203` semantics): one
        read plus one batched delete, not one round trip per field.

        Generative streaming (ISSUE 18) writes extra ``<uri>#<index>``
        token rows before the final ``uri`` row lands; a result exists
        only once its exact uri field does. Token rows whose final row
        is present are consumed (deleted) with it; token rows of a
        STILL-DECODING sequence are left in place — draining them would
        misread a partial stream as a completed result."""
        allr = self._call(self.broker.hgetall, self.result_key)
        out, drop = {}, []
        for uri, raw in allr.items():
            if "#" in uri:
                base = uri.rsplit("#", 1)[0]
                if base in allr:      # finished: consumed with its final
                    drop.append(uri)
                continue
            out[uri] = self._decode(raw, uri=uri)
            drop.append(uri)
        if drop:
            self._call(self.broker.hdel_many, self.result_key, drop)
        return out

    def stream_tokens(self, uri: str, timeout_s: float = 30.0,
                      delete: bool = True, start: int = 0,
                      keepalive_s: Optional[float] = None,
                      stall_timeout_s: Optional[float] = None):
        """Incrementally consume one generative request's token stream.

        Yields each token row ``{"i", "t", "ms"}`` as the decode engine
        writes it, then one final ``{"done": True, "tokens": ndarray,
        "gen": {...}}`` once the final row lands. Each poll sweep is ONE
        HMGET asking for a WINDOW of upcoming token rows plus the final
        row, so tokens that accumulated while the client slept (or
        between fused per-step writebacks) drain in a single sweep
        instead of one round trip each. Idle sweeps back off
        exponentially (1 ms → 50 ms) like `predict_batch`; ANY sweep
        that returns new tokens resets the backoff to the floor, so an
        idle pause never inflates client-observed inter-token latency
        once the stream resumes. With `delete` (default) the final row
        and every token row are removed in one batched HDEL at
        completion. Raises TimeoutError if the final row hasn't landed
        inside `timeout_s`.

        Crash-safe streaming (ISSUE 20): the cursor only ever moves
        forward, so every token index is yielded EXACTLY once per call
        — and `start` skips rows a previous (disconnected) call already
        delivered, which is how the frontend honors ``Last-Event-ID``
        (replay only the missing rows; the rows are durable in the
        result hash until the final is consumed). `keepalive_s` yields
        ``{"keepalive": True}`` markers during idle gaps so an SSE
        writer can emit comment frames that hold proxies open.
        `stall_timeout_s` arms heartbeat-aware death detection: when no
        row lands for that long AND the fleet's heartbeat rows
        (`engines:<stream>`) show zero timestamp progress between two
        consecutive checks, the stream ends with ``{"done": True,
        "error": "engine-dead"}`` instead of hanging until the
        deadline — a live-but-slow engine keeps beating and is given
        the full `timeout_s`."""
        from analytics_zoo_tpu.serving.decode import token_row_field
        from analytics_zoo_tpu.serving.fleet import engines_key
        deadline = time.monotonic() + timeout_s
        nxt = max(0, int(start))
        backoff = 0.001
        window = 8
        t_progress = time.monotonic()
        last_keep = time.monotonic()
        last_beats: Optional[Dict[str, str]] = None
        while True:
            fields = [token_row_field(uri, nxt + j)
                      for j in range(window)] + [uri]
            raws = self._call(self.broker.hmget, self.result_key, fields,
                              deadline=deadline)
            final = raws[window]
            progressed = False
            for raw in raws[:window]:
                if raw is None:
                    break
                progressed = True
                nxt += 1
                yield json.loads(raw)
            if progressed:
                backoff = 0.001
                t_progress = time.monotonic()
                last_beats = None
                continue
            if final is not None:
                if final in ("NaN", "SHED"):
                    if delete:
                        self._call(self.broker.hdel, self.result_key, uri)
                    yield {"done": True, "error": final, "tokens": None,
                           "gen": {}}
                    return
                blob = json.loads(final)
                gen = blob.get("gen", {})
                # rows the engine wrote after our last sweep: the final
                # row commits last, so any remaining token rows are
                # already present — drain them in order before done
                total = int(gen.get("rows", nxt))
                if nxt < total:
                    raws = self._call(
                        self.broker.hmget, self.result_key,
                        [token_row_field(uri, i)
                         for i in range(nxt, total)], deadline=deadline)
                    for raw in raws:
                        if raw is None:  # non-streamed request: no rows
                            break
                        nxt += 1
                        yield json.loads(raw)
                if delete:
                    self._call(
                        self.broker.hdel_many, self.result_key,
                        [uri] + [token_row_field(uri, i)
                                 for i in range(total)])
                blob.pop("hops", None)
                yield {"done": True, "tokens": decode_ndarray(blob),
                       "gen": gen}
                return
            now = time.monotonic()
            if keepalive_s is not None and now - last_keep >= keepalive_s:
                last_keep = now
                yield {"keepalive": True}
            if (stall_timeout_s is not None
                    and now - t_progress >= stall_timeout_s):
                try:
                    beats = self._call(self.broker.hgetall,
                                       engines_key(self.stream),
                                       deadline=deadline)
                except (ConnectionError, OSError):
                    beats = None      # can't tell: keep waiting
                if beats is not None:
                    if last_beats is not None and beats == last_beats:
                        # one full stall window with zero heartbeat
                        # progress (ts values are inside the row JSON,
                        # so ANY beat changes its row): the fleet is
                        # dead, not slow — answered failure, no hang
                        yield {"done": True, "error": "engine-dead",
                               "tokens": None, "gen": {}}
                        return
                    # first check (or progress seen): baseline and give
                    # the fleet one more full stall window
                    last_beats = beats
                    t_progress = now
            remaining = deadline - now
            if remaining <= 0:
                raise TimeoutError(
                    f"no completed result for {uri} within {timeout_s}s "
                    f"({nxt} token rows seen)")
            time.sleep(min(backoff, remaining))
            backoff = min(backoff * 2, 0.05)

    def _decode(self, raw: str, uri: Optional[str] = None):
        if raw == "NaN":   # per-record failure marker
            return float("nan")
        if raw == "SHED":  # admission shed (ISSUE 11): an answered
            return raw     # rejection — distinguishable from a failure
        if raw.startswith("["):  # filtered result string, e.g. topN(5)
            return raw
        blob = json.loads(raw)
        if isinstance(blob, dict) and "hops" in blob:
            hops = blob.pop("hops")
            if uri is not None and isinstance(hops, dict):
                if len(self.last_hops) >= self._MAX_HOPS:
                    self.last_hops.pop(next(iter(self.last_hops)))
                self.last_hops[uri] = hops
        return decode_ndarray(blob)
