"""Serving client — `InputQueue`/`OutputQueue` (`pyzoo/zoo/serving/client.py`).

Protocol preserved from the reference: `enqueue` XADDs a b64-encoded ndarray
(or image file) to the serving stream (`client.py:114`), `predict` is the
sync round-trip (`client.py:199` via the HTTP frontend there; here it polls
the result hash), `OutputQueue.query/dequeue` read results back
(`client.py:203`). Results arrive as b64 ndarrays or the literal "NaN" for
per-record failures (`ClusterServingInference.scala:71-79` degradation)."""

from __future__ import annotations

import json
import time
import uuid
from typing import Dict, Optional, Union

import numpy as np

from analytics_zoo_tpu.serving.broker import (Broker, connect_broker,
                                              decode_ndarray, encode_ndarray)

STREAM = "serving_stream"          # reference stream name
RESULT_KEY = "result:serving_stream"


class InputQueue:
    def __init__(self, broker: Union[Broker, str, None] = None,
                 stream: str = STREAM):
        self.broker = broker if isinstance(broker, Broker) \
            else connect_broker(broker)
        self.stream = stream

    def enqueue(self, uri: Optional[str] = None, tier: Optional[str] = None,
                **data) -> str:
        """`enqueue("uuid", t=ndarray)` or path/bytes via `image=`.
        `tier` (ISSUE 11) names the record's priority class — the
        engine's tiered scheduler dispatches higher tiers first and
        sheds the lowest tier first under overload; records without one
        rank lowest."""
        uri = uri or uuid.uuid4().hex
        payload: Dict = {}
        for name, value in data.items():
            if isinstance(value, np.ndarray):
                payload[name] = encode_ndarray(value)
            elif name == "image":
                payload[name] = self._encode_image(value)
            else:
                payload[name] = value
        record = {"uri": uri, "data": payload}
        if tier is not None:
            record["tier"] = str(tier)
        self.broker.xadd(self.stream, record)
        return uri

    @staticmethod
    def _encode_image(value) -> Dict:
        """Image path/bytes -> decoded float ndarray record (the reference
        ships b64 JPEG and decodes OpenCV-side; decode client-side here so
        the server stays shape-generic)."""
        from analytics_zoo_tpu.data.image import load_image
        arr = load_image(value)
        return encode_ndarray(arr.astype(np.float32))

    def predict(self, data: np.ndarray, timeout_s: float = 30.0,
                tier: Optional[str] = None) -> np.ndarray:
        """Sync path (`client.py:199`): enqueue then poll the result."""
        return self.predict_batch([np.asarray(data)], timeout_s,
                                  tier=tier)[0]

    def predict_batch(self, samples, timeout_s: float = 30.0,
                      tier: Optional[str] = None) -> list:
        """Sync multi-record path: each sample is ONE serving record (the
        per-instance contract of the reference frontend — records batch up
        inside the serving loop, not inside one record). Results return in
        input order; a failed record yields float('nan').

        Deadlines use `time.monotonic()` (a wall-clock step — NTP slew,
        suspend/resume — must not shrink or blow the budget), and idle
        polls back off exponentially from 1 ms to a 50 ms cap instead of
        hammering the broker at a fixed tight interval; any progress
        resets the backoff so a streaming burst is drained promptly."""
        uris = [self.enqueue(None, tier=tier, t=np.asarray(s))
                for s in samples]
        out = OutputQueue(self.broker, self.stream)
        results: dict = {}
        deadline = time.monotonic() + timeout_s
        backoff = 0.001
        while len(results) < len(uris):
            # deadline checked every pass, progress or not: trickling
            # results must tighten the remaining budget, not renew it
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            progress = False
            for uri in uris:
                if uri in results:
                    continue
                res = out.query(uri, delete=True)
                if res is not None:
                    results[uri] = res
                    progress = True
            if progress:
                backoff = 0.001
                continue
            time.sleep(min(backoff, max(0.0, remaining)))
            backoff = min(backoff * 2, 0.05)
        missing = [u for u in uris if u not in results]
        if missing:
            raise TimeoutError(
                f"No prediction for {len(missing)}/{len(uris)} records "
                f"within {timeout_s}s")
        return [results[u] for u in uris]


class OutputQueue:
    def __init__(self, broker: Union[Broker, str, None] = None,
                 stream: str = STREAM):
        self.broker = broker if isinstance(broker, Broker) \
            else connect_broker(broker)
        self.result_key = f"result:{stream}"

    def query(self, uri: str, delete: bool = False):
        raw = self.broker.hget(self.result_key, uri)
        if raw is None:
            return None
        if delete:
            self.broker.hdel(self.result_key, uri)
        return self._decode(raw)

    def dequeue(self) -> Dict[str, np.ndarray]:
        """Drain all results (`client.py:203` semantics): one read plus
        one batched delete, not one round trip per field."""
        allr = self.broker.hgetall(self.result_key)
        out = {}
        for uri, raw in allr.items():
            out[uri] = self._decode(raw)
        if allr:
            self.broker.hdel_many(self.result_key, list(allr))
        return out

    @staticmethod
    def _decode(raw: str):
        if raw == "NaN":   # per-record failure marker
            return float("nan")
        if raw == "SHED":  # admission shed (ISSUE 11): an answered
            return raw     # rejection — distinguishable from a failure
        if raw.startswith("["):  # filtered result string, e.g. topN(5)
            return raw
        return decode_ndarray(json.loads(raw))
