"""InferenceModel — the multi-backend concurrent inference façade.

Reference: `pipeline/inference/InferenceModel.scala:28`: a queue of
`concurrentNum` model copies (`:62,520-624`), loaders for every engine, and
thread-safe `doPredict`. TPU-native redesign:

- No model copies: a jit-compiled function is immutable and thread-safe;
  "concurrency" is a semaphore bounding in-flight predict calls (XLA
  serializes device work; the bound keeps host-side queuing sane) — with
  `auto_scaling` the permit count grows on contention like the reference's
  queue-cloning (`:587`).
- Dynamic shapes are the TPU hazard (recompiles), so predict pads the batch
  to a power-of-two bucket and caches one executable per bucket — the
  serving analogue of `hard_code_batch_size`.
- Loaders: native Keras-style models / ZooModel zoo dirs / pure fn+params /
  torch modules (via the torch bridge). The reference's TF/OpenVINO/Caffe
  loaders map onto the native-model path (their runtimes don't exist on TPU;
  weights must be converted, cf. `learn/torch_bridge.py`).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.serving.timer import Timer


def _next_bucket(n: int, buckets) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


class PendingPrediction:
    """Async handle from `predict_async`: the device computes while the
    caller keeps dispatching; `result()` materializes the output (the one
    blocking `np.asarray`) and slices off bucket padding. `result()` is
    idempotent and thread-safe — the sink stage and a curious caller can
    both touch it."""

    def __init__(self, out, valid_n: int, timer=None,
                 dispatch_s: float = 0.0):
        self._out = out
        self._n = valid_n
        self._timer = timer
        self._dispatch_s = dispatch_s
        self._result = None
        self._done = False
        self._lock = threading.Lock()

    def done(self) -> bool:
        """True once the device output is ready (or already materialized);
        a done() poll never blocks — it must not share the materialize
        lock, or polling would stall for the whole device sync inside a
        concurrent result()."""
        if self._done:
            return True
        out = self._out          # racy snapshot: result() may be midway
        if out is None:          # ... in which case it is done or about to be
            return True
        try:
            return all(a.is_ready() for a in
                       jax.tree_util.tree_leaves(out))
        except AttributeError:
            # jax without Array.is_ready(): report ready rather than
            # trap a done() poll loop at forever-False — result() is
            # the authoritative sync either way
            return True

    def result(self):
        with self._lock:
            if not self._done:
                t0 = time.perf_counter()
                out = jax.tree_util.tree_map(
                    lambda a: np.asarray(a)[:self._n], self._out)
                self._out = None            # free device refs promptly
                self._result = out
                self._done = True
                if self._timer is not None:
                    # model time = dispatch + materialize wait; time the
                    # handle sat unmaterialized (e.g. behind a slow sink
                    # queue) is excluded, so /metrics "predict" doesn't
                    # misattribute a broker stall to the device
                    self._timer.record(
                        self._dispatch_s + time.perf_counter() - t0)
        return self._result


class _JoinedPending:
    """PendingPrediction over max_batch chunks: each chunk was dispatched
    independently; result() syncs them in order and concatenates."""

    def __init__(self, parts: List[PendingPrediction]):
        self._parts = parts
        self._result = None
        self._done = False
        self._lock = threading.Lock()

    def done(self) -> bool:
        # lock-free like PendingPrediction.done(): _parts is reassigned
        # (never mutated), so a racy snapshot is safe and all([]) is True
        return self._done or all(p.done() for p in self._parts)

    def result(self):
        with self._lock:
            if not self._done:
                chunks = [p.result() for p in self._parts]
                self._result = jax.tree_util.tree_map(
                    lambda *cs: np.concatenate(cs), *chunks)
                self._parts = []
                self._done = True
        return self._result


class InferenceModel:
    def __init__(self, concurrent_num: int = 1, auto_scaling: bool = False,
                 max_batch: int = 512):
        self.concurrent_num = concurrent_num
        self.auto_scaling = auto_scaling
        self._sema = threading.BoundedSemaphore(concurrent_num) \
            if not auto_scaling else threading.Semaphore(concurrent_num)
        self._fn: Optional[Callable] = None
        self._params = None
        self.max_batch = max_batch
        self.buckets = [b for b in (1, 2, 4, 8, 16, 32, 64, 128, 256, 512)
                        if b <= max_batch] or [max_batch]
        self._jit: Optional[Callable] = None
        self.timer = Timer("predict")
        self.warmup_report: Dict[str, float] = {}
        self.warmed_buckets: set = set()

    # -- loaders (`doLoad*`, InferenceModel.scala:76-318) ------------------
    def load_keras(self, model, params=None,
                   quantize: Optional[str] = None) -> "InferenceModel":
        """A native Keras-style model (Sequential/Model/ZooModel).

        `quantize="int8"` rewrites every Dense/conv/Embedding weight to
        symmetric per-channel int8 and serves through the layers' int8
        MXU path (`serving/quantization.py`) — the TPU counterpart of the
        reference's OpenVINO int8 engine
        (`OpenVinoInferenceSupportive.scala:34-57`)."""
        from analytics_zoo_tpu.models.common import ZooModel
        if isinstance(model, ZooModel):
            model = model.model
        if params is not None:
            model.params = params
        if model.params is None:
            raise ValueError("Model has no parameters; fit or load first")
        params = model.params
        if quantize is not None:
            if quantize != "int8":
                raise ValueError(
                    f"Unsupported quantize={quantize!r}; only 'int8'")
            from analytics_zoo_tpu.serving.quantization import \
                quantize_model_params
            params = quantize_model_params(model, jax.device_get(params))
        return self.load_fn(lambda p, x: model.apply(p, x, training=False),
                            params)

    def load_zoo_model(self, cls, path: str,
                       quantize: Optional[str] = None) -> "InferenceModel":
        """`doLoadBigDL` analogue: a saved ZooModel directory."""
        return self.load_keras(cls.load_model(path), quantize=quantize)

    def load_quantized(self, model, path: str) -> "InferenceModel":
        """A pre-quantized int8 artifact (written by
        `serving.quantization.save_quantized`) onto `model`'s
        architecture — the `loadOpenVinoIRInt8` shape: ship the small
        int8 file, no f32 weights needed at serve time."""
        from analytics_zoo_tpu.models.common import ZooModel
        from analytics_zoo_tpu.serving.quantization import load_quantized
        net = model.model if isinstance(model, ZooModel) else model
        return self.load_fn(
            lambda p, x: net.apply(p, x, training=False),
            load_quantized(net, path))

    def load_fn(self, fn: Callable, params) -> "InferenceModel":
        """Pure `fn(params, x)` forward."""
        self._fn = fn
        # weights transfer ONCE at load: a host pytree here would be
        # re-uploaded on every predict (jit does not cache arg transfers)
        self._params = jax.device_put(params)
        # one jit wrapper; jax caches an executable per input shape (= per
        # bucket), so no per-bucket bookkeeping is needed
        self._jit = jax.jit(fn)
        self.warmup_report = {}
        self.warmed_buckets = set()
        return self

    def load_keras_encrypted(self, model, path: str, secret: str,
                             salt: str = "analytics-zoo"
                             ) -> "InferenceModel":
        """Encrypted-model analogue of `doLoadBigDL(path, secret)`
        (InferenceModel.scala:121-226): decrypt an AES-GCM-sealed param
        tree and attach it to the given architecture."""
        from analytics_zoo_tpu.learn.encrypted import load_encrypted_pytree
        from analytics_zoo_tpu.models.common import ZooModel
        params = load_encrypted_pytree(path, secret, salt)
        net = model.model if isinstance(model, ZooModel) else model
        params = net._remap_loaded(params)
        return self.load_keras(model, params=params)

    def load_torch(self, torch_module) -> "InferenceModel":
        """`doLoadPyTorch` analogue: convert the module natively (the
        reference embeds CPython via JEP; on TPU the model becomes XLA)."""
        from analytics_zoo_tpu.learn.torch_bridge import convert_torch_module
        native = convert_torch_module(torch_module)
        sample_shape = getattr(native, "input_shape", None)
        if native.params is None and sample_shape is not None:
            native.ensure_built(np.zeros((1,) + tuple(sample_shape[1:]),
                                         np.float32))
        return self.load_keras(native)

    # -- predict (`doPredict`, InferenceModel.scala:520-624) ---------------
    def predict(self, x) -> np.ndarray:
        """Sync predict: dispatch + materialize. Equivalent to
        `predict_async(x).result()`."""
        return self.predict_async(x).result()

    def predict_async(self, x, valid_n: Optional[int] = None):
        """Dispatch without syncing: pad to the shape bucket (on device —
        the raw batch uploads once and extends by broadcasting its last
        row, so the dispatch thread never runs a host-side pad copy),
        hand the padded batch to the cached per-bucket executable, and
        return a `PendingPrediction` immediately. XLA computes in the
        background; the caller (the serving sink stage) materializes via
        `.result()` while the dispatch thread feeds batch N+1.

        `valid_n` marks how many leading records are real when the caller
        already stacked the batch to a bucket size (the serving decode
        stage does: stacking straight to the bucket is free — the stack
        copies every record anyway — and skips the pad entirely)."""
        if self._fn is None:
            raise RuntimeError("No model loaded")
        x = jax.tree_util.tree_map(np.asarray, x)
        leaves = jax.tree_util.tree_leaves(x)
        n = leaves[0].shape[0] if leaves[0].ndim > 0 else 1
        valid_n = n if valid_n is None else min(valid_n, n)

        if n > self.max_batch:
            # split oversize inputs into max_batch chunks, all in flight
            parts = []
            for s in range(0, n, self.max_batch):
                part = jax.tree_util.tree_map(
                    lambda a: a[s:s + self.max_batch], x)
                remain = max(0, valid_n - s)
                parts.append(self.predict_async(
                    part, valid_n=min(remain, self.max_batch)))
            return _JoinedPending(parts)

        acquired = self._sema.acquire(timeout=60)
        if not acquired:
            if not self.auto_scaling:
                raise TimeoutError("predict queue exhausted "
                                   "(concurrent_num permits busy)")
            self._sema.release()  # grow like the reference's auto-scaling
        t0 = time.perf_counter()
        try:
            bucket = _next_bucket(n, self.buckets)
            if n != bucket:
                pad = bucket - n
                x = jax.tree_util.tree_map(
                    lambda a: jnp.concatenate(
                        [jnp.asarray(a),
                         jnp.broadcast_to(jnp.asarray(a)[-1:],
                                          (pad,) + a.shape[1:])]), x)
            out = self._jit(self._params, x)
        finally:
            # the permit bounds dispatch admission, not result lifetime:
            # async callers bound in-flight results with their own queue
            # (ClusterServing's sink queue), so holding the permit until
            # result() would serialize the pipeline at concurrent_num=1
            if acquired:
                self._sema.release()
        # recorded once at result(): dispatch cost + materialize wait
        return PendingPrediction(out, valid_n, timer=self.timer,
                                 dispatch_s=time.perf_counter() - t0)

    def predict_batches(self, xs: List) -> List:
        return [self.predict(x) for x in xs]

    # -- warmup (`warmup()` per-bucket pre-compile) ------------------------
    def warmup(self, sample, buckets: Optional[List[int]] = None
               ) -> "InferenceModel":
        """Pre-compile every shape bucket at load time so no XLA compile
        ever lands on the request path. `sample` is ONE record (no batch
        dim, serving dtype — executables are keyed on dtype too), e.g.
        ``np.zeros((32, 32, 3), np.float32)``, or a pytree of records for
        multi-input models. Per-bucket compile+run seconds land in
        ``self.warmup_report``; warmed buckets in ``self.warmed_buckets``."""
        if self._fn is None:
            raise RuntimeError("No model loaded")
        buckets = list(buckets) if buckets is not None else list(self.buckets)
        sample = jax.tree_util.tree_map(np.asarray, sample)
        tag = "x".join(map(str, jax.tree_util.tree_leaves(sample)[0].shape)
                       ) or "scalar"
        for b in buckets:
            batch = jax.tree_util.tree_map(
                lambda a: np.ascontiguousarray(
                    np.broadcast_to(a[None], (b,) + a.shape)), sample)
            t0 = time.perf_counter()
            # straight through the jit (not predict): warmup must not
            # pollute the serving timer percentiles
            jax.block_until_ready(self._jit(self._params, batch))
            self.warmup_report[f"{tag}:b{b}"] = round(
                time.perf_counter() - t0, 4)
            self.warmed_buckets.add(b)
        return self

    def compile_cache_size(self) -> int:
        """Number of cached executables (one per warmed shape bucket);
        -1 when the running jax version doesn't expose the counter."""
        try:
            return self._jit._cache_size()
        except Exception:  # noqa: BLE001 — diagnostics only
            return -1
