"""InferenceModel — the multi-backend concurrent inference façade.

Reference: `pipeline/inference/InferenceModel.scala:28`: a queue of
`concurrentNum` model copies (`:62,520-624`), loaders for every engine, and
thread-safe `doPredict`. TPU-native redesign:

- No model copies: a jit-compiled function is immutable and thread-safe;
  "concurrency" is a semaphore bounding in-flight predict calls (XLA
  serializes device work; the bound keeps host-side queuing sane) — with
  `auto_scaling` the permit count grows on contention like the reference's
  queue-cloning (`:587`).
- Dynamic shapes are the TPU hazard (recompiles), so predict pads the batch
  to a power-of-two bucket and caches one executable per bucket — the
  serving analogue of `hard_code_batch_size`.
- Loaders: native Keras-style models / ZooModel zoo dirs / pure fn+params /
  torch modules (via the torch bridge). The reference's TF/OpenVINO/Caffe
  loaders map onto the native-model path (their runtimes don't exist on TPU;
  weights must be converted, cf. `learn/torch_bridge.py`).

Multi-device placement (the reference scales by one model replica per Flink
task slot; here one per chip):

- **replicated** (`num_replicas=N`): one params copy per device
  (`jax.device_put(params, device)`), one cached executable per
  (replica, bucket) — jax keys its jit cache on the committed device —
  and a least-outstanding-work router with a per-replica in-flight
  bound. Each replica owns a worker thread because XLA's CPU backend
  executes in the dispatching thread: without per-replica threads N
  chips would serialize behind one dispatcher (a real TPU dispatch is
  async, where the extra hop costs ~µs).
- **sharded** (`placement="sharded"`): for models too large for one
  chip — params land with `NamedSharding`s from the GSPMD rule table
  (`parallel/sharding.py`, fsdp fallback) over a `common/mesh.py`
  DeviceMesh, and each batch is `device_put` split along the data axes.
  One logical replica spans every device; XLA emits the collectives.
- `num_replicas=1` (the default) is the original single-device path,
  byte-for-byte: bare `device_put`, single jit, no router, no threads.
"""

from __future__ import annotations

import functools
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.common import faults
from analytics_zoo_tpu.serving.timer import Timer

PLACEMENTS = ("replicated", "sharded")


class NoHealthyReplicaError(RuntimeError):
    """Every replica in the pool is quarantined: the router fails FAST
    (no 60 s permit wait) so callers can park work / answer 503 instead
    of hanging behind a fully-sick pool."""


def _next_bucket(n: int, buckets) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


class PendingPrediction:
    """Async handle from `predict_async`: the device computes while the
    caller keeps dispatching; `result()` materializes the output (the one
    blocking `np.asarray`) and slices off bucket padding. `result()` is
    idempotent and thread-safe — the sink stage and a curious caller can
    both touch it."""

    def __init__(self, out, valid_n: int, timer=None,
                 dispatch_s: float = 0.0, replica: int = 0,
                 roofline_cb: Optional[Callable[[float], None]] = None):
        self._out = out
        self._n = valid_n
        self._timer = timer
        self._dispatch_s = dispatch_s
        self.replica = replica        # which model replica computed this
        self._roofline_cb = roofline_cb
        self._result = None
        self._done = False
        self._lock = threading.Lock()

    def done(self) -> bool:
        """True once the device output is ready (or already materialized);
        a done() poll never blocks — it must not share the materialize
        lock, or polling would stall for the whole device sync inside a
        concurrent result()."""
        if self._done:
            return True
        out = self._out          # racy snapshot: result() may be midway
        if out is None:          # ... in which case it is done or about to be
            return True
        try:
            return all(a.is_ready() for a in
                       jax.tree_util.tree_leaves(out))
        except AttributeError:
            # jax without Array.is_ready(): report ready rather than
            # trap a done() poll loop at forever-False — result() is
            # the authoritative sync either way
            return True

    def result(self):
        with self._lock:
            if not self._done:
                t0 = time.perf_counter()
                out = jax.tree_util.tree_map(
                    lambda a: np.asarray(a)[:self._n], self._out)
                self._out = None            # free device refs promptly
                self._result = out
                self._done = True
                busy_s = self._dispatch_s + time.perf_counter() - t0
                if self._timer is not None:
                    # model time = dispatch + materialize wait; time the
                    # handle sat unmaterialized (e.g. behind a slow sink
                    # queue) is excluded, so /metrics "predict" doesn't
                    # misattribute a broker stall to the device
                    self._timer.record(busy_s)
                if self._roofline_cb is not None:
                    # utilization accounting rides the same measured
                    # window (accountant.account never raises)
                    self._roofline_cb(busy_s)
        return self._result


class _RoutedPending:
    """PendingPrediction fulfilled by a replica worker thread:
    `predict_async` returns it before the batch has even reached the
    device; the worker attaches the device output (or the dispatch
    failure, which `result()` re-raises so the serving sink's NaN
    degradation path sees it exactly like a synchronous dispatch
    error)."""

    def __init__(self, valid_n: int, timer=None, replica: int = 0,
                 on_done: Optional[Callable[[], None]] = None,
                 roofline_cb: Optional[Callable[[float], None]] = None):
        self._n = valid_n
        self._timer = timer
        self.replica = replica
        self._on_done = on_done
        self._roofline_cb = roofline_cb
        self._event = threading.Event()
        self._out = None
        self._exc: Optional[BaseException] = None
        self._dispatch_s = 0.0
        self._result = None
        self._done = False
        self._lock = threading.Lock()

    # -- worker side -------------------------------------------------------
    def _fulfill(self, out, dispatch_s: float):
        self._out = out
        self._dispatch_s = dispatch_s
        self._event.set()

    def _fail(self, exc: BaseException):
        self._exc = exc
        self._event.set()

    # -- consumer side -----------------------------------------------------
    def done(self) -> bool:
        """Never blocks (same contract as PendingPrediction.done): False
        until the worker has dispatched, then device-readiness."""
        if self._done:
            return True
        if not self._event.is_set():
            return False
        if self._exc is not None:
            return True
        out = self._out            # racy snapshot, same as PendingPrediction
        if out is None:
            return True
        try:
            return all(a.is_ready() for a in
                       jax.tree_util.tree_leaves(out))
        except AttributeError:
            return True

    def result(self):
        with self._lock:
            if not self._done:
                # the worker sets the event on every exit path
                # (_fulfill/_fail), and abandon() sets it too
                self._event.wait()  # blocking-ok: always signalled
                try:
                    if self._exc is None:
                        t0 = time.perf_counter()
                        out = jax.tree_util.tree_map(
                            lambda a: np.asarray(a)[:self._n], self._out)
                        self._out = None
                        self._result = out
                        busy_s = self._dispatch_s \
                            + time.perf_counter() - t0
                        if self._timer is not None:
                            self._timer.record(busy_s)
                        if self._roofline_cb is not None:
                            self._roofline_cb(busy_s)
                except Exception as e:  # noqa: BLE001 — keep for re-raise
                    self._exc = e
                finally:
                    # the replica permit releases exactly once, success or
                    # failure — a leak here would wedge the router
                    self._done = True
                    cb, self._on_done = self._on_done, None
                    if cb is not None:
                        cb()
            if self._exc is not None:
                raise self._exc
        return self._result

    def _rebind(self, replica: int, on_done) -> bool:
        """Quarantine re-dispatch: point this pending at a new replica
        (and its permit-release callback) BEFORE re-enqueueing it there.
        Refused (False) once the pending is already done/abandoned — the
        old callback has run and a rebind would leak the new permit.

        NON-blocking on the pending lock: the caller holds the router
        CV, and a sink thread can sit inside `result()` holding this
        lock while waiting for the event — blocking here would deadlock
        lock-order-inverted against `result()`'s `on_done` →
        `_release_replica` (CV) path. A contended pending simply
        refuses the rebind; the caller fails it instead (NaN degrade),
        which sets the event lock-free and unblocks that waiter."""
        if not self._lock.acquire(blocking=False):
            return False
        try:
            if self._done:
                return False
            self.replica = replica
            self._on_done = on_done
            return True
        finally:
            self._lock.release()

    def abandon(self):
        """Release the replica permit WITHOUT materializing — the
        shutdown-drop path (`ClusterServing._poison` discarding queued
        work once a stage is wedged): the device result is discarded and
        the broker's redelivery owns the records, but the permit must
        come back or the replica is down a slot forever."""
        with self._lock:
            if not self._done:
                self._done = True
                self._out = None
                cb, self._on_done = self._on_done, None
                if cb is not None:
                    cb()


class _Replica:
    """One device's slot in the replicated pool: committed params, a work
    queue, and the router's book-keeping. `inflight`/`batches`/
    `quarantined` are guarded by the model's router condition variable."""

    __slots__ = ("index", "device", "params", "inflight", "batches",
                 "work_q", "thread", "quarantined")

    def __init__(self, index: int, device, params):
        self.index = index
        self.device = device
        self.params = params
        self.inflight = 0          # routed but not yet materialized
        self.batches = 0           # total batches ever routed here
        self.quarantined = False   # supervisor pulled it from the router
        self.work_q: "queue.Queue" = queue.Queue()
        self.thread: Optional[threading.Thread] = None


class _JoinedPending:
    """PendingPrediction over max_batch chunks: each chunk was dispatched
    independently; result() syncs them in order and concatenates."""

    replica = None                 # spans replicas; no single owner

    def __init__(self, parts: List[PendingPrediction]):
        self._parts = parts
        self._result = None
        self._done = False
        self._lock = threading.Lock()

    def done(self) -> bool:
        # lock-free like PendingPrediction.done(): _parts is reassigned
        # (never mutated), so a racy snapshot is safe and all([]) is True
        return self._done or all(p.done() for p in self._parts)

    def result(self):
        with self._lock:
            if not self._done:
                chunks = [p.result() for p in self._parts]
                self._result = jax.tree_util.tree_map(
                    lambda *cs: np.concatenate(cs), *chunks)
                self._parts = []
                self._done = True
        return self._result


class InferenceModel:
    def __init__(self, concurrent_num: int = 1, auto_scaling: bool = False,
                 max_batch: int = 512,
                 num_replicas: Optional[int] = 1,
                 placement: str = "replicated",
                 devices: Optional[List] = None,
                 mesh=None,
                 max_inflight_per_replica: int = 2,
                 compile_cache=None):
        """`num_replicas`: model copies, one per device. 1 (default) keeps
        the original single-device path untouched; ``"auto"``/``-1``/``0``/
        ``None`` takes every local device. `placement="sharded"` instead
        spreads ONE copy across all devices (`mesh`, or a data+fsdp
        DeviceMesh over `devices`) for models too large for a chip.
        `max_inflight_per_replica` bounds routed-but-unmaterialized
        batches per replica — the router's backpressure.

        `compile_cache`: a `compile_cache.CompileCache` — warmup then
        consults the persistent executable cache per (replica, bucket)
        before compiling (hit → deserialize in ~ms; miss → compile once,
        persist, and every later process start hits). Replicated
        placement persists ONE entry per bucket and retarget-loads it
        onto each replica's device."""
        self.concurrent_num = concurrent_num
        self.auto_scaling = auto_scaling
        self._sema = threading.BoundedSemaphore(concurrent_num) \
            if not auto_scaling else threading.Semaphore(concurrent_num)
        self._fn: Optional[Callable] = None
        self._params = None
        self.max_batch = max_batch
        self.buckets = [b for b in (1, 2, 4, 8, 16, 32, 64, 128, 256, 512)
                        if b <= max_batch] or [max_batch]
        if placement not in PLACEMENTS:
            raise ValueError(
                f"placement={placement!r} not in {PLACEMENTS}")
        self.placement = placement
        devs = list(devices) if devices is not None else jax.local_devices()
        if not devs:
            raise ValueError("no devices available")
        if num_replicas in (None, 0, -1, "auto"):
            n = len(devs) if placement == "replicated" else 1
        else:
            n = int(num_replicas)
        if n < 1:
            raise ValueError(f"num_replicas={num_replicas!r} must be >= 1 "
                             "(or 'auto'/-1 for one per local device)")
        if n > len(devs):
            raise ValueError(
                f"num_replicas={n} exceeds the {len(devs)} available "
                "device(s); lower it or pass more devices")
        if placement == "sharded":
            n = 1                  # one logical replica spans the mesh
        self.num_replicas = n
        self.devices = devs[:n] if placement == "replicated" else devs
        # explicit devices pin replica 1 too; the bare default keeps the
        # legacy uncommitted device_put (single-replica byte-for-byte)
        self._pin_single = devices is not None
        self.mesh = mesh
        self.max_inflight_per_replica = max(1, int(max_inflight_per_replica))
        self._replicas: Optional[List[_Replica]] = None
        self._replica_cv = threading.Condition()
        self._rr = 0               # round-robin tie-break cursor
        # supervision hooks (serving/supervisor.py): outcome stream and
        # the canary batch probes reuse
        self._on_replica_event: Optional[Callable[[int, bool, float],
                                                  None]] = None
        self._last_input = None        # most recent dispatched batch
        self._last_good_input = None   # most recent SUCCESSFUL batch
        self._batch_sharding = None
        self._jit: Optional[Callable] = None
        self.timer = Timer("predict")
        self.warmup_report: Dict[str, float] = {}
        self.warmup_source: Dict[str, str] = {}
        self.warmed_buckets: set = set()
        # the per-record sample the last warmup() ran with — what a
        # restructured swap_params re-warms through the bucket path
        self._warmup_sample = None
        self.compile_cache = compile_cache
        # AOT executable table, (replica index, input signature) ->
        # jax.stages.Compiled — populated only by cache-backed warmup;
        # empty ⇒ every predict path is byte-for-byte the legacy jit
        self._aot: Dict[tuple, Any] = {}
        self._model_fp: Optional[str] = None
        # serving precision (ISSUE 12): set by load_fn from the weight
        # leaves; "float32" until a model loads
        self.serving_dtype: str = "float32"
        # roofline accounting (ISSUE 6): per-bucket XLA cost-analysis
        # FLOPs/bytes harvested at warmup, charged per materialized
        # batch against the measured predict time. Empty until warmup
        # runs — an unwarmed model pays nothing on the predict path.
        self._exec_cost: Dict[tuple, Any] = {}
        self._roofline = None

    # -- loaders (`doLoad*`, InferenceModel.scala:76-318) ------------------
    def load_keras(self, model, params=None,
                   quantize: Optional[str] = None) -> "InferenceModel":
        """A native Keras-style model (Sequential/Model/ZooModel).

        `quantize="int8"` rewrites every Dense/conv/Embedding weight to
        symmetric per-channel int8 and serves through the layers' int8
        MXU path (`serving/quantization.py`) — the TPU counterpart of the
        reference's OpenVINO int8 engine
        (`OpenVinoInferenceSupportive.scala:34-57`)."""
        from analytics_zoo_tpu.models.common import ZooModel
        if isinstance(model, ZooModel):
            model = model.model
        if params is not None:
            model.params = params
        if model.params is None:
            raise ValueError("Model has no parameters; fit or load first")
        params = model.params
        if quantize is not None:
            if quantize != "int8":
                raise ValueError(
                    f"Unsupported quantize={quantize!r}; only 'int8'")
            from analytics_zoo_tpu.serving.quantization import \
                quantize_model_params
            params = quantize_model_params(model, jax.device_get(params))
        return self.load_fn(lambda p, x: model.apply(p, x, training=False),
                            params)

    def load_zoo_model(self, cls, path: str,
                       quantize: Optional[str] = None) -> "InferenceModel":
        """`doLoadBigDL` analogue: a saved ZooModel directory."""
        return self.load_keras(cls.load_model(path), quantize=quantize)

    def load_quantized(self, model, path: str) -> "InferenceModel":
        """A pre-quantized int8 artifact (written by
        `serving.quantization.save_quantized`) onto `model`'s
        architecture — the `loadOpenVinoIRInt8` shape: ship the small
        int8 file, no f32 weights needed at serve time."""
        from analytics_zoo_tpu.models.common import ZooModel
        from analytics_zoo_tpu.serving.quantization import load_quantized
        net = model.model if isinstance(model, ZooModel) else model
        return self.load_fn(
            lambda p, x: net.apply(p, x, training=False),
            load_quantized(net, path))

    def load_checkpoint(self, model, path: str,
                        version: Optional[int] = None,
                        quantize: Optional[str] = None
                        ) -> "InferenceModel":
        """Serve a TRAINING checkpoint (`learn/checkpoint.py` layout)
        on `model`'s architecture. `quantize="int8"` prefers the
        checkpoint's pre-calibrated int8 sidecar
        (`fit_keras(int8_sidecar=True)` /
        `scripts/quantize_checkpoint.py`) — the shipped-artifact shape
        of the reference's int8 OpenVINO IR — and falls back to
        quantize-at-load when no intact sidecar exists (a torn sidecar
        costs a calibration, never the serve)."""
        from analytics_zoo_tpu.learn import checkpoint as ckpt_mod
        from analytics_zoo_tpu.models.common import ZooModel
        net = model.model if isinstance(model, ZooModel) else model
        if quantize is not None:
            if quantize != "int8":
                raise ValueError(
                    f"Unsupported quantize={quantize!r}; only 'int8'")
            # ONE resolution (shared with checkpoint.load_checkpoint),
            # reused below so the fallback never re-runs the CRC scan
            found = ckpt_mod.resolve_checkpoint(path, version)
            from analytics_zoo_tpu.serving.quantization import \
                load_int8_sidecar
            q = load_int8_sidecar(*found)
            if q is not None:
                remap = getattr(net, "_remap_loaded", None)
                return self.load_fn(
                    lambda p, x: net.apply(p, x, training=False),
                    remap(q) if remap is not None else q)
            path, version = found
        params, _, _ = ckpt_mod.load_checkpoint(path, version)
        remap = getattr(net, "_remap_loaded", None)
        if remap is not None:
            params = remap(params)
        return self.load_keras(net, params=params, quantize=quantize)

    @staticmethod
    def _infer_serving_dtype(params) -> str:
        """What precision this model SERVES in, from the weight leaves:
        any int8 leaf means the quantized MXU path ("int8"), else bf16
        weights mean "bfloat16", else "float32". The label every
        `serving_*` metric/span carries when non-default, and an
        explicit component of the compile-cache key — toggling dtype
        can never load the other precision's executable."""
        dtypes = {str(getattr(leaf, "dtype", ""))
                  for leaf in jax.tree_util.tree_leaves(params)}
        if "int8" in dtypes:
            return "int8"
        if "bfloat16" in dtypes:
            return "bfloat16"
        return "float32"

    def load_fn(self, fn: Callable, params) -> "InferenceModel":
        """Pure `fn(params, x)` forward."""
        self.close()               # reload: retire any old replica pool
        self._fn = fn
        self.serving_dtype = self._infer_serving_dtype(params)
        # one jit wrapper; jax caches an executable per input shape AND
        # per committed device/sharding, so each (replica, bucket) pair
        # gets its own cached executable with no bookkeeping here
        self._jit = jax.jit(fn)
        self._aot = {}
        self._model_fp = None
        if self.compile_cache is not None:
            from analytics_zoo_tpu.compile_cache import model_fingerprint
            # fingerprint BEFORE any device placement: the key must be
            # identical across processes, and device_put order is not
            self._model_fp = model_fingerprint(fn, params)
        if self.placement == "sharded":
            if self.mesh is None:
                from analytics_zoo_tpu.common.config import MeshConfig
                from analytics_zoo_tpu.common.mesh import DeviceMesh
                # fsdp carries both roles: params shard over it (the rule
                # table's fallback axis) and it is a batch axis, so the
                # input splits across every device too
                self.mesh = DeviceMesh(MeshConfig(data=1, fsdp=-1),
                                       self.devices)
            from analytics_zoo_tpu.parallel.sharding import shard_params
            self._params = shard_params(params, self.mesh)
            self._batch_sharding = self.mesh.batch_sharding()
            dp = self.mesh.data_parallel_size
            # buckets must split evenly over the data axes: GSPMD would
            # pad an uneven split, costing more than host-side padding to
            # the next divisible bucket. When NO power-of-two bucket
            # divides (dp=6, 12, ...), rebuild the ladder from dp itself
            # — a single max-size bucket would pad every request to
            # ~max_batch rows
            kept = [b for b in self.buckets if b % dp == 0]
            if not kept:
                b = dp
                while b <= self.max_batch:
                    kept.append(b)
                    b *= 2
            self.buckets = kept or [dp]
        elif self.num_replicas > 1:
            self._replicas = []
            for i, dev in enumerate(self.devices):
                rep = _Replica(i, dev, jax.device_put(params, dev))
                rep.thread = threading.Thread(
                    target=self._replica_loop, args=(rep,),
                    name=f"infer-replica-{i}", daemon=True)
                rep.thread.start()
                self._replicas.append(rep)
        elif self._pin_single:
            self._params = jax.device_put(params, self.devices[0])
        else:
            # weights transfer ONCE at load: a host pytree here would be
            # re-uploaded on every predict (jit does not cache arg
            # transfers)
            self._params = jax.device_put(params)
        self.warmup_report = {}
        self.warmup_source = {}
        self.warmed_buckets = set()
        self._warmup_sample = None
        # fresh program, fresh roofline: the live serving gauges must
        # describe THIS model, not whatever was loaded before
        self._exec_cost = {}
        try:
            from analytics_zoo_tpu.observability.roofline import \
                get_accountant
            self._roofline = get_accountant()
            self._roofline.reset("serving")
        except Exception:  # noqa: BLE001 — telemetry only
            self._roofline = None
        return self

    # -- hot swap (ISSUE 14: zero-downtime model rollout) ------------------
    def current_params(self) -> Any:
        """The LIVE device-resident weight tree (replica 0's copy for a
        replicated pool; None until a model loads). What a rollout agent
        snapshots before `swap_params` so a failed canary restores the
        exact serving state without a disk round trip."""
        if self._replicas:
            return self._replicas[0].params
        return self._params

    @staticmethod
    def _swap_signature(tree) -> tuple:
        """Post-transfer aval signature for the swap's structure test:
        treedef + per-leaf (shape, CANONICAL dtype). jax canonicalizes
        host dtypes at `device_put` (float64 → float32 with x64 off),
        so a float64 host checkpoint swapped onto an f32 live tree
        lands as the SAME executable structure — comparing raw host
        dtypes would misread it as a restructure and pay a pointless
        recompile."""
        from jax import dtypes as jdtypes
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        return (str(treedef),
                tuple((tuple(np.shape(leaf)),
                       str(jdtypes.canonicalize_dtype(
                           getattr(leaf, "dtype", None)
                           or np.asarray(leaf).dtype)))
                      for leaf in leaves))

    def swap_params(self, params) -> str:
        """Replace the served weights WITHOUT reloading the model — the
        engine-side primitive of a versioned rollout. Returns how the
        executables fared:

        - ``"same"`` — the new tree has the identical structure, leaf
          shapes and dtypes as the live one. Params are swapped in
          place (per replica device / resharded onto the mesh) and
          every cached executable — the AOT table and jax's jit cache
          both key on the params *structure*, never its values — keeps
          serving: a same-shape swap costs **zero XLA compiles**.
        - ``"restructured"`` — the tree changed shape (new layer, new
          dtype, int8⇄f32). There is no honest way to keep the old
          executables, so the model reloads through `load_fn` (fresh
          jit, fresh AOT/cost tables, fresh fingerprint) and re-warms
          the previously-warmed buckets through the existing warmup
          path — the caller pays real compiles, visibly, instead of a
          silent structure mismatch at dispatch time.

        Swapping is reference-atomic per replica: a batch already
        dispatched keeps the tree it captured; the next dispatch sees
        the new one. Callers wanting a version boundary with no mixed
        batches (the rollout agent) drain dispatch first —
        `ClusterServing.pause_intake()` + `quiesce()`."""
        if self._fn is None:
            raise RuntimeError("No model loaded; load_* before swapping")
        live = self.current_params()
        new_sig, live_sig = self._swap_signature(params), \
            self._swap_signature(live)
        if new_sig != live_sig:
            import logging
            logging.getLogger("analytics_zoo_tpu.serving").info(
                "swap_params: structure changed (%s -> %s); honest "
                "reload + re-warmup", live_sig, new_sig)
            sample, buckets = self._warmup_sample, sorted(
                self.warmed_buckets)
            self.load_fn(self._fn, params)
            if sample is not None:
                self.warmup(sample, buckets=buckets or None)
            return "restructured"
        if self.placement == "sharded" and self.mesh is not None:
            from analytics_zoo_tpu.parallel.sharding import shard_params
            self._params = shard_params(params, self.mesh)
        elif self._replicas is not None:
            with self._replica_cv:
                reps = self._replicas
                if reps is None:
                    raise RuntimeError(
                        "replica pool closed mid-swap; reload the model")
                for rep in reps:
                    rep.params = jax.device_put(params, rep.device)
        elif self._pin_single:
            self._params = jax.device_put(params, self.devices[0])
        else:
            self._params = jax.device_put(params)
        return "same"

    # -- roofline accounting (observability/roofline.py) -------------------
    @staticmethod
    def _cost_key(x) -> tuple:
        """Per-batch cost-table key: leaf shapes/dtypes only (the params
        side is fixed per model) — cheap enough for the dispatch path.
        The shared `compile_cache.key.cheap_signature` so this can never
        drift from the AOT cache's spelling."""
        from analytics_zoo_tpu.compile_cache.key import cheap_signature
        return cheap_signature(x)

    def _program_span(self) -> int:
        """Devices one forward call spans: the whole mesh for sharded
        placement, one device otherwise (each replica runs its own
        single-device program)."""
        if self.placement == "sharded" and self.mesh is not None:
            return self.mesh.n_devices
        return 1

    def _record_cost(self, batch, stages_obj):
        """Harvest per-call FLOPs/bytes from a Compiled/Lowered for this
        batch shape; silently absent when the backend has no cost
        model. Callers hand a partitioned (sharded-placement)
        EXECUTABLE to `_harvest_jit_cost` instead: its cost analysis
        counts one device's per-device module, not the logical model
        cost (`roofline.ExecCost` basis contract)."""
        try:
            key = self._cost_key(batch)
            if key in self._exec_cost:
                return
            from analytics_zoo_tpu.observability.roofline import cost_of
            c = cost_of(stages_obj)
            if c is not None:
                self._exec_cost[key] = c
        except Exception:  # noqa: BLE001 — telemetry only
            pass

    def _harvest_jit_cost(self, params, batch):
        """Jit-path warmup harvest: lowering is cheap next to the XLA
        compile warmup is already paying, and `Lowered.cost_analysis()`
        matches the compiled numbers on this backend."""
        if self._cost_key(batch) in self._exec_cost:
            return
        try:
            low = self._jit.lower(params, batch)
        except Exception:  # noqa: BLE001 — telemetry only
            return
        self._record_cost(batch, low)

    def _roofline_cb(self, x):
        """The per-batch accounting callback for a pending, or None when
        this batch shape has no harvested cost (e.g. no warmup ran)."""
        if not self._exec_cost or self._roofline is None:
            return None
        cost = self._exec_cost.get(self._cost_key(x))
        if cost is None:
            return None
        acct = self._roofline
        span = self._program_span()
        return lambda secs, _c=cost, _a=acct, _n=span: _a.account(
            "serving", _c.flops, _c.bytes, secs, n_devices=_n)

    # -- persistent compile cache (compile_cache/) -------------------------
    @staticmethod
    def _exec_sig(x) -> tuple:
        """In-process executable-table key: tree structure + per-leaf
        shape/dtype of the (bucket-padded) batch."""
        from analytics_zoo_tpu.compile_cache import abstract_signature
        return abstract_signature(x)

    def _cache_key(self, sig):
        from analytics_zoo_tpu.compile_cache import make_key
        sharding = ""
        if self.placement == "sharded" and self.mesh is not None:
            # the RULE TABLE is part of the layout, not just the mesh:
            # two tables (or two versions of the default table) can
            # place the same params differently on the same mesh, and a
            # persisted executable embeds its input layout. ONE
            # canonical spelling shared with the trainer's step key
            # (parallel/sharding.sharding_descriptor), plus the device
            # ids this executable's assignment is pinned to.
            from analytics_zoo_tpu.parallel.sharding import \
                sharding_descriptor
            sharding = sharding_descriptor(self.mesh,
                                           devices=self.devices)
        # serving_dtype is an EXPLICIT key component (the params
        # structure already differs between f32 and int8 trees, but the
        # isolation must not hinge on a fingerprint heuristic): an int8
        # reload can never deserialize the bf16/f32 executable, and
        # vice versa. Default-f32 keys stay byte-identical to pre-ISSUE
        # 12 entries (no fleet-wide cache invalidation).
        return make_key("serving", self._model_fp or "", sig,
                        placement=self.placement, sharding=sharding,
                        dtype=self.serving_dtype
                        if self.serving_dtype != "float32" else "")

    def _aot_call(self, replica_idx: int, params, x):
        """One forward through the AOT table when it has an executable
        for this (replica, signature), else through the jit wrapper —
        the ONLY dispatch point shared by all three placement paths."""
        if self._aot:
            ex = self._aot.get((replica_idx, self._exec_sig(x)))
            if ex is not None:
                return ex(params, x)
        return self._jit(params, x)

    def _warm_executable(self, replica_idx: int, params, batch,
                         target_device_id=None) -> str:
        """Cache-backed warmup for one (replica, bucket): consult the
        persistent cache before compiling; returns how the executable
        was obtained ("warm" | "cached" | "compiled")."""
        from analytics_zoo_tpu.compile_cache import serialization
        sig = self._exec_sig(batch)
        if (replica_idx, sig) in self._aot:
            return "warm"
        key = self._cache_key(sig)
        ex = self.compile_cache.load(key, target_device_id=target_device_id)
        if ex is not None:
            stored = serialization.args_treedef(ex)
            live = serialization.live_treedef((params, batch))
            if stored != live:
                # same canonical structure, different auto-numbered
                # layer names (a naming-counter offset between the
                # persisting process and this one): adapt the call
                # rather than rejecting the hit
                ex = serialization.retree_call(ex, stored)
            self._aot[(replica_idx, sig)] = ex
            # AOT-cache loads are a harvest point too: deserialized
            # executables still answer cost_analysis(). A sharded
            # (partitioned) executable reports per-device cost — the
            # logical basis needs the lowered module instead
            if self._program_span() > 1:
                self._harvest_jit_cost(params, batch)
            else:
                self._record_cost(batch, ex)
            return "cached"
        t0 = time.perf_counter()
        # module-attribute call: serialization.compile_lowered is THE
        # fresh-compile funnel tests monkeypatch to assert zero compiles
        ex = serialization.compile_lowered(self._jit.lower(params, batch))
        self.compile_cache.put(  # blocking-ok: disk cache write, not a queue
            key, ex, compile_ms=(time.perf_counter() - t0) * 1e3)
        self._aot[(replica_idx, sig)] = ex
        if self._program_span() > 1:
            self._harvest_jit_cost(params, batch)
        else:
            self._record_cost(batch, ex)
        return "compiled"

    def _replica_loop(self, rep: _Replica):
        """Per-replica dispatcher: XLA:CPU executes in the calling thread,
        so each replica needs its own; on TPU the jit call returns as soon
        as the async dispatch is enqueued and this thread is just a cheap
        hop. `t0` is the router hand-off time, so `dispatch_s` covers
        queue wait + dispatch (+ compute, on synchronous backends).

        Every job's outcome + latency reports through
        `_on_replica_event` (the ReplicaSupervisor's feed) unless the
        replica is quarantined — queued-before-quarantine stragglers and
        canary probes must not double-count against or for it. The
        `replica.dispatch` fault-injection point sits where a real chip
        fault would land."""
        while True:
            try:
                job = rep.work_q.get(timeout=1.0)
            except queue.Empty:
                continue
            if job is None:
                return
            x, pending, t0 = job
            t_start = time.perf_counter() if t0 is None else t0
            # the canary the supervisor probes quarantined replicas
            # with: the input is valid whatever the replica does to it
            self._last_input = x
            try:
                faults.fire("replica.dispatch", replica=rep.index,
                            batch=rep.batches)
                if self._aot:
                    ex = self._aot.get((rep.index, self._exec_sig(x)))
                    if ex is not None:
                        # AOT executables are strict about committed
                        # placement: land the batch on this replica's
                        # device first (a no-op when already there)
                        x = jax.device_put(x, rep.device)
                        out = ex(rep.params, x)
                    else:
                        out = self._jit(rep.params, x)
                else:
                    out = self._jit(rep.params, x)
                # the PREFERRED canary: an input a replica has actually
                # handled successfully — probing with the most recent
                # raw input alone would replay a poison batch forever
                # and turn one bad input into an unrevivable pool
                self._last_good_input = x
                pending._fulfill(out, time.perf_counter() - t_start)
                self._notify_replica(rep, True,
                                     time.perf_counter() - t_start)
            except Exception as e:  # noqa: BLE001 — surfaces in result()
                pending._fail(e)
                self._notify_replica(rep, False,
                                     time.perf_counter() - t_start)

    def _notify_replica(self, rep: _Replica, ok: bool, latency_s: float):
        cb = self._on_replica_event
        if cb is None or rep.quarantined:
            return
        try:
            cb(rep.index, ok, latency_s)
        except Exception:  # noqa: BLE001 — supervision must never take
            pass           # down the dispatch path it watches

    def close(self):
        """Retire the replica pool's worker threads (no-op otherwise).
        Safe to call repeatedly; `load_fn` calls it on reload. Stop the
        serving engine BEFORE closing a model it still routes through —
        after close the model needs a fresh `load_*` to predict again."""
        with self._replica_cv:
            # swap the pool out under the router CV: a concurrent
            # predict_async either enqueued its job BEFORE this point
            # (FIFO: the worker fulfills it before seeing the pill) or
            # sees the dead pool and raises the clear closed error. The
            # notify wakes permit-blocked routers into that error now,
            # not after their 60s timeout.
            reps, self._replicas = self._replicas, None
            self._replica_cv.notify_all()
        if reps:
            for rep in reps:
                rep.work_q.put_nowait(None)
            for rep in reps:
                if rep.thread is not None:
                    rep.thread.join(timeout=5)
            # the pool was the only executor (no single-device _params):
            # a predict now must say "load first", not jit(None, x)
            if self._params is None:
                self._fn = None

    # -- router ------------------------------------------------------------
    def _acquire_replica(self, timeout: float = 60.0) -> _Replica:
        """Least-outstanding-work selection with a per-replica in-flight
        bound; round-robin tie-break so equally-idle replicas alternate
        instead of piling onto index 0. Blocks (bounded) when every
        replica is at the bound — the router's backpressure."""
        deadline = time.monotonic() + timeout
        with self._replica_cv:
            while True:
                reps = self._replicas
                if reps is None:
                    # close()/load_fn() retired the pool mid-route (the
                    # documented misuse — stop the engine first); fail
                    # with the real cause, not a NoneType iteration
                    raise RuntimeError(
                        "replica pool closed while routing; stop the "
                        "serving engine before close()/load_fn()")
                healthy = [r for r in reps if not r.quarantined]
                if not healthy:
                    # fail FAST, not after the 60s permit wait: the
                    # caller (dispatch stage / frontend) owns the
                    # park-or-503 decision
                    raise NoHealthyReplicaError(
                        f"all {len(reps)} replicas are quarantined; "
                        "waiting on canary revival")
                free = [r for r in healthy
                        if r.inflight < self.max_inflight_per_replica]
                if free:
                    lo = min(r.inflight for r in free)
                    n = len(reps)
                    rep = min((r for r in free if r.inflight == lo),
                              key=lambda r: (r.index - self._rr) % n)
                    self._rr = (rep.index + 1) % n
                    rep.inflight += 1
                    rep.batches += 1
                    return rep
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._replica_cv.wait(remaining):
                    raise TimeoutError(
                        "every model replica is at its in-flight bound "
                        f"({self.max_inflight_per_replica}); results are "
                        "not being materialized")

    def _release_replica(self, rep: _Replica):
        with self._replica_cv:
            rep.inflight -= 1
            self._replica_cv.notify()

    # -- quarantine / revival (driven by serving/supervisor.py) ------------
    def quarantine_replica(self, index: int) -> bool:
        """Pull one replica out of the routing set: the router stops
        considering it, and every job still QUEUED on it (not yet picked
        up by its worker) re-dispatches to the least-loaded healthy
        replica with its in-flight permit transferred. The job the
        worker is currently executing finishes (or fails) normally.
        Idempotent; returns True when this call made the transition."""
        with self._replica_cv:
            reps = self._replicas
            if reps is None or index >= len(reps):
                return False
            rep = reps[index]
            if rep.quarantined:
                return False
            rep.quarantined = True
            healthy = [r for r in reps if not r.quarantined]
            moved = []
            while True:
                try:
                    job = rep.work_q.get_nowait()
                except queue.Empty:
                    break
                if job is None:
                    # close() pill mid-quarantine: the worker must still
                    # see it, and it carries no permit
                    rep.work_q.put_nowait(job)
                    break
                moved.append(job)
            for x, pending, t0 in moved:
                target = min(healthy, key=lambda r: r.inflight) \
                    if healthy else None
                if target is not None and pending._rebind(
                        target.index,
                        lambda _r=target: self._release_replica(_r)):
                    # permit transfer: the quarantined slot frees now,
                    # the target's releases via the rebound callback
                    rep.inflight -= 1
                    target.inflight += 1
                    target.batches += 1
                    # t0 resets: charging the detour (queue wait on the
                    # dead replica) to the healthy target's supervised
                    # latency would read as an outlier and cascade the
                    # quarantine across the pool
                    target.work_q.put_nowait((x, pending,
                                              time.perf_counter()))
                else:
                    # no healthy replica left (or the pending already
                    # finished): fail it — the serving sink degrades the
                    # batch to NaN and the OLD permit releases through
                    # the pending's original callback
                    pending._fail(NoHealthyReplicaError(
                        "replica quarantined with no healthy peer to "
                        "re-dispatch to"))
            self._replica_cv.notify_all()
            return True

    def revive_replica(self, index: int) -> bool:
        """Return a quarantined replica to the routing set (the
        supervisor calls this after a successful canary probe)."""
        with self._replica_cv:
            reps = self._replicas
            if reps is None or index >= len(reps) \
                    or not reps[index].quarantined:
                return False
            reps[index].quarantined = False
            self._replica_cv.notify_all()
            return True

    def healthy_replicas(self) -> int:
        """Replicas currently accepting routed work (the whole model for
        the single-device and sharded paths)."""
        reps = self._replicas
        if reps is None:
            return self.num_replicas
        with self._replica_cv:
            return sum(1 for r in reps if not r.quarantined)

    def quarantined_replicas(self) -> List[int]:
        reps = self._replicas
        if reps is None:
            return []
        with self._replica_cv:
            return [r.index for r in reps if r.quarantined]

    def probe_replica_async(self, index: int, x=None):
        """Enqueue a canary batch on `index`'s worker (bypassing the
        router — a quarantined replica still drains its queue) and
        return the `_RoutedPending` WITHOUT waiting, or None when there
        is nothing to probe with. `x` defaults to the most recent batch
        any replica handled SUCCESSFULLY (falling back to the most
        recent dispatched batch when no success ever happened — e.g.
        every replica faulted from the first record): a poison input
        must not become the only canary, or revival could never
        succeed."""
        reps = self._replicas
        if reps is None or index >= len(reps):
            return None
        x = x if x is not None else (
            self._last_good_input if self._last_good_input is not None
            else self._last_input)
        if x is None:
            return None                # nothing credible to probe with
        leaves = jax.tree_util.tree_leaves(x)
        n = leaves[0].shape[0] if leaves and leaves[0].ndim > 0 else 1
        pending = _RoutedPending(n, timer=None, replica=index)
        reps[index].work_q.put_nowait((x, pending, None))
        return pending

    def probe_replica(self, index: int, x=None,
                      timeout_s: float = 10.0) -> bool:
        """Blocking canary probe: True iff the forward completes within
        the budget — the revival signal. (The supervisor uses the async
        variant so one wedged replica cannot stall the probe loop.)"""
        pending = self.probe_replica_async(index, x)
        if pending is None:
            return False
        if not pending._event.wait(timeout_s):
            return False
        try:
            pending.result()
        except Exception:  # noqa: BLE001 — a failing probe IS the signal
            return False
        return True

    def replica_inflight(self, index: int) -> int:
        """Routed-but-unmaterialized batches on one replica (live; 0 for
        the single-device and sharded paths)."""
        reps = self._replicas
        if reps is None or index >= len(reps):
            return 0
        return reps[index].inflight

    def replica_stats(self) -> List[Dict[str, Any]]:
        """Per-replica routing book-keeping for metrics/bench output."""
        if self._replicas is None:
            return [{"replica": 0, "device": str(d), "batches": None,
                     "inflight": 0}
                    for d in (self.devices[:1] if self.placement ==
                              "replicated" else self.devices)]
        with self._replica_cv:
            return [{"replica": r.index, "device": str(r.device),
                     "batches": r.batches, "inflight": r.inflight,
                     "quarantined": r.quarantined}
                    for r in self._replicas]

    def weight_bytes(self) -> int:
        """LOGICAL bytes of the loaded weight leaves (one copy's worth —
        replication and sharding don't change the number; a sharded
        jax.Array reports its global nbytes). 0 until a model loads.
        The honest byte price the `serving_weight_bytes` gauge
        publishes: int8 weights read ~4x under their f32 source."""
        if self._replicas:
            tree = self._replicas[0].params
        else:
            tree = self._params
        if tree is None:
            return 0
        return sum(int(getattr(leaf, "nbytes", 0))
                   for leaf in jax.tree_util.tree_leaves(tree))

    def placement_info(self) -> Dict[str, Any]:
        """Placement summary for `ClusterServing.metrics()` / the CLI."""
        info: Dict[str, Any] = {"placement": self.placement,
                                "num_replicas": self.num_replicas,
                                "n_devices": len(self.devices),
                                "serving_dtype": self.serving_dtype}
        if self.placement == "sharded" and self.mesh is not None:
            info["mesh"] = {a: s for a, s in self.mesh.axis_sizes.items()
                            if s != 1}
            info["data_parallel_size"] = self.mesh.data_parallel_size
        return info

    def load_keras_encrypted(self, model, path: str, secret: str,
                             salt: str = "analytics-zoo"
                             ) -> "InferenceModel":
        """Encrypted-model analogue of `doLoadBigDL(path, secret)`
        (InferenceModel.scala:121-226): decrypt an AES-GCM-sealed param
        tree and attach it to the given architecture."""
        from analytics_zoo_tpu.learn.encrypted import load_encrypted_pytree
        from analytics_zoo_tpu.models.common import ZooModel
        params = load_encrypted_pytree(path, secret, salt)
        net = model.model if isinstance(model, ZooModel) else model
        params = net._remap_loaded(params)
        return self.load_keras(model, params=params)

    def load_torch(self, torch_module) -> "InferenceModel":
        """`doLoadPyTorch` analogue: convert the module natively (the
        reference embeds CPython via JEP; on TPU the model becomes XLA)."""
        from analytics_zoo_tpu.learn.torch_bridge import convert_torch_module
        native = convert_torch_module(torch_module)
        sample_shape = getattr(native, "input_shape", None)
        if native.params is None and sample_shape is not None:
            native.ensure_built(np.zeros((1,) + tuple(sample_shape[1:]),
                                         np.float32))
        return self.load_keras(native)

    # -- predict (`doPredict`, InferenceModel.scala:520-624) ---------------
    def predict(self, x) -> np.ndarray:
        """Sync predict: dispatch + materialize. Equivalent to
        `predict_async(x).result()`."""
        return self.predict_async(x).result()

    def predict_async(self, x, valid_n: Optional[int] = None):
        """Dispatch without syncing: pad to the shape bucket (on device —
        the raw batch uploads once and extends by broadcasting its last
        row, so the dispatch thread never runs a host-side pad copy),
        hand the padded batch to the cached per-bucket executable, and
        return a `PendingPrediction` immediately. XLA computes in the
        background; the caller (the serving sink stage) materializes via
        `.result()` while the dispatch thread feeds batch N+1.

        `valid_n` marks how many leading records are real when the caller
        already stacked the batch to a bucket size (the serving decode
        stage does: stacking straight to the bucket is free — the stack
        copies every record anyway — and skips the pad entirely)."""
        if self._fn is None:
            raise RuntimeError("No model loaded")
        x = jax.tree_util.tree_map(np.asarray, x)
        leaves = jax.tree_util.tree_leaves(x)
        n = leaves[0].shape[0] if leaves[0].ndim > 0 else 1
        valid_n = n if valid_n is None else min(valid_n, n)

        if n > self.max_batch:
            # split oversize inputs into max_batch chunks, all in flight
            parts = []
            for s in range(0, n, self.max_batch):
                part = jax.tree_util.tree_map(
                    lambda a: a[s:s + self.max_batch], x)
                remain = max(0, valid_n - s)
                parts.append(self.predict_async(
                    part, valid_n=min(remain, self.max_batch)))
            return _JoinedPending(parts)

        acquired = self._sema.acquire(timeout=60)
        if not acquired:
            if not self.auto_scaling:
                raise TimeoutError("predict queue exhausted "
                                   "(concurrent_num permits busy)")
            self._sema.release()  # grow like the reference's auto-scaling
        t0 = time.perf_counter()
        try:
            bucket = _next_bucket(n, self.buckets)
            if n != bucket:
                pad = bucket - n
                x = jax.tree_util.tree_map(
                    lambda a: jnp.concatenate(
                        [jnp.asarray(a),
                         jnp.broadcast_to(jnp.asarray(a)[-1:],
                                          (pad,) + a.shape[1:])]), x)
            rcb = self._roofline_cb(x)
            if self._replicas is not None:
                # replica pool: route to the least-loaded device and
                # return immediately — its worker thread dispatches.
                # acquire AND enqueue under the router CV (an RLock, so
                # _acquire_replica re-enters): close() also swaps the
                # pool out under it, so a job can never land behind a
                # worker's stop pill and wait forever unfulfilled
                with self._replica_cv:
                    rep = self._acquire_replica()
                    pending = _RoutedPending(
                        valid_n, timer=self.timer, replica=rep.index,
                        on_done=lambda rep=rep:
                            self._release_replica(rep),
                        roofline_cb=rcb)
                    rep.work_q.put_nowait((x, pending, t0))
                return pending
            if self._batch_sharding is not None:
                # sharded placement: split the (bucket-padded, so evenly
                # divisible) batch along the data axes before the call
                x = jax.device_put(x, self._batch_sharding)
            if self._params is None:
                # a concurrent close() retired a replica pool between
                # the _fn check and here: params never existed on the
                # single-device path — fail clearly, not jit(None, x)
                raise RuntimeError(
                    "model closed mid-predict; reload before predicting")
            out = self._aot_call(0, self._params, x)
        finally:
            # the permit bounds dispatch admission, not result lifetime:
            # async callers bound in-flight results with their own queue
            # (ClusterServing's sink queue), so holding the permit until
            # result() would serialize the pipeline at concurrent_num=1
            if acquired:
                self._sema.release()
        # recorded once at result(): dispatch cost + materialize wait
        return PendingPrediction(out, valid_n, timer=self.timer,
                                 dispatch_s=time.perf_counter() - t0,
                                 roofline_cb=rcb)

    def predict_batches(self, xs: List) -> List:
        return [self.predict(x) for x in xs]

    # -- warmup (`warmup()` per-bucket pre-compile) ------------------------
    def warmup(self, sample, buckets: Optional[List[int]] = None
               ) -> "InferenceModel":
        """Pre-compile every shape bucket at load time so no XLA compile
        ever lands on the request path. `sample` is ONE record (no batch
        dim, serving dtype — executables are keyed on dtype too), e.g.
        ``np.zeros((32, 32, 3), np.float32)``, or a pytree of records for
        multi-input models. Per-bucket compile+run seconds land in
        ``self.warmup_report``; warmed buckets in ``self.warmed_buckets``."""
        if self._fn is None:
            raise RuntimeError("No model loaded")
        buckets = list(buckets) if buckets is not None else list(self.buckets)
        if self._batch_sharding is not None:
            # sharded placement only ever sees divisible buckets; all
            # indivisible → warm the smallest real bucket, not nothing
            dp = self.mesh.data_parallel_size
            buckets = [b for b in buckets if b % dp == 0] or \
                [self.buckets[0]]
        sample = jax.tree_util.tree_map(np.asarray, sample)
        self._warmup_sample = sample
        tag = "x".join(map(str, jax.tree_util.tree_leaves(sample)[0].shape)
                       ) or "scalar"
        use_cache = self._use_compile_cache()
        if self._replicas is not None:
            return self._warmup_replicas(sample, buckets, tag, use_cache)
        for b in buckets:
            batch = jax.tree_util.tree_map(
                lambda a: np.ascontiguousarray(
                    np.broadcast_to(a[None], (b,) + a.shape)), sample)
            if self._batch_sharding is not None:
                batch = jax.device_put(batch, self._batch_sharding)
            t0 = time.perf_counter()
            if use_cache:
                # persistent cache first: a hit deserializes in ~ms
                # where a miss compiles once and persists for the next
                # process. Sharded executables keep their stored device
                # assignment (the mesh is part of the key); the single-
                # device executable re-pins onto this model's device.
                src = self._warm_executable(
                    0, self._params, batch,
                    target_device_id=None if self._batch_sharding
                    is not None else self.devices[0].id)
                jax.block_until_ready(
                    self._aot[(0, self._exec_sig(batch))](
                        self._params, batch))
            else:
                src = "jit"
                # straight through the jit (not predict): warmup must
                # not pollute the serving timer percentiles
                jax.block_until_ready(self._jit(self._params, batch))
                self._harvest_jit_cost(self._params, batch)
            rkey = f"{tag}:b{b}"
            self.warmup_report[rkey] = round(time.perf_counter() - t0, 4)
            self.warmup_source[rkey] = src
            self.warmed_buckets.add(b)
        return self

    def _use_compile_cache(self) -> bool:
        if self.compile_cache is None:
            return False
        from analytics_zoo_tpu.compile_cache import HAVE_AOT
        return HAVE_AOT

    def _warmup_replicas(self, sample, buckets, tag,
                         use_cache: bool = False) -> "InferenceModel":
        """Fan warmup out across the pool: every replica's worker thread
        compiles its own (replica, bucket) executables concurrently —
        N chips warm in roughly the time one takes. Jobs bypass the
        router (no in-flight accounting: nothing else runs at load) and
        carry no timer, so percentiles stay unpolluted.

        With a compile cache, each bucket is ONE cache entry: a hit
        deserializes N times (re-pinned per replica device); a miss
        compiles per replica in parallel as before, then persists a
        single entry — "persist once, load N times"."""
        if use_cache:
            for b in buckets:
                batch = jax.tree_util.tree_map(
                    lambda a, _b=b: np.ascontiguousarray(
                        np.broadcast_to(a[None], (_b,) + a.shape)), sample)
                sig = self._exec_sig(batch)
                # replica 0 probes the cache; on a miss it compiles and
                # persists the bucket's ONE entry — which every later
                # replica then LOADS (retargeted onto its own device,
                # ~ms each) instead of re-compiling. Cold wall time ≈
                # one compile + (N-1) deserializes; warm ≈ N
                # deserializes. warmup_source shows exactly what this
                # restart paid per replica.
                for rep in self._replicas:
                    t0 = time.perf_counter()
                    src = self._warm_executable(
                        rep.index, rep.params, batch,
                        target_device_id=rep.device.id)
                    jax.block_until_ready(
                        self._aot[(rep.index, sig)](rep.params, batch))
                    rkey = f"r{rep.index}:{tag}:b{b}"
                    self.warmup_report[rkey] = round(
                        time.perf_counter() - t0, 4)
                    self.warmup_source[rkey] = src
                self.warmed_buckets.add(b)
            return self
        jobs = []
        for b in buckets:
            batch = jax.tree_util.tree_map(
                lambda a, _b=b: np.ascontiguousarray(
                    np.broadcast_to(a[None], (_b,) + a.shape)), sample)
            # one harvest per bucket (every replica runs the same
            # program; replica 0's params stand in for all)
            self._harvest_jit_cost(self._replicas[0].params, batch)
            for rep in self._replicas:
                pending = _RoutedPending(b, timer=None, replica=rep.index)
                # t0=None: the worker stamps its own start, so the report
                # is per-(replica, bucket) compile+run, not queue wait
                rep.work_q.put_nowait((batch, pending, None))
                jobs.append((rep.index, b, pending))
        for idx, b, pending in jobs:
            pending.result()
            rkey = f"r{idx}:{tag}:b{b}"
            self.warmup_report[rkey] = round(pending._dispatch_s, 4)
            self.warmup_source[rkey] = "jit"
            self.warmed_buckets.add(b)
        return self

    # -- generative decode mode (ISSUE 18) ---------------------------------
    #
    # Autoregressive serving replaces the single forward program with
    # TWO program families: a PREFILL per prompt bucket (run the padded
    # prompt, park its KV into one pool slot, emit the first token's
    # logits) and a DECODE STEP per kv bucket (one token for every slot
    # at once, windowed to the step's serving bucket). Both families go
    # through the same persistent compile cache as the forward path —
    # same `make_key` discipline (placement/sharding/dtype), with an
    # `extra=("decode", kind, bucket)` discriminator because a step's
    # INPUT signature is identical across kv buckets (the bucket is a
    # static argument baked per executable, not a shape). Warmup
    # pre-compiles every (prompt bucket × kv bucket) so the decode
    # request path performs 0 XLA compiles — the same contract the
    # compile-cache spy asserts for the forward path.

    def load_generative(self, prefill_fn: Callable, step_fn: Callable,
                        params, paged_prefill_fn: Optional[Callable] = None,
                        paged_step_fn: Optional[Callable] = None,
                        ) -> "InferenceModel":
        """Load the decode-mode program pair (see models/generative.py
        for the exact calling contract). Single-device placement only:
        the KV pool is one device buffer threaded functionally through
        every call — replicating or sharding it is a later PR's
        problem, and silently ignoring the setting would serve from one
        chip while claiming many."""
        if self.placement != "replicated" or self.num_replicas != 1:
            raise ValueError(
                "load_generative supports single-device replicated "
                f"placement only (got placement={self.placement!r}, "
                f"num_replicas={self.num_replicas})")
        self.close()
        self._fn = None
        self._jit = None
        self._aot = {}
        self.serving_dtype = self._infer_serving_dtype(params)
        self._gen_prefill_fn = prefill_fn
        self._gen_step_fn = step_fn
        self._gen_paged_prefill_fn = paged_prefill_fn
        self._gen_paged_step_fn = paged_step_fn
        # one jit wrapper per program family; "step" wrappers are built
        # per kv bucket (the bucket is static — each is its own program)
        self._gen_jit = {"prefill": jax.jit(prefill_fn)}
        self._gen_aot = {}
        self._gen_cost = {}
        self._gen_fp = None
        if self.compile_cache is not None:
            from analytics_zoo_tpu.compile_cache import model_fingerprint
            # fingerprint BEFORE device placement, like load_fn; the
            # paged fns join the fingerprint only when supplied so a
            # non-paged deployment keeps its existing cache keys
            fns = (prefill_fn, step_fn)
            if paged_prefill_fn is not None or paged_step_fn is not None:
                fns = fns + (paged_prefill_fn, paged_step_fn)
            self._gen_fp = model_fingerprint(fns, params)
        if self._pin_single:
            self._params = jax.device_put(params, self.devices[0])
        else:
            self._params = jax.device_put(params)
        self.warmup_report = {}
        self.warmup_source = {}
        self.warmed_buckets = set()
        try:
            from analytics_zoo_tpu.observability.roofline import \
                get_accountant
            self._roofline = get_accountant()
            self._roofline.reset("serving")
        except Exception:  # noqa: BLE001 — telemetry only
            self._roofline = None
        return self

    def _gen_step_jit(self, kv_bucket: int):
        key = ("step", int(kv_bucket))
        jitted = self._gen_jit.get(key)
        if jitted is None:
            jitted = jax.jit(functools.partial(self._gen_step_fn,
                                               kv_bucket=int(kv_bucket)))
            self._gen_jit[key] = jitted
        return jitted

    def _gen_paged_step_jit(self, kv_bucket: int):
        key = ("paged_step", int(kv_bucket))
        jitted = self._gen_jit.get(key)
        if jitted is None:
            jitted = jax.jit(functools.partial(
                self._gen_paged_step_fn, kv_bucket=int(kv_bucket)))
            self._gen_jit[key] = jitted
        return jitted

    def _gen_paged_prefill_jit(self, kv_bucket: int):
        key = ("paged_prefill", int(kv_bucket))
        jitted = self._gen_jit.get(key)
        if jitted is None:
            jitted = jax.jit(functools.partial(
                self._gen_paged_prefill_fn, kv_bucket=int(kv_bucket)))
            self._gen_jit[key] = jitted
        return jitted

    @staticmethod
    def _gen_bucket_key(bucket):
        """Normalize a bucket discriminator: plain int for the PR 18
        families, (chunk_bucket, kv_bucket) tuple for paged prefill."""
        if isinstance(bucket, (tuple, list)):
            return tuple(int(b) for b in bucket)
        return int(bucket)

    def _warm_gen(self, kind: str, bucket, jitted, args) -> str:
        """Cache-backed warmup for one generative program — the decode
        analogue of `_warm_executable` (same funnel: every fresh
        compile goes through `serialization.compile_lowered`)."""
        from analytics_zoo_tpu.compile_cache import make_key, serialization
        bkey = self._gen_bucket_key(bucket)
        tkey = (kind, bkey)
        if tkey in self._gen_aot:
            return "warm"
        if not self._use_compile_cache():
            # plain-jit fallback: run once so jax's own cache holds the
            # executable; dispatch stays on the jit wrapper
            jax.block_until_ready(jitted(*args))
            try:
                from analytics_zoo_tpu.observability.roofline import cost_of
                c = cost_of(jitted.lower(*args))
                if c is not None:
                    self._gen_cost[tkey] = c
            except Exception:  # noqa: BLE001 — telemetry only
                pass
            return "jit"
        sig = self._exec_sig(args)
        key = make_key("serving", self._gen_fp or "", sig,
                       placement=self.placement,
                       dtype=self.serving_dtype
                       if self.serving_dtype != "float32" else "",
                       extra=("decode", kind) + (bkey if isinstance(
                           bkey, tuple) else (bkey,)))
        ex = self.compile_cache.load(key,
                                     target_device_id=self.devices[0].id)
        src = "cached"
        if ex is not None:
            stored = serialization.args_treedef(ex)
            if stored != serialization.live_treedef(args):
                ex = serialization.retree_call(ex, stored)
        else:
            t0 = time.perf_counter()
            # module-attribute call: serialization.compile_lowered is
            # THE fresh-compile funnel the 0-compile tests monkeypatch
            ex = serialization.compile_lowered(jitted.lower(*args))
            self.compile_cache.put(  # blocking-ok: disk cache write
                key, ex, compile_ms=(time.perf_counter() - t0) * 1e3)
            src = "compiled"
        self._gen_aot[tkey] = ex
        try:
            from analytics_zoo_tpu.observability.roofline import cost_of
            c = cost_of(ex)
            if c is not None:
                self._gen_cost[tkey] = c
        except Exception:  # noqa: BLE001 — telemetry only
            pass
        return src

    def warmup_generative(self, init_kv: Callable, slots: int,
                          max_kv_len: int, prompt_buckets: List[int],
                          kv_buckets: List[int]) -> "InferenceModel":
        """Pre-compile the whole decode program ladder: one prefill
        executable per prompt bucket, one step executable per kv
        bucket, each keyed (slots, bucket) through the persistent
        cache. The scratch KV pool built here is warmup-only — the
        engine allocates its own with identical shapes, so every
        request-path call lands on a warmed executable."""
        if getattr(self, "_gen_jit", None) is None:
            raise RuntimeError("load_generative() first")
        params = self._params
        kv = init_kv(int(slots), int(max_kv_len))
        for P in sorted({int(p) for p in prompt_buckets}):
            args = (params, kv, np.zeros(P, np.int32),
                    np.int32(1), np.int32(0))
            t0 = time.perf_counter()
            src = self._warm_gen("prefill", P, self._gen_jit["prefill"],
                                 args)
            ex = self._gen_aot.get(("prefill", P))
            if ex is not None:
                jax.block_until_ready(ex(*args))
            rkey = f"gen-prefill:p{P}"
            self.warmup_report[rkey] = round(time.perf_counter() - t0, 4)
            self.warmup_source[rkey] = src
        for b in sorted({int(b) for b in kv_buckets}):
            if b > max_kv_len:
                raise ValueError(f"kv bucket {b} exceeds max_kv_len "
                                 f"{max_kv_len}")
            args = (params, kv, np.zeros(slots, np.int32),
                    np.zeros(slots, np.int32))
            t0 = time.perf_counter()
            src = self._warm_gen("step", b, self._gen_step_jit(b), args)
            ex = self._gen_aot.get(("step", b))
            if ex is not None:
                jax.block_until_ready(ex(*args))
            rkey = f"gen-step:kv{b}"
            self.warmup_report[rkey] = round(time.perf_counter() - t0, 4)
            self.warmup_source[rkey] = src
        return self

    def warmup_generative_paged(self, init_kv_blocks: Callable,
                                num_blocks: int, block_len: int,
                                lanes: int, table_len: int,
                                chunk_buckets: List[int],
                                kv_buckets: List[int]) -> "InferenceModel":
        """Pre-compile the PAGED decode ladder: one chunked-prefill
        executable per (chunk bucket × context kv bucket) — the context
        window is 0 on a fresh first chunk and a kv bucket covering the
        adopted prefix plus earlier chunks otherwise — and one paged
        step executable per kv bucket, block tables in the signature.
        Same persistent-cache funnel as `warmup_generative`; the engine
        then performs 0 request-path compiles with the table in the
        loop."""
        if getattr(self, "_gen_paged_prefill_fn", None) is None:
            raise RuntimeError(
                "load_generative(..., paged_prefill_fn=, paged_step_fn=) "
                "first")
        params = self._params
        kv = init_kv_blocks(int(num_blocks), int(block_len))
        ctx_buckets = [0] + sorted({int(b) for b in kv_buckets})
        for Cb in sorted({int(c) for c in chunk_buckets}):
            for kvb in ctx_buckets:
                args = (params, kv, np.zeros(Cb, np.int32),
                        np.zeros(table_len, np.int32),
                        np.int32(0), np.int32(1))
                t0 = time.perf_counter()
                src = self._warm_gen("paged_prefill", (Cb, kvb),
                                     self._gen_paged_prefill_jit(kvb),
                                     args)
                ex = self._gen_aot.get(("paged_prefill", (Cb, kvb)))
                if ex is not None:
                    jax.block_until_ready(ex(*args))
                rkey = f"gen-paged-prefill:c{Cb}:kv{kvb}"
                self.warmup_report[rkey] = round(
                    time.perf_counter() - t0, 4)
                self.warmup_source[rkey] = src
        for b in sorted({int(b) for b in kv_buckets}):
            if b % int(block_len):
                raise ValueError(f"kv bucket {b} not a multiple of "
                                 f"block_len {block_len}")
            args = (params, kv, np.zeros(lanes, np.int32),
                    np.zeros(lanes, np.int32),
                    np.zeros((lanes, table_len), np.int32))
            t0 = time.perf_counter()
            src = self._warm_gen("paged_step", b,
                                 self._gen_paged_step_jit(b), args)
            ex = self._gen_aot.get(("paged_step", b))
            if ex is not None:
                jax.block_until_ready(ex(*args))
            rkey = f"gen-paged-step:kv{b}"
            self.warmup_report[rkey] = round(time.perf_counter() - t0, 4)
            self.warmup_source[rkey] = src
        return self

    def generative_prefill_paged(self, kv, tokens, table, pre_len,
                                 chunk_len, kv_bucket: int):
        """One prompt CHUNK through the warmed paged-prefill executable
        for its (chunk bucket, context bucket). Returns (kv, logits)."""
        tokens = np.ascontiguousarray(tokens, np.int32)
        args = (self._params, kv, tokens,
                np.ascontiguousarray(table, np.int32),
                np.int32(pre_len), np.int32(chunk_len))
        ex = self._gen_aot.get(
            ("paged_prefill", (int(tokens.shape[-1]), int(kv_bucket))))
        if ex is not None:
            return ex(*args)
        return self._gen_paged_prefill_jit(int(kv_bucket))(*args)

    def generative_step_paged(self, kv, tokens, positions, tables,
                              kv_bucket: int):
        """One decode step for every lane through the block tables.
        Returns (kv, logits[lanes, vocab])."""
        args = (self._params, kv,
                np.ascontiguousarray(tokens, np.int32),
                np.ascontiguousarray(positions, np.int32),
                np.ascontiguousarray(tables, np.int32))
        ex = self._gen_aot.get(("paged_step", int(kv_bucket)))
        if ex is not None:
            return ex(*args)
        return self._gen_paged_step_jit(int(kv_bucket))(*args)

    def generative_prefill(self, kv, tokens, length, slot):
        """One prompt through the warmed prefill executable for its
        bucket (tokens MUST already be padded to a warmed bucket).
        Returns (kv, logits)."""
        tokens = np.ascontiguousarray(tokens, np.int32)
        args = (self._params, kv, tokens, np.int32(length), np.int32(slot))
        ex = self._gen_aot.get(("prefill", int(tokens.shape[-1])))
        if ex is not None:
            return ex(*args)
        return self._gen_jit["prefill"](*args)

    def generative_step(self, kv, tokens, positions, kv_bucket: int):
        """One decode step for every slot under the static serving
        bucket. Returns (kv, logits[slots, vocab])."""
        args = (self._params, kv,
                np.ascontiguousarray(tokens, np.int32),
                np.ascontiguousarray(positions, np.int32))
        ex = self._gen_aot.get(("step", int(kv_bucket)))
        if ex is not None:
            return ex(*args)
        return self._gen_step_jit(int(kv_bucket))(*args)

    def account_generative(self, kind: str, bucket, secs: float):
        """Charge one generative call against the serving roofline with
        the cost harvested at warmup — decode is memory-bound and the
        Pallas kernel's analytic estimate is what makes the accountant
        see that (HLO cost analysis is blind inside a Mosaic call)."""
        if self._roofline is None:
            return
        cost = getattr(self, "_gen_cost", {}).get(
            (kind, self._gen_bucket_key(bucket)))
        if cost is None:
            return
        try:
            self._roofline.account("serving", cost.flops, cost.bytes,
                                   secs, n_devices=1)
        except Exception:  # noqa: BLE001 — telemetry only
            pass

    def compile_cache_size(self) -> int:
        """Number of in-process executables this model holds: AOT
        executables installed by cache-backed warmup PLUS the jit
        wrapper's own cache — which keys per (shape, committed device),
        so replicated placement counts its per-(replica, bucket)
        executables rather than reporting -1. -1 only when no counter
        is available at all (no model loaded on an old jax)."""
        n_aot = len(self._aot)
        try:
            n_jit = int(self._jit._cache_size())
        except Exception:  # noqa: BLE001 — diagnostics only
            return n_aot if n_aot else -1
        return n_aot + n_jit
