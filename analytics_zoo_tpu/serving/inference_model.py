"""InferenceModel — the multi-backend concurrent inference façade.

Reference: `pipeline/inference/InferenceModel.scala:28`: a queue of
`concurrentNum` model copies (`:62,520-624`), loaders for every engine, and
thread-safe `doPredict`. TPU-native redesign:

- No model copies: a jit-compiled function is immutable and thread-safe;
  "concurrency" is a semaphore bounding in-flight predict calls (XLA
  serializes device work; the bound keeps host-side queuing sane) — with
  `auto_scaling` the permit count grows on contention like the reference's
  queue-cloning (`:587`).
- Dynamic shapes are the TPU hazard (recompiles), so predict pads the batch
  to a power-of-two bucket and caches one executable per bucket — the
  serving analogue of `hard_code_batch_size`.
- Loaders: native Keras-style models / ZooModel zoo dirs / pure fn+params /
  torch modules (via the torch bridge). The reference's TF/OpenVINO/Caffe
  loaders map onto the native-model path (their runtimes don't exist on TPU;
  weights must be converted, cf. `learn/torch_bridge.py`).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.serving.timer import Timer


def _next_bucket(n: int, buckets) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


class InferenceModel:
    def __init__(self, concurrent_num: int = 1, auto_scaling: bool = False,
                 max_batch: int = 512):
        self.concurrent_num = concurrent_num
        self.auto_scaling = auto_scaling
        self._sema = threading.BoundedSemaphore(concurrent_num) \
            if not auto_scaling else threading.Semaphore(concurrent_num)
        self._fn: Optional[Callable] = None
        self._params = None
        self.max_batch = max_batch
        self.buckets = [b for b in (1, 2, 4, 8, 16, 32, 64, 128, 256, 512)
                        if b <= max_batch] or [max_batch]
        self._jit: Optional[Callable] = None
        self.timer = Timer("predict")

    # -- loaders (`doLoad*`, InferenceModel.scala:76-318) ------------------
    def load_keras(self, model, params=None,
                   quantize: Optional[str] = None) -> "InferenceModel":
        """A native Keras-style model (Sequential/Model/ZooModel).

        `quantize="int8"` rewrites every Dense/conv/Embedding weight to
        symmetric per-channel int8 and serves through the layers' int8
        MXU path (`serving/quantization.py`) — the TPU counterpart of the
        reference's OpenVINO int8 engine
        (`OpenVinoInferenceSupportive.scala:34-57`)."""
        from analytics_zoo_tpu.models.common import ZooModel
        if isinstance(model, ZooModel):
            model = model.model
        if params is not None:
            model.params = params
        if model.params is None:
            raise ValueError("Model has no parameters; fit or load first")
        params = model.params
        if quantize is not None:
            if quantize != "int8":
                raise ValueError(
                    f"Unsupported quantize={quantize!r}; only 'int8'")
            from analytics_zoo_tpu.serving.quantization import \
                quantize_model_params
            params = quantize_model_params(model, jax.device_get(params))
        return self.load_fn(lambda p, x: model.apply(p, x, training=False),
                            params)

    def load_zoo_model(self, cls, path: str,
                       quantize: Optional[str] = None) -> "InferenceModel":
        """`doLoadBigDL` analogue: a saved ZooModel directory."""
        return self.load_keras(cls.load_model(path), quantize=quantize)

    def load_quantized(self, model, path: str) -> "InferenceModel":
        """A pre-quantized int8 artifact (written by
        `serving.quantization.save_quantized`) onto `model`'s
        architecture — the `loadOpenVinoIRInt8` shape: ship the small
        int8 file, no f32 weights needed at serve time."""
        from analytics_zoo_tpu.models.common import ZooModel
        from analytics_zoo_tpu.serving.quantization import load_quantized
        net = model.model if isinstance(model, ZooModel) else model
        return self.load_fn(
            lambda p, x: net.apply(p, x, training=False),
            load_quantized(net, path))

    def load_fn(self, fn: Callable, params) -> "InferenceModel":
        """Pure `fn(params, x)` forward."""
        self._fn = fn
        # weights transfer ONCE at load: a host pytree here would be
        # re-uploaded on every predict (jit does not cache arg transfers)
        self._params = jax.device_put(params)
        # one jit wrapper; jax caches an executable per input shape (= per
        # bucket), so no per-bucket bookkeeping is needed
        self._jit = jax.jit(fn)
        return self

    def load_keras_encrypted(self, model, path: str, secret: str,
                             salt: str = "analytics-zoo"
                             ) -> "InferenceModel":
        """Encrypted-model analogue of `doLoadBigDL(path, secret)`
        (InferenceModel.scala:121-226): decrypt an AES-GCM-sealed param
        tree and attach it to the given architecture."""
        from analytics_zoo_tpu.learn.encrypted import load_encrypted_pytree
        from analytics_zoo_tpu.models.common import ZooModel
        params = load_encrypted_pytree(path, secret, salt)
        net = model.model if isinstance(model, ZooModel) else model
        params = net._remap_loaded(params)
        return self.load_keras(model, params=params)

    def load_torch(self, torch_module) -> "InferenceModel":
        """`doLoadPyTorch` analogue: convert the module natively (the
        reference embeds CPython via JEP; on TPU the model becomes XLA)."""
        from analytics_zoo_tpu.learn.torch_bridge import convert_torch_module
        native = convert_torch_module(torch_module)
        sample_shape = getattr(native, "input_shape", None)
        if native.params is None and sample_shape is not None:
            native.ensure_built(np.zeros((1,) + tuple(sample_shape[1:]),
                                         np.float32))
        return self.load_keras(native)

    # -- predict (`doPredict`, InferenceModel.scala:520-624) ---------------
    def predict(self, x) -> np.ndarray:
        if self._fn is None:
            raise RuntimeError("No model loaded")
        x = jax.tree_util.tree_map(np.asarray, x)
        leaves = jax.tree_util.tree_leaves(x)
        n = leaves[0].shape[0] if leaves[0].ndim > 0 else 1

        if n > self.max_batch:
            # split oversize inputs into max_batch chunks
            chunks = []
            for s in range(0, n, self.max_batch):
                part = jax.tree_util.tree_map(
                    lambda a: a[s:s + self.max_batch], x)
                chunks.append(self.predict(part))
            return jax.tree_util.tree_map(
                lambda *cs: np.concatenate(cs), *chunks)

        acquired = self._sema.acquire(timeout=60)
        if not acquired:
            if not self.auto_scaling:
                raise TimeoutError("predict queue exhausted "
                                   "(concurrent_num permits busy)")
            self._sema.release()  # grow like the reference's auto-scaling
        try:
            with self.timer.timing():
                bucket = _next_bucket(n, self.buckets)
                if n != bucket:
                    pad = bucket - n
                    x = jax.tree_util.tree_map(
                        lambda a: np.concatenate(
                            [a, np.repeat(a[-1:], pad, axis=0)]), x)
                out = self._jit(self._params, x)
                out = jax.tree_util.tree_map(
                    lambda a: np.asarray(a)[:n], out)
                return out
        finally:
            if acquired:
                self._sema.release()

    def predict_batches(self, xs: List) -> List:
        return [self.predict(x) for x in xs]
