"""Versioned zero-downtime model rollout (ISSUE 14 tentpole).

The reference platform's whole point is models that keep serving while
the system around them changes (ClusterServing decouples model
artifacts from the always-on Flink/Redis data plane); until now this
fleet loaded params once at startup, so a new checkpoint meant
restarting engines and eating a serving gap. This module closes the
loop PR 7 opened — sharded auto-resume training continuously publishes
CRC-disciplined versioned checkpoints; the fleet now picks them up
live, one engine at a time, with the traffic never stopping.

Two halves over the broker that already carries the data plane:

- **`RolloutController`** (the gateway): watches a checkpoint dir
  through the PUBLISH-marker gate (`learn/checkpoint.py`: a version is
  visible only once params, opt_state and the int8 sidecar are ALL
  durable — a torn or mid-write version cannot be observed), and
  converges the fleet onto the newest published, non-quarantined
  version by directing ONE engine at a time through the
  `rollout:<stream>` control hash. Convergence is judged on the
  heartbeat rows: an engine reports `model_version` only after its
  swap's canary passed, so the beat is the commit. The controller's
  whole goal state is derivable from (published versions, quarantine
  set, heartbeat versions) — a controller killed mid-rollout and
  restarted simply re-observes a mixed fleet and resumes converging
  it, which is exactly the `--chaos-rollout` contract.

- **`EngineRolloutAgent`** (each engine): polls the control hash; when
  a directive targets this engine it drains dispatch
  (`pause_intake()` + `quiesce()` — no mixed-version batches), calls
  `InferenceModel.swap_params` (same tree structure ⇒ the AOT/jit
  caches key on params *structure*, never values — **zero XLA
  compiles**; changed structure ⇒ honest re-warmup through the
  existing bucket path), canaries the new version with the
  supervisor's existing `probe_replica` machinery plus a
  golden-output delta gate, and only then reports the new version in
  its heartbeat. A failed canary swaps the old params back and VETOES
  the version — the controller quarantines it fleet-wide and walks
  every already-converted engine back.

Failure semantics ride the PR 10 rails: an engine SIGKILLed mid-swap
never beats the new version, so the controller skips it and its unacked
backlog claim-sweeps to peers (zero accepted-record loss); a dead
gateway leaves the fleet serving whatever it serves until a new
controller converges it.

Control hash (`rollout:<stream>`):

    directive      {"version", "run_dir", "target"}
    quarantine     {"<version>": "<reason>", ...}
    veto:<engine>  {"version", "reason", "scope", "engine_id"}

Registry families: `serving_rollout_state` (0 idle / 1 rolling /
2 rolled_back), `serving_rollout_transitions_total{state,version}`,
`serving_rollout_rollbacks_total{version}`, and the engine-side
`serving_model_version` (server.py).
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

log = logging.getLogger("analytics_zoo_tpu.serving.rollout")

ROLLOUT_KEY_PREFIX = "rollout:"
STATE_VALUES = {"idle": 0, "rolling": 1, "rolled_back": 2}


def rollout_key(stream: str) -> str:
    """The broker hash carrying the rollout control plane."""
    return ROLLOUT_KEY_PREFIX + stream


def default_params_loader(run_dir: str, version: int):
    """Load the param tree of one published checkpoint version — the
    engine agent's default way from a directive to weights."""
    from analytics_zoo_tpu.learn.checkpoint import load_checkpoint
    params, _, _ = load_checkpoint(run_dir, version)
    return params


class EngineRolloutAgent:
    """One engine's side of a rollout: watch the control hash, hot-swap
    on directive, canary, report (heartbeat) or veto (control hash).

    `params_loader(run_dir, version) -> params` maps a directive to a
    weight tree (default: `learn.checkpoint.load_checkpoint`; pass a
    wrapper applying `net._remap_loaded` for architectures that rename
    layers). `golden_tolerance` bounds how far the new version's output
    on the golden input may move from the old version's (relative
    max-abs delta; None = finiteness-only gate — versions legitimately
    change outputs, the gate exists to catch garbage)."""

    def __init__(self, serving, broker, stream: Optional[str] = None,
                 params_loader: Optional[Callable[[str, int], Any]] = None,
                 poll_interval_s: float = 0.5,
                 drain_timeout_s: float = 10.0,
                 canary_timeout_s: float = 10.0,
                 golden_tolerance: Optional[float] = None,
                 registry=None):
        if serving.engine_id is None:
            raise ValueError(
                "rollout needs a fleet identity: start the engine with "
                "engine_id set — the directive targeting and the "
                "heartbeat version report both key on it")
        self.serving = serving
        self.broker = broker
        self.stream = stream or serving.stream
        self.key = rollout_key(self.stream)
        self.engine_id = serving.engine_id
        self.params_loader = params_loader or default_params_loader
        self.poll_interval_s = max(0.05, float(poll_interval_s))
        self.drain_timeout_s = float(drain_timeout_s)
        self.canary_timeout_s = float(canary_timeout_s)
        self.golden_tolerance = golden_tolerance
        self._vetoed: set = set()
        # engine-scope refusals (load failures) retry after a backoff
        # instead of joining the permanent veto set: the failure was a
        # fact about THIS HOST at that moment (mount down, replication
        # lag) — once the controller's straggler entry expires and the
        # directive returns, the repaired engine must be able to apply
        self._refused_until: Dict[int, float] = {}
        self.last_swap: Optional[Dict[str, Any]] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if registry is None:
            from analytics_zoo_tpu.observability.registry import get_registry
            registry = get_registry()
        self._transitions = registry.counter(
            "serving_rollout_transitions_total",
            "rollout state transitions, by state and model version")
        self._rollbacks = registry.counter(
            "serving_rollout_rollbacks_total",
            "rollouts rolled back after a failed canary or a "
            "fleet-wide version quarantine, by model version")

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "EngineRolloutAgent":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop,
                name=f"serving-rollout-{self.engine_id}", daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self):
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 — the loop must live
                log.warning("rollout agent poll failed (%s: %s); "
                            "retrying next interval",
                            type(e).__name__, e)

    # -- control-plane poll ------------------------------------------------
    def poll_once(self) -> Optional[str]:
        """One control-hash check; applies at most one directive.
        Returns the outcome ("swapped"/"vetoed") when a swap ran."""
        raw = self.broker.hget(self.key, "directive")
        if not raw:
            return None
        try:
            d = json.loads(raw)
        except (TypeError, ValueError):
            return None
        if d.get("target") != self.engine_id:
            return None
        version = int(d["version"])
        if version == self.serving.model_version \
                or version in self._vetoed \
                or str(version) in self._quarantined():
            return None
        if time.monotonic() < self._refused_until.get(version, 0.0):
            return None          # load-failure backoff; retried after
        return self.apply(version, d.get("run_dir"))

    def _quarantined(self) -> Dict[str, str]:
        try:
            raw = self.broker.hget(self.key, "quarantine")
            return json.loads(raw) if raw else {}
        except Exception:  # noqa: BLE001 — treat unknown as empty
            return {}

    def _veto(self, version: int, reason: str,
              scope: str = "version"):
        """Publish a refusal. `scope="version"` is evidence AGAINST THE
        VERSION (a canary failed on healthy hardware) — the controller
        quarantines it fleet-wide. `scope="engine"` is evidence about
        THIS ENGINE only (its checkpoint mount is broken, the artifact
        hasn't replicated here yet) — the controller skips the engine
        as a straggler; one sick mount must never poison a version
        every other engine would serve happily."""
        if scope == "version":
            self._vetoed.add(version)
            # the engine really did roll its own swap back — counted
            # HERE, once; the controller's campaign abandonment shows
            # in serving_rollout_transitions_total{state="rolled_back"}
            self._rollbacks.inc(version=str(version))
        else:
            self._refused_until[version] = time.monotonic() \
                + max(5.0, 10 * self.poll_interval_s)
        try:
            self.broker.hset(self.key, f"veto:{self.engine_id}",
                             json.dumps({"version": version,
                                         "reason": reason,
                                         "scope": scope,
                                         "engine_id": self.engine_id}))
        except Exception as e:  # noqa: BLE001 — the rollback already
            # happened locally; the controller's engine-timeout is the
            # backstop for a veto that never lands
            log.warning("veto publish failed (%s: %s)",
                        type(e).__name__, e)
        log.warning("engine %s refused model version %d (%s scope): %s",
                    self.engine_id, version, scope, reason)

    # -- the swap ----------------------------------------------------------
    def _golden_input(self, model):
        """The canary batch: the most recent input any replica handled
        successfully (the supervisor's canary discipline), falling back
        to a batch built from the warmup sample when no traffic has
        flowed yet. None = nothing credible to probe with (the gate is
        then vacuous — there is also nothing the new version could
        corrupt an answer for)."""
        x = model._last_good_input
        if x is None:
            x = model._last_input
        if x is None and model._warmup_sample is not None:
            import jax
            x = jax.tree_util.tree_map(
                lambda a: np.ascontiguousarray(
                    np.broadcast_to(a[None], (1,) + a.shape)),
                model._warmup_sample)
        return x

    def _canary(self, model, x, old_out):
        """The admission gate for a just-swapped version: every HEALTHY
        replica must answer the golden batch through the supervisor's
        existing probe machinery, the pooled output must be finite, and
        (with a tolerance configured) it must sit within the golden
        delta of the OLD version's output on the same input. Replicas
        the supervisor had already quarantined BEFORE the swap are not
        probed — a pre-existing sick chip is a fact about the chip, and
        letting it veto would poison every future version fleet-wide."""
        if model._replicas is not None:
            sick = set(model.quarantined_replicas())
            for rep in range(len(model._replicas)):
                if rep in sick:
                    continue
                if not model.probe_replica(
                        rep, x, timeout_s=self.canary_timeout_s):
                    return False, f"replica {rep} failed the canary probe"
        try:
            new_out = self._out_leaves(model.predict(x))
        except Exception as e:  # noqa: BLE001 — a failing canary IS
            return False, f"canary forward raised {type(e).__name__}: {e}"
        for leaf in new_out:
            if leaf.dtype.kind in "fc" and not np.all(np.isfinite(leaf)):
                return False, "canary output is not finite"
        if self.golden_tolerance is not None and old_out is not None \
                and len(old_out) == len(new_out):
            # relative delta PER LEAF, worst ratio wins: a shared
            # denominator would let a large-magnitude logits head mask
            # total corruption of a small-magnitude probability head
            delta = 0.0
            for o, n in zip(old_out, new_out):
                if o.shape != n.shape or o.dtype.kind not in "fc":
                    continue
                denom = max(float(np.max(np.abs(o))), 1e-6)
                delta = max(delta, float(np.max(np.abs(
                    n.astype(np.float64) - o.astype(np.float64))))
                    / denom)
            if not delta <= self.golden_tolerance:
                return False, (f"golden-output delta {delta:.4g} exceeds "
                               f"tolerance {self.golden_tolerance:g}")
        return True, None

    @staticmethod
    def _out_leaves(out) -> List[np.ndarray]:
        """Model outputs as flat ndarray leaves — multi-output models
        (dict/tuple predictions) gate per leaf instead of tripping
        np.isfinite on an object array."""
        import jax
        return [np.asarray(leaf)
                for leaf in jax.tree_util.tree_leaves(out)]

    def apply(self, version: int, run_dir: str) -> str:
        """Drain → swap → canary → report-or-rollback, one version on
        this engine. Every exit path resumes intake and re-arms the
        supervisor; the heartbeat only ever carries a version whose
        canary passed."""
        from analytics_zoo_tpu.learn.checkpoint import \
            verify_publish_marker
        t0 = time.perf_counter()
        try:
            if not verify_publish_marker(run_dir, version):
                raise RuntimeError("version is not intact-published "
                                   "on this host")
            params = self.params_loader(run_dir, version)
        except Exception as e:  # noqa: BLE001 — a bad artifact must
            # refuse, not kill the agent. ENGINE scope: failing to
            # read the checkpoint here says nothing about the version
            # (broken mount, replication lag) — the fleet's other
            # engines must still get to serve it
            self._veto(version,
                       f"load failed: {type(e).__name__}: {e}",
                       scope="engine")
            self.last_swap = {"version": version, "outcome": "vetoed",
                              "reason": "load failed"}
            return "vetoed"
        serving, model = self.serving, self.serving.model
        sup = getattr(serving, "supervisor", None)
        serving.pause_intake()
        if sup is not None:
            # a restructured swap's first batches pay honest re-warmup
            # latency; judged against the old model's baseline they
            # would read as outliers and cascade a quarantine
            sup.suspend()
        try:
            drained = serving.quiesce(self.drain_timeout_s)
            if not drained:
                log.warning(
                    "pipeline did not fully drain within %.1fs before "
                    "swapping to version %d; the old version's tail "
                    "finishes on its own captured params",
                    self.drain_timeout_s, version)
            x = self._golden_input(model)
            old_out = None
            if x is not None:
                try:
                    old_out = self._out_leaves(model.predict(x))
                except Exception:  # noqa: BLE001 — no golden baseline
                    old_out = None
            old_params = model.current_params()
            # executable count across the swap+canary: the 0-compiles
            # contract is about THIS window (a same-structure swap
            # keeps every executable), not about whatever unrelated
            # bucket traffic compiles around it
            size_fn = getattr(model, "compile_cache_size", None)
            n_before = size_fn() if callable(size_fn) else None
            mode = None
            local_fault = False
            try:
                mode = model.swap_params(params)
                ok, reason = (True, None) if x is None \
                    else self._canary(model, x, old_out)
            except Exception as e:  # noqa: BLE001 — a raising swap
                # (device OOM mid-device_put, indivisible shard on a
                # restructure) must restore-and-veto like a failed
                # canary, never leave the engine model-less. A RAISE
                # is a fact about THIS HOST's resources, not about the
                # version's outputs — engine scope
                ok = False
                local_fault = True
                reason = f"swap raised {type(e).__name__}: {e}"
            if not ok and (self._stop.is_set()
                           or serving._stop.is_set()):
                # a dying engine's canary verdict is not evidence: its
                # replicas are being torn down under the probe — a
                # routine single-engine restart mid-rollout must not
                # quarantine the version and roll the whole fleet back
                local_fault = True
                reason = f"{reason} (engine stopping)"
            ms = round((time.perf_counter() - t0) * 1e3, 2)
            swap_compiles = None
            if n_before is not None and n_before >= 0:
                n_after = size_fn()
                if n_after >= 0:
                    swap_compiles = n_after - n_before
            if ok:
                serving.set_model_version(version)
                self._transitions.inc(state="swapped",
                                      version=str(version))
                self.last_swap = {"version": version, "mode": mode,
                                  "outcome": "swapped", "ms": ms,
                                  "swap_executables_delta":
                                      swap_compiles}
                if serving.tracer is not None:
                    serving.tracer.add_span(
                        "rollout_swap", t0, time.perf_counter(),
                        cat="serving.rollout",
                        args={"version": version, "mode": mode,
                              "engine": self.engine_id})
                log.info("engine %s now serves model version %d "
                         "(%s swap, %.1f ms, drained=%s)",
                         self.engine_id, version, mode, ms, drained)
                return "swapped"
            try:
                model.swap_params(old_params)
            except Exception as e:  # noqa: BLE001 — the engine is now
                # model-less; keep intake paused via the health story
                # (every dispatch fails → replicas quarantine → the
                # engine reads not-ready) and say so loudly
                log.error(
                    "restoring the previous params after a failed "
                    "swap to version %d ALSO failed (%s: %s); this "
                    "engine needs a model reload", version,
                    type(e).__name__, e)
            self._veto(version, reason,
                       scope="engine" if local_fault else "version")
            self.last_swap = {"version": version, "mode": mode,
                              "outcome": "vetoed", "reason": reason,
                              "ms": ms,
                              "swap_executables_delta": swap_compiles}
            return "vetoed"
        finally:
            if sup is not None:
                sup.resume()
            serving.resume_intake()

    def status(self) -> Dict[str, Any]:
        return {"engine_id": self.engine_id,
                "model_version": self.serving.model_version,
                "last_swap": self.last_swap,
                "vetoed_versions": sorted(self._vetoed)}


class RolloutController:
    """The gateway's rollout brain: one control loop converging the
    fleet onto the newest published, non-quarantined checkpoint
    version, one engine at a time.

    The decision core is `tick(now)` — a (locked) function of the
    observed state: published versions on disk, the quarantine set
    (mirrored into the broker control hash so it survives gateway
    restarts), and the heartbeat-reported per-engine versions. Tests
    drive it directly; `start()` runs it on a stop-event-paced daemon
    thread (no untimed waits — see scripts/check_blocking_calls.py).

    Because the goal state is fully derivable from those three inputs,
    a controller killed at ANY point and restarted resumes correctly:
    a half-converted fleet is just a fleet where some engines don't
    report the newest published version yet."""

    def __init__(self, broker, stream: str, model_dir: str,
                 tracker, poll_interval_s: float = 1.0,
                 engine_timeout_s: float = 60.0,
                 leader_fn: Optional[Callable[[], bool]] = None,
                 registry=None):
        if poll_interval_s <= 0 or engine_timeout_s <= 0:
            raise ValueError("poll_interval_s and engine_timeout_s "
                             "must be > 0")
        self.broker = broker
        # replicated-gateway gate (ISSUE 16): when set, only the
        # replica whose leader lease holds runs the convergence core —
        # followers' ticks are no-ops, but request()/status() stay
        # live everywhere because the pin and quarantine both persist
        # in the control hash and the goal state derives from it
        self.leader_fn = leader_fn
        self.stream = stream
        self.key = rollout_key(stream)
        self.model_dir = model_dir
        self.tracker = tracker
        self.poll_interval_s = float(poll_interval_s)
        self.engine_timeout_s = float(engine_timeout_s)
        self.state = "idle"
        self.active_version: Optional[int] = None
        self.target_version: Optional[int] = None
        self.target_run_dir: Optional[str] = None
        self.rolling_back = False
        self.pending_engine: Optional[str] = None
        self._directed_at: Optional[float] = None
        self.converted: List[str] = []
        self.quarantined: Dict[str, str] = {}
        # engine -> (version it failed to convert to, when): skipped
        # (NOT a version quarantine — an agent-less or wedged ENGINE
        # must not poison every future version for the healthy rest of
        # the fleet). Entries expire after 10x engine_timeout_s so an
        # engine fixed in place (agent enabled, mount repaired) gets
        # re-tried without waiting for a new publish; a different goal
        # version or a heartbeat gap (restart) re-tries immediately
        self.stragglers: Dict[str, tuple] = {}
        self.force_version: Optional[int] = None
        # memoized publish-verification verdicts (stat-keyed): idle
        # polls must not re-CRC a multi-GB artifact set every second
        self._verify_cache: Dict = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if registry is None:
            from analytics_zoo_tpu.observability.registry import get_registry
            registry = get_registry()
        self._state_gauge = registry.gauge(
            "serving_rollout_state",
            "rollout controller state (0 idle, 1 rolling, "
            "2 rolled_back)")
        self._state_fn = (lambda: float(STATE_VALUES.get(self.state, 0)))
        self._state_gauge.set_function(self._state_fn)
        self._transitions = registry.counter(
            "serving_rollout_transitions_total",
            "rollout state transitions, by state and model version")
        self._rollbacks = registry.counter(
            "serving_rollout_rollbacks_total",
            "rollouts rolled back after a failed canary or a "
            "fleet-wide version quarantine, by model version")
        self._load_quarantine()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "RolloutController":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="serving-rollout-controller",
                daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self._state_gauge.release_function(self._state_fn, freeze=True)

    def _loop(self):
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — the loop must live
                log.warning("rollout tick failed (%s: %s); retrying "
                            "next interval", type(e).__name__, e)

    # -- quarantine persistence -------------------------------------------
    def _load_quarantine(self):
        try:
            raw = self.broker.hget(self.key, "quarantine")
            if raw:
                self.quarantined.update(json.loads(raw))
        except Exception:  # noqa: BLE001 — broker blip: local set rules
            pass

    def _quarantine(self, version: int, reason: str):
        """Quarantine a version FLEET-WIDE: persisted in the control
        hash so agents refuse it and a restarted controller (or a
        peer gateway) never re-targets it."""
        self.quarantined[str(version)] = reason
        try:
            self.broker.hset(self.key, "quarantine",
                             json.dumps(self.quarantined))
        except Exception as e:  # noqa: BLE001 — retried next write
            log.warning("quarantine publish failed (%s: %s)",
                        type(e).__name__, e)
        log.warning("model version %d quarantined fleet-wide: %s",
                    version, reason)

    def _read_vetoes(self) -> List[Dict[str, Any]]:
        try:
            rows = self.broker.hgetall(self.key)
        except Exception:  # noqa: BLE001 — broker blip
            return []
        out = []
        for field, blob in rows.items():
            if not field.startswith("veto:"):
                continue
            try:
                out.append((field, json.loads(blob)))
            except (TypeError, ValueError):
                out.append((field, {}))
        for field, _ in out:
            try:
                self.broker.hdel(self.key, field)
            except Exception:  # noqa: BLE001 — re-read next tick
                pass
        return [v for _, v in out]

    # -- decision core -----------------------------------------------------
    def tick(self, now: Optional[float] = None) -> Optional[str]:
        """One control pass; returns "direct"/"advance"/"converged"/
        "rollback" when something happened, else None."""
        if self.leader_fn is not None and not self.leader_fn():
            return None          # follower: reads only, never directs
        with self._lock:
            return self._tick_locked(
                time.monotonic() if now is None else now)

    def _sync_pin_locked(self):
        """Adopt the broker-persisted operator pin. Any gateway replica
        accepts POST /rollout by writing the `pin` field; the leader's
        tick reads it here, so a kill-the-leader handover converges the
        in-flight request without the operator re-issuing it. A broker
        blip keeps the last-synced local value (never silently unpins)."""
        try:
            raw = self.broker.hget(self.key, "pin")
        except Exception:  # noqa: BLE001 — broker blip: local pin rules
            return
        if raw:
            try:
                self.force_version = int(json.loads(raw))
            except (TypeError, ValueError):
                pass
        else:
            self.force_version = None

    def _tick_locked(self, now: float) -> Optional[str]:
        self._sync_pin_locked()
        # vetoes first: a failed canary anywhere quarantines the
        # version before any further engine is directed at it; an
        # ENGINE-scope refusal (load failure — a fact about that
        # engine's disk, not the version) only stragglers the engine
        for veto in self._read_vetoes():
            v = veto.get("version")
            if v is None:
                continue
            if veto.get("scope") == "engine":
                eid = veto.get("engine_id")
                if eid:
                    log.warning(
                        "engine %s cannot load version %s (%s); "
                        "skipping it, the campaign continues", eid, v,
                        veto.get("reason", "load failed"))
                    self.stragglers[eid] = (int(v), now)
                    self._transitions.inc(state="engine_skipped",
                                          version=str(v))
                    if self.pending_engine == eid \
                            and self.target_version == int(v):
                        self.pending_engine = None
                continue
            if str(v) not in self.quarantined:
                self._quarantine(int(v), veto.get("reason", "vetoed"))
                if self.target_version == int(v):
                    # abandon the campaign; the idle branch below
                    # immediately re-targets the newest GOOD version,
                    # walking every converted engine back (the
                    # rollback itself is counted once, by the engine
                    # that restored its params)
                    self._transitions.inc(state="rolled_back",
                                          version=str(v))
                    self._reset_campaign(rolled_back=True)
        versions = self.tracker.versions()
        if versions is None:
            return None          # broker unreachable: no claim to make
        if self.state in ("rolling", "rolled_back"):
            return self._step_campaign(now, versions)
        return self._idle_step(now, versions)

    def _published_target(self):
        """(run_dir, version) the fleet SHOULD serve: the forced
        version when an operator pinned one, else the newest published
        version outside the quarantine set. A pin whose version gets
        quarantined (its canary failed somewhere) releases itself with
        a warning — quarantine evidence outranks the pin, and holding
        it would re-target the poisoned version forever."""
        from analytics_zoo_tpu.learn.checkpoint import (
            latest_published_checkpoint, published_intact,
            resolve_checkpoint)
        if self.force_version is not None \
                and str(self.force_version) in self.quarantined:
            log.warning(
                "pinned version %d was quarantined (%s); releasing "
                "the pin", self.force_version,
                self.quarantined[str(self.force_version)])
            self.force_version = None
            try:
                # clear the persisted pin too, or the next sync would
                # re-adopt the poisoned version forever
                self.broker.hdel(self.key, "pin")
            except Exception:  # noqa: BLE001 — quarantine outranks the
                pass           # pin on every future sync anyway
        if self.force_version is not None:
            run_dir, v = resolve_checkpoint(self.model_dir,
                                            self.force_version)
            # the SAME memoized verifier as the watcher path: a pin
            # is held indefinitely, and re-CRCing the pinned artifact
            # set every poll tick is exactly the cost the cache exists
            # to avoid
            if not published_intact(run_dir, v,
                                    verify_cache=self._verify_cache):
                raise FileNotFoundError(
                    f"version {v} under {self.model_dir} is not "
                    "published")
            return run_dir, v
        return latest_published_checkpoint(
            self.model_dir, skip_versions=self.quarantined,
            verify_cache=self._verify_cache)

    def _needers(self, versions: Dict[str, Any], target: int) -> List[str]:
        """Alive engines that should convert to `target` — excluding
        stragglers already skipped for exactly this version (an engine
        with no rollout agent, or one wedged mid-swap, must not hang
        the campaign or poison the VERSION for the healthy rest)."""
        return sorted(
            e for e, ev in versions.items()
            if ev != target
            and self.stragglers.get(e, (None,))[0] != target)

    def _idle_step(self, now: float, versions: Dict[str, Any]):
        try:
            pub = self._published_target()
        except (OSError, ValueError) as e:
            # transient (NFS blip, mid-GC listing): log and HOLD —
            # clearing the operator's pin here would let the next tick
            # re-roll the very version they backed out of
            log.warning("rollout target resolution failed: %s", e)
            return None
        if pub is None:
            return None
        run_dir, v = pub
        # an engine that vanished and returned (restart) gets a fresh
        # chance, and straggler entries expire on a 10x-timeout backoff
        # (an engine fixed IN PLACE — agent enabled, mount repaired —
        # must not stay skipped until the next publish); entries for
        # other versions are inert either way
        for eid in [e for e, (_, ts) in self.stragglers.items()
                    if e not in versions
                    or now - ts > 10 * self.engine_timeout_s]:
            self.stragglers.pop(eid, None)
        needers = self._needers(versions, v)
        if not needers:
            if versions and all(ev == v for ev in versions.values()):
                # every alive engine serves the goal version
                self.rolling_back = False
                if self.active_version != v:
                    self.active_version = v
            return None
        # begin (or resume, after a controller restart) a campaign
        self.state = "rolled_back" if self.rolling_back else "rolling"
        self.target_version = v
        self.target_run_dir = run_dir
        self.converted = sorted(e for e, ev in versions.items()
                                if ev == v)
        self._transitions.inc(state=self.state, version=str(v))
        log.info("rollout %s: fleet -> version %d (%d engine(s) to "
                 "convert: %s)", self.state, v, len(needers), needers)
        return self._direct(now, needers[0])

    def _direct(self, now: float, engine: str) -> str:
        self.pending_engine = engine
        self._directed_at = now
        self._publish_directive()
        return "direct"

    def _publish_directive(self):
        """Idempotent: re-published every tick while an engine is
        pending, so a broker blip (or an engine that restarted and
        lost the directive) cannot strand the campaign — the agent
        ignores directives for the version it already serves (and for
        versions it vetoed or sees quarantined), so no freshness token
        is needed."""
        try:
            self.broker.hset(self.key, "directive", json.dumps(
                {"version": self.target_version,
                 "run_dir": self.target_run_dir,
                 "target": self.pending_engine}))
        except Exception as e:  # noqa: BLE001 — re-issued next tick
            log.warning("directive publish failed (%s: %s)",
                        type(e).__name__, e)

    def _step_campaign(self, now: float, versions: Dict[str, Any]):
        target = self.target_version
        engine = self.pending_engine
        if engine is not None and engine not in versions:
            # engine died mid-swap (SIGKILL): it never beat the new
            # version, its unacked backlog claim-sweeps to peers, and
            # when it restarts the idle branch converges it. Skip.
            log.warning("engine %s vanished mid-rollout; skipping "
                        "(its backlog redelivers to peers)", engine)
            self.pending_engine = None
        elif engine is not None and versions.get(engine) == target:
            self.converted.append(engine)
            self.pending_engine = None
            self._transitions.inc(state="engine_converted",
                                  version=str(target))
            log.info("engine %s converted to version %s (%d/%d)",
                     engine, target, len(set(self.converted)),
                     len(versions))
        elif engine is not None and self._directed_at is not None \
                and now - self._directed_at > self.engine_timeout_s:
            # alive but never converted — and never VETOED, so this is
            # not evidence against the version (a canary failure vetoes
            # within canary_timeout_s): an engine with no rollout
            # agent, or one wedged mid-swap. Skip the ENGINE, not the
            # version — quarantining here would let one legacy engine
            # poison every future publish for the healthy fleet
            log.warning(
                "engine %s did not convert to version %s within %gs; "
                "skipping it (re-tried when a new version publishes "
                "or the engine restarts)", engine, target,
                self.engine_timeout_s)
            self.stragglers[engine] = (target, now)
            self._transitions.inc(state="engine_skipped",
                                  version=str(target))
            self.pending_engine = None
        if self.pending_engine is None:
            needers = self._needers(versions, target)
            if not needers:
                state = self.state
                stragglers = sorted(
                    e for e, (v, _) in self.stragglers.items()
                    if v == target and e in versions)
                if stragglers:
                    self._transitions.inc(state="partial",
                                          version=str(target))
                    log.warning(
                        "rollout to version %s is PARTIAL: %s never "
                        "converted (skipped); the rest of the fleet "
                        "serves it", target, stragglers)
                else:
                    self._transitions.inc(state="converged",
                                          version=str(target))
                    log.info("fleet converged on model version %s (%s)",
                             target, state)
                    self.active_version = target
                self._reset_campaign(rolled_back=False)
                try:
                    self.broker.hdel(self.key, "directive")
                except Exception:  # noqa: BLE001 — agents ignore a
                    pass           # stale directive for their version
                return "partial" if stragglers else "converged"
            return self._direct(now, needers[0])
        self._publish_directive()
        return None

    def _reset_campaign(self, rolled_back: bool):
        self.rolling_back = rolled_back
        self.state = "idle"
        self.pending_engine = None
        self._directed_at = None
        self.target_version = None
        self.target_run_dir = None
        self.converted = []

    # -- operator surface (POST /rollout, GET /rollout/status) -------------
    def request(self, version: Optional[int] = None,
                unpin: bool = False) -> Dict[str, Any]:
        """Operator ask: roll the fleet to `version` (must be published
        and not quarantined; also the manual-rollback path — an OLDER
        published version is a legal target), or just poke the watcher
        (version None). A pinned version is STICKY: the watcher holds
        the fleet there — newer publishes included — until another
        version is pinned or `unpin` releases it (an operator who
        rolled back does not want the next tick re-rolling the version
        they just backed out of; quarantine it, or stay pinned).
        Raises ValueError on a quarantined version, FileNotFoundError
        on an unpublished one."""
        if unpin:
            with self._lock:
                self.force_version = None
            self.broker.hdel(self.key, "pin")
        if version is not None:
            from analytics_zoo_tpu.learn.checkpoint import (
                published_intact, resolve_checkpoint)
            if str(int(version)) in self.quarantined:
                raise ValueError(
                    f"version {version} is quarantined: "
                    f"{self.quarantined[str(int(version))]}")
            run_dir, v = resolve_checkpoint(self.model_dir, int(version))
            # memoized like every other verification this controller
            # runs — the HTTP handler must not block on a full CRC
            # read of a multi-GB artifact set
            if not published_intact(run_dir, v,
                                    verify_cache=self._verify_cache):
                raise FileNotFoundError(
                    f"version {v} exists but is not published")
            with self._lock:
                self.force_version = v
            # the pin lives in the control hash, not in this replica:
            # ANY gateway accepts the request, and whichever replica
            # holds (or inherits) the leader lease converges it
            self.broker.hset(self.key, "pin", json.dumps(v))
        self.tick()
        return self.status()

    def status(self) -> Dict[str, Any]:
        with self._lock:
            # followers never tick, so surface the broker-persisted pin
            # here — GET /rollout/status answers the same on every
            # gateway replica
            self._sync_pin_locked()
            out = {
                "state": self.state,
                "active_version": self.active_version,
                "target_version": self.target_version,
                "pending_engine": self.pending_engine,
                "converted": sorted(set(self.converted)),
                "rolling_back": self.rolling_back,
                "pinned_version": self.force_version,
                "stragglers": {e: v for e, (v, _)
                               in self.stragglers.items()},
                "quarantined": dict(self.quarantined),
                "model_dir": self.model_dir,
            }
        versions = self.tracker.versions()
        out["fleet_versions"] = versions
        return out
