"""Fleet membership over the broker — heartbeats out, tracking in.

Horizontal scale-out (ISSUE 10) runs N `ClusterServing` engine processes
as co-consumers of one stream. The broker that already carries the data
plane carries the control plane too: each engine HSETs a heartbeat row
into `engines:<stream>` every `interval_s`, and the HTTP frontend — now
a fleet gateway — reads that hash to answer `/healthz` for the whole
fleet (200 while >= 1 engine is alive and ready, 503 + Retry-After when
none are) and to export `serving_engines_alive` / `serving_engines_total`.

No extra infrastructure: the reference platform leaned on Flink's
jobmanager for this; here the same Redis that queues records is the
membership registry, so a gateway and a fleet agree on liveness through
the one component they both already depend on.

Heartbeat row (JSON):

    {"engine_id": ..., "ts": <epoch seconds>, "ready": bool,
     "records_served": n, "records_read": n, "pid": n}

Liveness = the row's `ts` was observed to CHANGE within the last
`ttl_s` on the gateway's own monotonic clock — heartbeat PROGRESS, not
wall-clock arithmetic, so cross-host clock skew between engines and
the gateway can neither kill a healthy fleet nor keep a dead engine
alive. The cost of clock independence: right after a gateway (re)start
a crashed engine's leftover row reads as fresh for at most one TTL,
then ages out like any silent engine — self-correcting, and far
cheaper than 503ing a healthy skewed fleet. A cleanly stopping engine
deletes its row (HDEL) so the gateway notices immediately; a SIGKILLed
engine simply stops refreshing, ages out within the TTL — the same
window after which its unacked records become claimable by live peers
— and its dead row is purged from the hash once it sits 10x past the
TTL, so crash/restart churn under `engine_id: auto` cannot grow the
registry without bound.
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
import time
from typing import Callable, Dict, Optional

from analytics_zoo_tpu.serving.broker import Broker

log = logging.getLogger("analytics_zoo_tpu.serving.fleet")

ENGINES_KEY_PREFIX = "engines:"


def engines_key(stream: str) -> str:
    """The broker hash that holds one heartbeat row per engine."""
    return ENGINES_KEY_PREFIX + stream


class HeartbeatPublisher:
    """Periodic heartbeat HSET from one engine, on its own thread and
    its own broker connection (the reader blocks in XREADGROUP windows
    and the sink may be mid-writeback; a heartbeat must never queue
    behind either). Publish failures are survived and logged once per
    outage — a broker blip must not kill the engine's membership, the
    next beat re-registers it."""

    def __init__(self, broker: Broker, stream: str, engine_id: str,
                 payload_fn: Callable[[], Dict], interval_s: float = 2.0,
                 registry=None):
        self.broker = broker
        self.key = engines_key(stream)
        self.engine_id = engine_id
        self.payload_fn = payload_fn
        self.interval_s = max(0.05, float(interval_s))
        if registry is None:
            from analytics_zoo_tpu.observability.registry import get_registry
            registry = get_registry()
        self._beats = registry.counter(
            "serving_engine_heartbeats_total",
            "fleet heartbeats successfully published to the broker, "
            "by engine")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._down = False
        # last payload_fn fields that published cleanly: a transient
        # telemetry error must degrade to "not ready" WITHOUT dropping
        # slow-moving facts the gateway acts on (model_version,
        # slo_burn) — a beat that suddenly loses its model_version
        # would read at the rollout controller as a version regression
        self._last_good_fields: Dict = {}

    def _publish_once(self) -> bool:
        payload = {"engine_id": self.engine_id, "ts": time.time(),
                   "pid": os.getpid()}
        try:
            fields = self.payload_fn() or {}
            payload.update(fields)
            self._last_good_fields = dict(fields)
        except Exception as e:  # noqa: BLE001 — a beat must still go out
            payload.update(self._last_good_fields)
            payload["ready"] = False
            payload["error"] = f"{type(e).__name__}: {e}"
        try:
            self.broker.hset(self.key, self.engine_id,
                             json.dumps(payload))
        except Exception as e:  # noqa: BLE001 — outage: next beat retries
            if not self._down:
                log.warning("heartbeat publish failed for %s (%s: %s); "
                            "retrying each interval", self.engine_id,
                            type(e).__name__, e)
                self._down = True
            return False
        if self._down:
            log.info("heartbeat publishing recovered for %s",
                     self.engine_id)
            self._down = False
        self._beats.inc(engine=self.engine_id)
        return True

    def _loop(self):
        while not self._stop.is_set():
            self._publish_once()
            self._stop.wait(self.interval_s)

    def start(self) -> "HeartbeatPublisher":
        self._thread = threading.Thread(
            target=self._loop, name=f"serving-heartbeat-{self.engine_id}",
            daemon=True)
        self._thread.start()
        return self

    def stop(self, deregister: bool = True):
        """Stop beating; with `deregister` (clean shutdown) the row is
        deleted so the gateway drops this engine immediately instead of
        waiting out the TTL."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if deregister:
            try:
                self.broker.hdel(self.key, self.engine_id)
            except Exception:  # noqa: BLE001 — best-effort deregistration
                pass


class FleetTracker:
    """The gateway's view of the fleet: polls `engines:<stream>` (rate-
    limited — /healthz and /metrics scrapes share one poll per
    `poll_min_interval_s`), classifies rows by heartbeat age, and
    exports `serving_engines_alive` (gauge, live) plus
    `serving_engines_total` (counter: distinct engines ever seen by
    this gateway). `alive_count()` answers None when the broker itself
    is unreachable — the gateway then has no claim about fleet health
    and `/healthz` must say so (503), not guess."""

    def __init__(self, broker: Broker, stream: str = "serving_stream",
                 ttl_s: float = 6.0, registry=None,
                 poll_min_interval_s: float = 0.25):
        self.broker = broker
        self.stream = stream
        self.key = engines_key(stream)
        self.ttl_s = float(ttl_s)
        self.poll_min_interval_s = max(0.0, float(poll_min_interval_s))
        if registry is None:
            from analytics_zoo_tpu.observability.registry import get_registry
            registry = get_registry()
        self.registry = registry
        self._lock = threading.Lock()
        self._last_poll = 0.0
        self._engines: Dict[str, Dict] = {}
        # eid -> (last ts VALUE seen, local monotonic when it changed):
        # liveness is judged by locally-observed heartbeat progress, so
        # cross-host wall-clock skew between an engine and the gateway
        # can neither kill a healthy fleet nor keep a dead engine alive
        self._last_change: Dict[str, tuple] = {}
        self._broker_ok = True
        self._polling = False      # single-flight guard for broker I/O
        self._seen = set()
        self._total = registry.counter(
            "serving_engines_total",
            "distinct serving engines that have registered a heartbeat "
            "with this gateway")
        self._alive_gauge = registry.gauge(
            "serving_engines_alive",
            "serving engines with a fresh heartbeat (live fleet size)")
        self._alive_fn = self._alive_metric
        self._alive_gauge.set_function(self._alive_fn)

    # -- polling -----------------------------------------------------------
    def poll(self, force: bool = False) -> Optional[Dict[str, Dict]]:
        """Refresh (rate-limited) and return the engine table
        {engine_id: row} with an `alive` bool per row; None when the
        broker is unreachable.

        Broker I/O happens OUTSIDE the tracker lock, single-flight: one
        thread fetches while every concurrent /predict admission check,
        /healthz, and /metrics gauge read answers instantly from cached
        state — a stalled broker costs ONE thread a socket timeout, it
        must not dam the whole gateway behind a lock (the gateway's job
        at that moment is the fast 503)."""
        with self._lock:
            now = time.monotonic()
            due = force or now - self._last_poll >= self.poll_min_interval_s
            if due and not self._polling:
                self._polling = True
                self._last_poll = now
            else:
                return None if not self._broker_ok \
                    else dict(self._engines)
        try:
            raw = self.broker.hgetall(self.key)
        except Exception as e:  # noqa: BLE001 — report unknown
            with self._lock:
                if self._broker_ok:
                    log.warning(
                        "fleet poll failed (%s: %s); fleet state "
                        "unknown until the broker answers",
                        type(e).__name__, e)
                self._broker_ok = False
                self._polling = False
            return None
        purge = []
        with self._lock:
            self._broker_ok = True
            self._polling = False
            now = time.monotonic()
            engines: Dict[str, Dict] = {}
            wall = time.time()
            for eid, blob in raw.items():
                try:
                    row = json.loads(blob)
                except (TypeError, ValueError):
                    row = {}
                ts = row.get("ts", 0.0)
                prev = self._last_change.get(eid)
                if prev is None or prev[0] != ts:
                    self._last_change[eid] = (ts, now)
                    age = 0.0
                else:
                    age = now - prev[1]
                row["age_s"] = round(age, 3)
                # wall-clock age is informational only — liveness
                # must not depend on two hosts' clocks agreeing
                wall_age = wall - ts
                row["wall_age_s"] = round(wall_age, 3) \
                    if math.isfinite(wall_age) else None
                row["alive"] = bool(age <= self.ttl_s)
                if age > 10 * self.ttl_s:
                    # bound the hash: under crash/restart churn with
                    # engine_id=auto every crash strands a row forever,
                    # growing every later poll and /metrics payload
                    purge.append(eid)
                    self._last_change.pop(eid, None)
                    continue
                engines[eid] = row
                if eid not in self._seen:
                    self._seen.add(eid)
                    self._total.inc()
            # rows HDEL'd elsewhere (clean stops) leave the ledger
            for eid in list(self._last_change):
                if eid not in raw:
                    self._last_change.pop(eid, None)
            self._engines = engines
            out = dict(engines)
        for eid in purge:       # broker I/O outside the lock, as above
            try:
                self.broker.hdel(self.key, eid)
            except Exception:  # noqa: BLE001 — next poll retries
                pass
        if purge:
            log.info("purged %d dead engine heartbeat row(s): %s",
                     len(purge), sorted(purge)[:8])
        return out

    def alive_count(self) -> Optional[int]:
        """Engines alive AND ready (an engine beating with ready=False —
        every replica quarantined, breaker open — is present but not
        servable capacity); None when the broker is unreachable."""
        engines = self.poll()
        if engines is None:
            return None
        return sum(1 for row in engines.values()
                   if row.get("alive") and row.get("ready", True))

    def versions(self) -> Optional[Dict[str, object]]:
        """{engine_id: model_version} for every ALIVE engine (None per
        engine when it predates versioned serving, e.g. mid-rollout
        from an unversioned fleet); None when the broker is
        unreachable. The rollout controller's convergence view."""
        engines = self.poll()
        if engines is None:
            return None
        return {eid: row.get("model_version")
                for eid, row in engines.items() if row.get("alive")}

    def _alive_metric(self) -> float:
        n = self.alive_count()
        return float("nan") if n is None else float(n)

    @property
    def retry_after_s(self) -> int:
        """What a fleet-wide 503 tells clients: a replacement engine
        shows up within one heartbeat TTL."""
        return max(1, int(round(self.ttl_s)))

    def summary(self) -> Dict:
        """The /metrics JSON section."""
        engines = self.poll()
        if engines is None:
            return {"broker": "unreachable", "alive": None,
                    "engines_seen": len(self._seen)}
        return {
            "alive": sum(1 for r in engines.values() if r.get("alive")),
            "ready": sum(1 for r in engines.values()
                         if r.get("alive") and r.get("ready", True)),
            "engines_seen": len(self._seen),
            # the live version set (ISSUE 14): length 1 = converged
            # fleet; >1 = a rollout in flight (or wedged)
            "model_versions": sorted(
                {r.get("model_version") for r in engines.values()
                 if r.get("alive")
                 and r.get("model_version") is not None}),
            "engines": engines,
        }

    def close(self):
        """Release the gauge closure so a stopped gateway does not pin
        this tracker (and its broker connection) in the process-wide
        registry."""
        self._alive_gauge.release_function(self._alive_fn, freeze=True)


def validate_autoscale(knobs: Dict, prefix: str = "") -> None:
    """Shared validation for the autoscaler's knob set — called by
    `FleetAutoscaler.__init__` AND `ServingConfig._validate_elastic`
    so the bounds cannot drift between config load and construction
    (a config-accepted value the constructor rejects would crash
    `cmd_gateway` after the frontend is already up). `prefix` names
    the config spelling ("params.autoscale.") in load-time errors."""
    if knobs["min_engines"] < 1:
        raise ValueError(
            f"{prefix}min_engines={knobs['min_engines']} must be >= 1")
    if knobs["max_engines"] < knobs["min_engines"]:
        raise ValueError(
            f"{prefix}max_engines={knobs['max_engines']} must be >= "
            f"min_engines={knobs['min_engines']}")
    if knobs["backlog_low"] >= knobs["backlog_high"]:
        raise ValueError(
            f"{prefix}backlog_low={knobs['backlog_low']:g} must be "
            f"below backlog_high={knobs['backlog_high']:g}: equal "
            "thresholds flap")
    for name in ("up_stable_s", "down_stable_s", "cooldown_s",
                 "interval_s", "spawn_grace_s", "burn_high"):
        if knobs[name] <= 0:
            raise ValueError(
                f"{prefix}{name}={knobs[name]:g} must be > 0")


class FleetAutoscaler:
    """SLO-driven engine autoscaling on the gateway (ISSUE 11).

    A control loop that watches two signals and spawns/retires engine
    processes through caller-supplied hooks:

    - **backlog depth** — the broker's stream depth (undelivered plus
      in-flight records; the sink XDELs on commit, so this is exactly
      the unserved work). Scaling on queue depth instead of request
      rate is what makes the loop model-free: an expensive model backs
      the queue up at a request rate a cheap model would shrug off.
    - **SLO burn rate** — the worst ``slo_burn`` any alive engine
      reports in its heartbeat row (`ClusterServing._heartbeat_payload`
      publishes it when objectives are configured): latency already
      burning budget is a scale-up signal even while the backlog still
      looks shallow.

    Decisions are hysteretic: the overload signal must hold for
    ``up_stable_s`` before a spawn, the idle signal for
    ``down_stable_s`` before a retire, and any action starts a
    ``cooldown_s`` window in which no further action fires — a spike
    cannot flap the fleet, and scale-down is deliberately the slower
    direction. Bounds are hard: never below ``min_engines``, never
    above ``max_engines``.

    Scale-up is cheap by construction: every engine warms from the
    shared persistent compile cache (PR 10), so a new process costs
    ~0 cold compiles. Scale-down is a CLEAN stop (`retire_fn` should
    SIGTERM): the engine deregisters, drains, and whatever it had
    in-flight redelivers to peers via the claim sweep — proven under
    SIGKILL, so the graceful path is strictly safer.

    `spawn_fn()` must start one engine; `retire_fn()` must stop one and
    return True (False = nothing retirable, e.g. every child already
    exited — the desired count is then reconciled downward). The
    decision core is `tick(now)`, a pure function of the observed state
    and the clock, so tests drive it without threads or sleeps; `start`
    runs it on a daemon thread every `interval_s` (a timed Event.wait —
    the control path never parks untimed, see
    scripts/check_blocking_calls.py)."""

    def __init__(self, tracker: FleetTracker, broker: Broker,
                 stream: str, spawn_fn: Callable[[], object],
                 retire_fn: Callable[[], bool],
                 min_engines: int = 1, max_engines: int = 4,
                 backlog_high: float = 64.0, backlog_low: float = 8.0,
                 burn_high: float = 1.0,
                 up_stable_s: float = 2.0, down_stable_s: float = 10.0,
                 cooldown_s: float = 5.0, interval_s: float = 1.0,
                 spawn_grace_s: float = 30.0, registry=None,
                 backlog_fn: Optional[Callable[[], Optional[int]]]
                 = None,
                 leader_fn: Optional[Callable[[], bool]] = None):
        validate_autoscale({
            "min_engines": min_engines, "max_engines": max_engines,
            "backlog_high": backlog_high, "backlog_low": backlog_low,
            "burn_high": burn_high, "up_stable_s": up_stable_s,
            "down_stable_s": down_stable_s, "cooldown_s": cooldown_s,
            "interval_s": interval_s, "spawn_grace_s": spawn_grace_s})
        self.tracker = tracker
        self.broker = broker
        self.stream = stream
        self.spawn_fn = spawn_fn
        self.retire_fn = retire_fn
        # a gateway that already samples the stream (the admission
        # controller) shares its rate-limited probe via backlog_fn
        # instead of this loop running a second poller on the same key
        self.backlog_fn = backlog_fn
        # replicated gateway (ISSUE 16): only the leader replica's
        # autoscaler acts — two replicas both holding min_engines would
        # double-provision every scale-up. Followers tick as no-ops and
        # pick up instantly when the lease moves here.
        self.leader_fn = leader_fn
        self.min_engines = int(min_engines)
        self.max_engines = int(max_engines)
        self.backlog_high = float(backlog_high)
        self.backlog_low = float(backlog_low)
        self.burn_high = float(burn_high)
        self.up_stable_s = float(up_stable_s)
        self.down_stable_s = float(down_stable_s)
        self.cooldown_s = float(cooldown_s)
        self.interval_s = float(interval_s)
        self.spawn_grace_s = float(spawn_grace_s)
        self.desired = 0            # engines this autoscaler has live
        self._over_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._last_action = -float("inf")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if registry is None:
            from analytics_zoo_tpu.observability.registry import get_registry
            registry = get_registry()
        self._target_gauge = registry.gauge(
            "serving_engines_target",
            "engine count the autoscaler is currently holding the "
            "fleet at")
        self._decisions = registry.counter(
            "serving_autoscaler_decisions_total",
            "autoscaler actions by kind (up, down, hold_min)")
        self._backlog_gauge = registry.gauge(
            "serving_backlog_depth",
            "broker stream depth (enqueued records not yet committed) "
            "as last sampled by the elastic layer")

    # -- observed state ----------------------------------------------------
    def _backlog(self) -> Optional[int]:
        if self.backlog_fn is not None:
            try:
                return self.backlog_fn()
            except Exception:  # noqa: BLE001 — unknown, not fatal
                return None
        try:
            depth = int(self.broker.stream_depth(self.stream))
        except Exception:  # noqa: BLE001 — unknown, not fatal
            return None
        self._backlog_gauge.set(float(depth))
        return depth

    def _fleet_view(self):
        """(alive_ready_count, max_burn) from the heartbeat table; both
        None when the broker is unreachable."""
        engines = self.tracker.poll()
        if engines is None:
            return None, None
        alive = [r for r in engines.values()
                 if r.get("alive") and r.get("ready", True)]
        burns = [r.get("slo_burn") for r in alive
                 if isinstance(r.get("slo_burn"), (int, float))]
        return len(alive), (max(burns) if burns else None)

    # -- decision core (pure; tests drive it directly) ---------------------
    def tick(self, now: Optional[float] = None) -> Optional[str]:
        """One control-loop pass; returns "up"/"down" when an action
        fired, else None."""
        if self.leader_fn is not None and not self.leader_fn():
            return None          # follower replica: observe, never act
        now = time.monotonic() if now is None else now
        alive, burn = self._fleet_view()
        backlog = self._backlog()
        # reconcile desired with reality: children that died (or were
        # retired out from under us) must not leave the controller
        # believing capacity exists that doesn't. Only after
        # `spawn_grace_s`, though: a just-spawned engine needs process
        # start + warmup + first heartbeat before its absence from the
        # table means death — clamping sooner re-arms the spawn path
        # and double-provisions every scale-up (observed: cooldown <
        # engine cold-start spawned 3 engines for a 2-engine spike)
        if alive is not None and alive < self.desired \
                and now - self._last_action >= self.spawn_grace_s:
            self.desired = alive
        if self.desired < self.min_engines:
            # floor: hold the fleet at min_engines unconditionally —
            # also the initial ramp (desired starts at 0)
            self.spawn_fn()
            self.desired += 1
            self._decisions.inc(kind="hold_min")
            self._target_gauge.set(float(self.desired))
            self._last_action = now
            return "up"
        self._target_gauge.set(float(self.desired))
        if backlog is None and burn is None:
            # blind: no broker, no heartbeats — hold, reset hysteresis
            self._over_since = self._idle_since = None
            return None
        capacity = max(1, alive if alive is not None else self.desired)
        overloaded = (backlog is not None
                      and backlog > self.backlog_high * capacity) \
            or (burn is not None and burn >= self.burn_high)
        idle = (backlog is not None
                and backlog <= self.backlog_low * capacity) \
            and (burn is None or burn < self.burn_high / 2.0)
        self._over_since = (self._over_since or now) if overloaded \
            else None
        self._idle_since = (self._idle_since or now) if idle else None
        if now - self._last_action < self.cooldown_s:
            return None
        # while a previous spawn is still materializing (absent from the
        # heartbeat table, within the grace window), don't stack another
        # on the same overload signal — the backlog it was spawned for
        # hasn't seen its capacity yet
        spawn_pending = (alive is not None and alive < self.desired
                         and now - self._last_action
                         < self.spawn_grace_s)
        if overloaded and not spawn_pending \
                and self.desired < self.max_engines \
                and now - self._over_since >= self.up_stable_s:
            self.spawn_fn()
            self.desired += 1
            self._last_action = now
            self._over_since = None
            self._decisions.inc(kind="up")
            self._target_gauge.set(float(self.desired))
            log.info("autoscaler: scale UP to %d (backlog=%s burn=%s)",
                     self.desired, backlog, burn)
            return "up"
        if idle and self.desired > self.min_engines \
                and now - self._idle_since >= self.down_stable_s:
            if not self.retire_fn():
                # nothing retirable (children already exited on their
                # own): no action happened — don't log/count a phantom
                # scale-down or burn a cooldown on a no-op; the
                # reconcile clamp above will square `desired` with the
                # heartbeat table
                self._idle_since = None
                return None
            self.desired -= 1
            self._last_action = now
            self._idle_since = None
            self._decisions.inc(kind="down")
            self._target_gauge.set(float(self.desired))
            log.info("autoscaler: scale DOWN to %d (backlog=%s burn=%s)",
                     self.desired, backlog, burn)
            return "down"
        return None

    # -- lifecycle ---------------------------------------------------------
    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — the loop must live
                log.warning("autoscaler tick failed (%s: %s); retrying "
                            "next interval", type(e).__name__, e)

    def start(self) -> "FleetAutoscaler":
        if self._thread is None:
            self._stop.clear()
            # first tick inline: the min-engine floor must not wait one
            # interval before the fleet exists
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — loop recovers
                log.warning("autoscaler initial tick failed (%s: %s)",
                            type(e).__name__, e)
            self._thread = threading.Thread(
                target=self._loop, name="serving-autoscaler",
                daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
