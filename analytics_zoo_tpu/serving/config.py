"""Serving configuration — the `ClusterServingHelper` analogue.

Reference: `serving/utils/ClusterServingHelper.scala:481` parses
`scripts/cluster-serving/config.yaml` (`:3-34`: model path, core_number,
redis host/port, secure flags) and builds the InferenceModel. Same YAML
surface here, with broker URL generalized beyond redis and the model loaded
from this framework's formats."""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


def _load_yaml(path: str) -> Dict[str, Any]:
    try:
        import yaml
        with open(path) as fh:
            return yaml.safe_load(fh) or {}
    except ImportError:
        with open(path) as fh:
            return _parse_simple_yaml(fh.read())


def _parse_simple_yaml(text: str) -> Dict[str, Any]:
    """No-PyYAML fallback: nested `key:` maps / `key: value` scalars at any
    indentation depth (config.yaml uses up to three levels:
    model: {class, config: {kwargs...}})."""
    out: Dict[str, Any] = {}
    # stack of (indent, dict) from root to the innermost open map
    stack = [(-1, out)]
    for raw in text.splitlines():
        line = raw.rstrip()
        if not line or line.lstrip().startswith("#"):
            continue
        indent = len(line) - len(line.lstrip())
        key, _, value = line.strip().partition(":")
        value = value.strip()
        while len(stack) > 1 and indent <= stack[-1][0]:
            stack.pop()
        parent = stack[-1][1]
        if value:
            parent[key] = _coerce(value)
        else:
            child: Dict[str, Any] = {}
            parent[key] = child
            stack.append((indent, child))
    return out


def _coerce(v: str):
    if v in ("", "~", "null"):
        return None
    low = v.lower()
    if low in ("true", "false"):
        return low == "true"
    try:
        return int(v)
    except ValueError:
        pass
    try:
        return float(v)
    except ValueError:
        return v.strip("'\"")


@dataclass
class ServingConfig:
    """config.yaml schema (reference `scripts/cluster-serving/config.yaml`)."""

    model_path: Optional[str] = None
    model_class: Optional[str] = None       # zoo-model class name
    model_quantize: Optional[str] = None    # "int8" → quantized serving
    broker_url: str = "memory"              # memory | tcp://h:p | redis://h:p
    stream: str = "serving_stream"
    batch_size: int = 32                    # core_number analogue
    batch_timeout_ms: int = 5
    concurrent_num: int = 1
    # multi-device placement: model replicas (one per chip; "auto"/-1 =
    # every local device) or one GSPMD-sharded copy spanning all chips
    num_replicas: Any = 1                   # int, or "auto"
    placement: str = "replicated"           # replicated | sharded
    # sharded-placement mesh factorization (ISSUE 12): params.mesh — a
    # map {data: 1, fsdp: 2, tensor: 4} or the bare-parser string
    # "data=1,fsdp=2,tensor=4". Axis names follow common/mesh.AXIS_NAMES
    # (-1 infers one axis from the device count). Unset keeps the
    # data=1 × fsdp=all default; a `tensor` extent > 1 engages the rule
    # table's column/row-parallel specs for models whose activations
    # must shard too (bigger than one chip).
    mesh_axes: Optional[Dict[str, int]] = None
    # pipelined engine knobs (overlapped decode/compute/sink)
    pipelined: bool = True
    decode_workers: int = 2
    queue_depth: int = 8
    # fault tolerance (ISSUE 5, docs/ProgrammingGuide/fault-tolerance.md):
    # replica supervision (quarantine/canary revival) over a replica
    # pool, circuit breaker on the engine's broker connections, bounded
    # sink writeback buffer for broker outages
    supervise: bool = True
    failure_threshold: int = 3
    probe_interval_s: float = 0.5
    latency_factor: float = 8.0
    breaker_failure_threshold: int = 3
    breaker_reset_s: float = 1.0
    sink_buffer_batches: int = 256
    # fleet mode (ISSUE 10, docs/ProgrammingGuide/cluster-serving.md
    # "Scaling out"): engine_id names this process as one of N
    # co-consumers ("auto" generates a unique id); heartbeats publish
    # to engines:<stream> every heartbeat_interval_s and the gateway
    # counts an engine dead after engine_ttl_s without one; the claim
    # sweep adopts a dead peer's unacked records once they sit idle
    # claim_min_idle_s, checking every claim_interval_s
    engine_id: Optional[str] = None
    heartbeat_interval_s: float = 2.0
    engine_ttl_s: float = 6.0
    claim_min_idle_s: float = 30.0
    claim_interval_s: float = 5.0
    # partitioned request plane (ISSUE 16, docs/ProgrammingGuide/
    # request-plane.md): params.partitions splits the stream into N
    # broker streams keyed by consistent hash of the record id; engines
    # lease partition SETS from a broker table and take over an expired
    # peer's partitions. The count is a FLEET-WIDE agreement persisted
    # in the broker meta row — changing it under a live fleet is
    # rejected unless params.reshard (or --reshard) explicitly
    # acknowledges that in-flight records on the old layout may land on
    # engines not reading their stream until the fleet restarts.
    partitions: int = 1
    reshard: bool = False
    partition_lease_ttl_s: float = 5.0
    # elastic serving (ISSUE 11, docs/ProgrammingGuide/cluster-serving.md
    # "Elastic serving"): params.batching selects the reader's
    # micro-batching policy (adaptive | fixed | static) and its deadline
    # budget (defaults to slo.latency_ms when unset); params.admission
    # declares priority tiers (lowest first), the HTTP header/record
    # field that carries them, the gateway 429 threshold and the
    # engine-side shed threshold; params.autoscale bounds and tunes the
    # gateway's SLO-driven engine autoscaler
    # versioned rollout (ISSUE 14, docs/ProgrammingGuide/
    # cluster-serving.md "Model rollout"): params.rollout.model_dir
    # points the engine's rollout agent (and the gateway's controller,
    # via `gateway --rollout-dir`) at the trainer's checkpoint root;
    # only PUBLISH-marked versions are acted on. poll/drain/canary
    # cadences plus the golden-output delta tolerance (None =
    # finiteness-only canary gate) and the controller's per-engine
    # conversion timeout.
    rollout_model_dir: Optional[str] = None
    rollout_poll_interval_s: float = 2.0
    rollout_drain_timeout_s: float = 10.0
    rollout_canary_timeout_s: float = 10.0
    rollout_golden_tolerance: Optional[float] = None
    rollout_engine_timeout_s: float = 60.0
    batch_policy: str = "adaptive"
    deadline_ms: Optional[float] = None
    batch_margin_ms: float = 2.0
    admission_tiers: Optional[list] = None
    admission_header: str = "X-Priority"
    admission_field: str = "tier"
    admission_max_backlog: int = 512
    shed_backlog: Optional[int] = None
    autoscale: Optional[Dict[str, Any]] = None
    # shape-bucket pre-warming: list of per-record shapes, e.g.
    # [[32, 32, 3]] (or the string "32x32x3,224x224x3" in bare-parser
    # YAML) — every bucket of each shape pre-compiles at load so no XLA
    # compile lands on the request path
    warmup_shapes: Optional[list] = None
    warmup_dtype: str = "float32"
    # persistent compilation cache (`compile_cache/`): warmup consults a
    # disk-backed AOT executable cache per (replica, bucket) before
    # compiling, so a restart warms from disk in ~ms per bucket.
    # compile_cache_max_bytes (int, or "512M"/"2G") bounds the dir with
    # LRU eviction.
    compile_cache_dir: Optional[str] = None
    compile_cache_max_bytes: Optional[int] = None
    # request-scoped tracing (`observability/tracing.py`): `trace: true`
    # attaches a span Tracer to the pipeline; trace_path additionally
    # dumps Chrome trace JSON (Perfetto-viewable) on shutdown
    trace: bool = False
    trace_path: Optional[str] = None
    # fleet observability plane (ISSUE 17): trace_sample > 0 turns on
    # cross-process span export — clients/gateways stamp trace context
    # on every record, engines continue the trace per stage and publish
    # head-sampled spans (plus force-sampled failures/SLO violations)
    # into the traces:<stream> broker hash every
    # trace_export_interval_s; trace_buffer_spans bounds the local span
    # ring (overflow counted in observability_spans_dropped_total).
    # fleet_metrics_interval_s paces each engine's registry snapshot
    # into the metrics:<stream> hash for gateway-aggregated /metrics
    # (0 disables publishing).
    trace_sample: float = 0.0
    trace_buffer_spans: int = 20000
    trace_export_interval_s: float = 0.5
    fleet_metrics_interval_s: float = 2.0
    # SLO objectives (ISSUE 6, `observability/slo.py`): a params.slo
    # block — latency_ms (target at latency_quantile), availability
    # (non-degraded fraction), window_s. Evaluated by the engine's
    # SLOTracker; feeds /healthz and the slo_burn_rate gauges.
    slo_latency_ms: Optional[float] = None
    slo_latency_quantile: float = 0.95
    slo_availability: Optional[float] = None
    slo_window_s: float = 300.0
    # generative decode mode (`serving/decode.py`): a params.generative
    # block switches the engine from the request-batched dispatch path to
    # the continuous-batching decode engine. slots sizes the pooled KV
    # cache (one [slots, heads, max_kv_len, head_dim] buffer per layer);
    # kv_buckets/prompt_buckets are the static shapes warmup pre-compiles
    # (default: pow-2 ladders derived from max_kv_len).
    generative: bool = False
    decode_slots: int = 8
    decode_max_kv_len: int = 256
    decode_kv_buckets: Optional[List[int]] = None
    decode_prompt_buckets: Optional[List[int]] = None
    decode_max_new_tokens: int = 64
    decode_eos_id: Optional[int] = None
    decode_max_waiting: int = 256
    decode_max_prefills: int = 4
    # paged KV (ISSUE 19): paged: true swaps the stripe pool for the
    # block pool + prefix cache + chunked prefill. block_len sizes one
    # KV block in tokens; kv_blocks the pool (default: slots ×
    # max_kv_len/block_len + scratch — byte parity with the stripes);
    # prefill_chunk bounds tokens per prefill chunk (null = whole
    # prompt); prefix_cache_blocks caps the trie (null = unbounded).
    decode_paged: bool = False
    decode_block_len: int = 16
    decode_kv_blocks: Optional[int] = None
    decode_prefill_chunk: Optional[int] = None
    decode_prefix_cache: bool = True
    decode_prefix_cache_blocks: Optional[int] = None
    # crash-safe serving (ISSUE 20): max_seq_wall_s arms the
    # per-sequence watchdog (null = off); preempt_max bounds how often
    # one sequence may be preempted under KV pressure before it must
    # complete ahead of new admissions (anti-thrash); writeback_buffer_
    # rows bounds the pending row buffer held through a broker outage
    # (oldest-step rows shed first — the final blob stays
    # authoritative); resume: false opts this engine out of claiming
    # and resuming a dead peer's in-flight generative records;
    # keepalive_s sets the SSE keepalive-comment cadence (null = none).
    decode_max_seq_wall_s: Optional[float] = None
    decode_preempt_max: int = 3
    decode_writeback_buffer: int = 512
    decode_resume: bool = True
    decode_keepalive_s: Optional[float] = None
    # on-demand profiler capture (POST /profile): artifact root +
    # rotation bound; profile_enabled: false turns the endpoint off
    # (404). Default root is <tmp>/zoo_profiles.
    profile_dir: Optional[str] = None
    profile_max_artifacts: int = 8
    profile_enabled: bool = True
    http_port: Optional[int] = None
    # secure block (`ClusterServingHelper.scala:121-134` — model_encrypted
    # gates the wait-for-secret/salt flow before weights load)
    model_encrypted: bool = False
    secret_timeout_s: float = 60.0
    scrub_secret: bool = False              # delete secret after first read
    # frontend hardening (`FrontEndApp.scala` tokenBucket/https arguments)
    tokens_per_second: Optional[float] = None
    token_acquire_timeout_ms: float = 100.0
    tls_certfile: Optional[str] = None
    tls_keyfile: Optional[str] = None
    extra: Dict[str, Any] = field(default_factory=dict)

    # pre-consolidation field names (ZooConfig JSON / ZOO_SERVING_* env vars)
    LEGACY_FIELDS = {"core_number": "batch_size",
                     "redis_url": "broker_url",
                     "queue": "stream",
                     "max_latency_ms": "batch_timeout_ms"}

    @classmethod
    def load(cls, path: str, num_replicas=None,
             placement: Optional[str] = None,
             compile_cache_dir: Optional[str] = None,
             mesh: Optional[str] = None) -> "ServingConfig":
        """`num_replicas`/`placement`/`compile_cache_dir` keyword
        overrides (the CLI flags) replace the file's values BEFORE
        validation, so an override can rescue a config authored for a
        bigger host (e.g. an 8-chip config started on a 2-device box
        with `--num-replicas 2`)."""
        raw = _load_yaml(path)
        model = raw.get("model", {}) or {}
        params = raw.get("params", {}) or {}
        redis = raw.get("redis", {}) or {}
        cfg = cls()
        cfg.model_path = model.get("path")
        cfg.model_class = model.get("class")
        cfg.model_quantize = model.get("quantize")
        if redis.get("host"):
            cfg.broker_url = f"redis://{redis['host']}:{redis.get('port', 6379)}"
        if raw.get("broker"):
            cfg.broker_url = raw["broker"]
        cfg.batch_size = int(params.get("core_number",
                                        params.get("batch_size", 32)))
        cfg.batch_timeout_ms = int(params.get("batch_timeout_ms", 5))
        cfg.concurrent_num = int(params.get("concurrent_num", 1))
        cfg.num_replicas = num_replicas if num_replicas is not None \
            else params.get("num_replicas", 1)
        cfg.placement = placement if placement is not None \
            else str(params.get("placement", "replicated"))
        cfg.mesh_axes = _parse_mesh_axes(
            mesh if mesh is not None else params.get("mesh"))
        # fail HERE, not deep inside the dispatch stage: a bad placement
        # string or a replica count the host cannot satisfy is a config
        # error, and config errors belong at load time
        cfg._validate_placement()
        cfg.compile_cache_dir = compile_cache_dir if compile_cache_dir \
            is not None else params.get("compile_cache_dir")
        cfg.compile_cache_max_bytes = _parse_bytes(
            params.get("compile_cache_max_bytes"))
        cfg._validate_compile_cache()
        cfg.pipelined = bool(params.get("pipelined", True))
        cfg.decode_workers = int(params.get("decode_workers", 2))
        cfg.queue_depth = int(params.get("queue_depth", 8))
        cfg.supervise = bool(params.get("supervise", True))
        cfg.failure_threshold = int(params.get("failure_threshold", 3))
        cfg.probe_interval_s = float(params.get("probe_interval_s", 0.5))
        cfg.latency_factor = float(params.get("latency_factor", 8.0))
        cfg.breaker_failure_threshold = int(
            params.get("breaker_failure_threshold", 3))
        cfg.breaker_reset_s = float(params.get("breaker_reset_s", 1.0))
        cfg.sink_buffer_batches = int(
            params.get("sink_buffer_batches", 256))
        cfg._validate_fault_tolerance()
        engine_id = params.get("engine_id")
        if engine_id is not None:
            cfg.engine_id = str(engine_id)
        cfg.heartbeat_interval_s = float(
            params.get("heartbeat_interval_s", 2.0))
        cfg.engine_ttl_s = float(params.get("engine_ttl_s", 6.0))
        cfg.claim_min_idle_s = float(params.get("claim_min_idle_s", 30.0))
        cfg.claim_interval_s = float(params.get("claim_interval_s", 5.0))
        cfg._validate_fleet()
        cfg.partitions = int(params.get("partitions", 1))
        cfg.reshard = bool(params.get("reshard", False))
        cfg.partition_lease_ttl_s = float(
            params.get("partition_lease_ttl_s", 5.0))
        cfg._validate_partitions()
        rollout = params.get("rollout", {}) or {}
        if not isinstance(rollout, dict):
            raise ValueError(
                f"params.rollout={rollout!r} must be a map (model_dir, "
                "poll_interval_s, drain_timeout_s, canary_timeout_s, "
                "golden_tolerance, engine_timeout_s)")
        cfg.rollout_model_dir = rollout.get("model_dir")
        cfg.rollout_poll_interval_s = float(
            rollout.get("poll_interval_s", 2.0))
        cfg.rollout_drain_timeout_s = float(
            rollout.get("drain_timeout_s", 10.0))
        cfg.rollout_canary_timeout_s = float(
            rollout.get("canary_timeout_s", 10.0))
        if rollout.get("golden_tolerance") is not None:
            cfg.rollout_golden_tolerance = float(
                rollout["golden_tolerance"])
        cfg.rollout_engine_timeout_s = float(
            rollout.get("engine_timeout_s", 60.0))
        cfg._validate_rollout()
        batching = params.get("batching", {}) or {}
        if not isinstance(batching, dict):
            raise ValueError(
                f"params.batching={batching!r} must be a map (policy, "
                "deadline_ms, margin_ms)")
        cfg.batch_policy = str(batching.get("policy", "adaptive"))
        if batching.get("deadline_ms") is not None:
            cfg.deadline_ms = float(batching["deadline_ms"])
        cfg.batch_margin_ms = float(batching.get("margin_ms", 2.0))
        admission = params.get("admission", {}) or {}
        if not isinstance(admission, dict):
            raise ValueError(
                f"params.admission={admission!r} must be a map (tiers, "
                "header, field, max_backlog, shed_backlog)")
        cfg.admission_tiers = _parse_tiers(admission.get("tiers"))
        cfg.admission_header = str(admission.get("header", "X-Priority"))
        cfg.admission_field = str(admission.get("field", "tier"))
        cfg.admission_max_backlog = int(admission.get("max_backlog", 512))
        if admission.get("shed_backlog") is not None:
            cfg.shed_backlog = int(admission["shed_backlog"])
        elif cfg.admission_tiers:
            # default: the engine starts shedding at twice the gateway's
            # hard 429 line — admission throttles first, shed is the
            # backstop for producers that bypass the gateway
            cfg.shed_backlog = 2 * cfg.admission_max_backlog
        autoscale = params.get("autoscale", None)
        if autoscale is not None and not isinstance(autoscale, dict):
            raise ValueError(
                f"params.autoscale={autoscale!r} must be a map "
                "(min_engines, max_engines, backlog_high, backlog_low, "
                "up_stable_s, down_stable_s, cooldown_s, interval_s, "
                "burn_high)")
        if autoscale is not None:
            cfg.autoscale = {
                "min_engines": int(autoscale.get("min_engines", 1)),
                "max_engines": int(autoscale.get("max_engines", 4)),
                "backlog_high": float(autoscale.get("backlog_high", 64)),
                "backlog_low": float(autoscale.get("backlog_low", 8)),
                "burn_high": float(autoscale.get("burn_high", 1.0)),
                "up_stable_s": float(autoscale.get("up_stable_s", 2.0)),
                "down_stable_s": float(
                    autoscale.get("down_stable_s", 10.0)),
                "cooldown_s": float(autoscale.get("cooldown_s", 5.0)),
                "interval_s": float(autoscale.get("interval_s", 1.0)),
                "spawn_grace_s": float(
                    autoscale.get("spawn_grace_s", 30.0)),
            }
        cfg._validate_elastic()
        cfg.warmup_shapes = _parse_warmup_shapes(
            params.get("warmup_shapes"))
        cfg.warmup_dtype = str(params.get("warmup_dtype", "float32"))
        cfg.trace = bool(params.get("trace", False))
        cfg.trace_path = params.get("trace_path")
        cfg.trace_sample = float(params.get("trace_sample", 0.0))
        cfg.trace_buffer_spans = int(
            params.get("trace_buffer_spans", 20000))
        cfg.trace_export_interval_s = float(
            params.get("trace_export_interval_s", 0.5))
        cfg.fleet_metrics_interval_s = float(
            params.get("fleet_metrics_interval_s", 2.0))
        cfg._validate_observability()
        slo = params.get("slo", {}) or {}
        if not isinstance(slo, dict):
            raise ValueError(
                f"params.slo={slo!r} must be a map (latency_ms, "
                "latency_quantile, availability, window_s)")
        if slo.get("latency_ms") is not None:
            cfg.slo_latency_ms = float(slo["latency_ms"])
        if slo.get("latency_quantile") is not None:
            cfg.slo_latency_quantile = float(slo["latency_quantile"])
        if slo.get("availability") is not None:
            cfg.slo_availability = float(slo["availability"])
        if slo.get("window_s") is not None:
            cfg.slo_window_s = float(slo["window_s"])
        cfg.build_slo()          # objective errors fail the load, like
        #                          placement — not the supervisor thread
        gen = params.get("generative", None)
        if gen is not None and not isinstance(gen, dict):
            raise ValueError(
                f"params.generative={gen!r} must be a map (slots, "
                "max_kv_len, kv_buckets, prompt_buckets, max_new_tokens, "
                "eos_id, max_waiting, max_prefills)")
        if gen is not None:
            cfg.generative = True
            cfg.decode_slots = int(gen.get("slots", 8))
            cfg.decode_max_kv_len = int(gen.get("max_kv_len", 256))
            if gen.get("kv_buckets") is not None:
                cfg.decode_kv_buckets = [
                    int(b) for b in gen["kv_buckets"]]
            if gen.get("prompt_buckets") is not None:
                cfg.decode_prompt_buckets = [
                    int(b) for b in gen["prompt_buckets"]]
            cfg.decode_max_new_tokens = int(gen.get("max_new_tokens", 64))
            if gen.get("eos_id") is not None:
                cfg.decode_eos_id = int(gen["eos_id"])
            cfg.decode_max_waiting = int(gen.get("max_waiting", 256))
            cfg.decode_max_prefills = int(gen.get("max_prefills", 4))
            cfg.decode_paged = bool(gen.get("paged", False))
            cfg.decode_block_len = int(gen.get("block_len", 16))
            if gen.get("kv_blocks") is not None:
                cfg.decode_kv_blocks = int(gen["kv_blocks"])
            if gen.get("prefill_chunk") is not None:
                cfg.decode_prefill_chunk = int(gen["prefill_chunk"])
            cfg.decode_prefix_cache = bool(gen.get("prefix_cache", True))
            if gen.get("prefix_cache_blocks") is not None:
                cfg.decode_prefix_cache_blocks = int(
                    gen["prefix_cache_blocks"])
            if gen.get("max_seq_wall_s") is not None:
                cfg.decode_max_seq_wall_s = float(gen["max_seq_wall_s"])
            cfg.decode_preempt_max = int(gen.get("preempt_max", 3))
            cfg.decode_writeback_buffer = int(
                gen.get("writeback_buffer_rows", 512))
            cfg.decode_resume = bool(gen.get("resume", True))
            if gen.get("keepalive_s") is not None:
                cfg.decode_keepalive_s = float(gen["keepalive_s"])
            cfg._validate_generative()
        cfg.profile_dir = params.get("profile_dir")
        cfg.profile_enabled = bool(params.get("profile_enabled", True))
        cfg.profile_max_artifacts = int(
            params.get("profile_max_artifacts", 8))
        if cfg.profile_max_artifacts < 1:
            raise ValueError(
                f"params.profile_max_artifacts="
                f"{cfg.profile_max_artifacts} must be >= 1")
        if raw.get("http_port") is not None:
            cfg.http_port = int(raw["http_port"])
        secure = raw.get("secure", {}) or {}
        cfg.model_encrypted = bool(secure.get("model_encrypted", False))
        if secure.get("secret_timeout_s") is not None:
            cfg.secret_timeout_s = float(secure["secret_timeout_s"])
        cfg.scrub_secret = bool(secure.get("scrub_secret", False))
        frontend = raw.get("frontend", {}) or {}
        if frontend.get("tokens_per_second") is not None:
            cfg.tokens_per_second = float(frontend["tokens_per_second"])
        if frontend.get("token_acquire_timeout_ms") is not None:
            cfg.token_acquire_timeout_ms = float(
                frontend["token_acquire_timeout_ms"])
        cfg.tls_certfile = frontend.get("tls_certfile")
        cfg.tls_keyfile = frontend.get("tls_keyfile")
        cfg.extra = raw
        return cfg

    def _validate_placement(self):
        """Reject bad `placement`/`num_replicas` values with a clear error
        while still parsing the config (counting local devices is cheap —
        the backend initializes lazily and serving needs it anyway)."""
        from analytics_zoo_tpu.serving.inference_model import PLACEMENTS
        if self.placement not in PLACEMENTS:
            raise ValueError(
                f"params.placement={self.placement!r} is not one of "
                f"{'/'.join(PLACEMENTS)}")
        if self.mesh_axes is not None:
            if self.placement != "sharded":
                raise ValueError(
                    "params.mesh describes the sharded placement's "
                    f"device-mesh factorization but placement is "
                    f"{self.placement!r}; set params.placement: sharded "
                    "(or drop the mesh block)")
            from analytics_zoo_tpu.common.mesh import validate_axis_names
            try:
                validate_axis_names(self.mesh_axes)
            except ValueError as e:
                raise ValueError(f"params.mesh: {e}") from None
        n = self.num_replicas
        if n is None or n == "auto":   # bare `num_replicas:` == auto,
            return                     # matching InferenceModel(None)
        try:
            n = int(n)
        except (TypeError, ValueError):
            raise ValueError(
                f"params.num_replicas={n!r} must be an integer, "
                "'auto', or -1 (one replica per local device)") from None
        if n in (0, -1):           # auto spellings
            return
        if n < -1:
            raise ValueError(
                f"params.num_replicas={n} must be >= 1 (or 'auto'/-1)")
        if n == 1:
            # cannot exceed any host's >=1 devices — and counting them
            # would initialize the jax backend at config-parse time,
            # freezing platform selection before a forced-host re-exec
            # (bench --devices / dryrun) can set its env
            return
        import jax
        avail = jax.local_device_count()
        if n > avail:
            raise ValueError(
                f"params.num_replicas={n} exceeds the {avail} available "
                f"local device(s); lower it or use 'auto'")

    def _validate_fault_tolerance(self):
        """Supervision/breaker knobs fail at config load like placement:
        a zero threshold or a negative interval is a config error, not a
        runtime surprise inside the supervisor thread."""
        for name, value, minimum in (
                ("failure_threshold", self.failure_threshold, 1),
                ("breaker_failure_threshold",
                 self.breaker_failure_threshold, 1),
                ("sink_buffer_batches", self.sink_buffer_batches, 1)):
            if value < minimum:
                raise ValueError(
                    f"params.{name}={value} must be >= {minimum}")
        for name, value in (("probe_interval_s", self.probe_interval_s),
                            ("breaker_reset_s", self.breaker_reset_s),
                            ("latency_factor", self.latency_factor)):
            if value <= 0:
                raise ValueError(
                    f"params.{name}={value} must be > 0")

    def _validate_fleet(self):
        """Fleet knobs fail at config load like the rest: a zero TTL or
        a claim window shorter than the heartbeat cadence is an
        operator error, not a runtime surprise."""
        for name, value in (
                ("heartbeat_interval_s", self.heartbeat_interval_s),
                ("engine_ttl_s", self.engine_ttl_s),
                ("claim_min_idle_s", self.claim_min_idle_s),
                ("claim_interval_s", self.claim_interval_s)):
            if value <= 0:
                raise ValueError(f"params.{name}={value} must be > 0")
        if self.engine_ttl_s <= self.heartbeat_interval_s:
            raise ValueError(
                f"params.engine_ttl_s={self.engine_ttl_s} must exceed "
                f"heartbeat_interval_s={self.heartbeat_interval_s}: one "
                "delayed beat would flap every engine dead")
        if self.engine_id is not None and not str(self.engine_id).strip():
            raise ValueError("params.engine_id must be a non-empty "
                             "string, 'auto', or unset")

    def _validate_partitions(self):
        """Partition knobs fail at config load like the rest (ISSUE
        16): a bad count, a partitioned engine without the pipelined
        path or a fleet identity, or a non-positive lease TTL are
        operator errors, not reader-loop surprises. (The count-change-
        under-a-live-fleet check is runtime state, not config: the
        broker's meta row enforces it when the engine starts —
        `partitions.PartitionLeaseTable.ensure_meta`.)"""
        from analytics_zoo_tpu.serving.partitions import \
            validate_partitions
        try:
            validate_partitions(self.partitions)
        except ValueError as e:
            raise ValueError(f"params.partitions: {e}") from None
        if self.partition_lease_ttl_s <= 0:
            raise ValueError(
                f"params.partition_lease_ttl_s="
                f"{self.partition_lease_ttl_s:g} must be > 0")
        if self.partitions > 1 and not self.pipelined:
            raise ValueError(
                "params.partitions > 1 needs params.pipelined: true — "
                "the legacy single-threaded loop reads one stream")
        # engine_id is NOT required here: the fleet identity usually
        # arrives as the CLI --engine-id override — cmd_start enforces
        # the pairing after overrides land

    def _validate_observability(self):
        """Trace-plane knobs fail at config load like the rest (ISSUE
        17): a sampling rate outside [0, 1] or a non-positive buffer /
        cadence is an operator error, not an exporter-thread surprise."""
        if not 0.0 <= self.trace_sample <= 1.0:
            raise ValueError(
                f"params.trace_sample={self.trace_sample:g} must be in "
                "[0, 1] (the head-sampling rate)")
        if self.trace_buffer_spans < 1:
            raise ValueError(
                f"params.trace_buffer_spans={self.trace_buffer_spans} "
                "must be >= 1")
        if self.trace_export_interval_s <= 0:
            raise ValueError(
                f"params.trace_export_interval_s="
                f"{self.trace_export_interval_s:g} must be > 0")
        if self.fleet_metrics_interval_s < 0:
            raise ValueError(
                f"params.fleet_metrics_interval_s="
                f"{self.fleet_metrics_interval_s:g} must be >= 0 "
                "(0 disables fleet metrics publishing)")

    def _validate_rollout(self):
        """Rollout knobs fail at config load like the rest (ISSUE 14):
        a bad dir spelling, non-positive cadence or negative tolerance
        is an operator error, not a control-loop surprise mid-swap."""
        d = self.rollout_model_dir
        if d is not None and (not isinstance(d, str) or not d.strip()):
            raise ValueError(
                f"params.rollout.model_dir={d!r} must be a non-empty "
                "path string (the trainer's checkpoint root)")
        for name, value in (
                ("poll_interval_s", self.rollout_poll_interval_s),
                ("drain_timeout_s", self.rollout_drain_timeout_s),
                ("canary_timeout_s", self.rollout_canary_timeout_s),
                ("engine_timeout_s", self.rollout_engine_timeout_s)):
            if value <= 0:
                raise ValueError(
                    f"params.rollout.{name}={value:g} must be > 0")
        tol = self.rollout_golden_tolerance
        if tol is not None and tol < 0:
            raise ValueError(
                f"params.rollout.golden_tolerance={tol:g} must be "
                ">= 0 (or unset for the finiteness-only gate)")
        # engine_id is NOT required here: the fleet identity usually
        # arrives as the CLI --engine-id override — cmd_start enforces
        # the pairing after overrides land

    def _validate_elastic(self):
        """Elastic knobs fail at config load like the rest (ISSUE 11):
        a bad policy string, a non-positive deadline, duplicate tiers,
        or inverted autoscaler thresholds are operator errors, not
        runtime surprises inside the reader or the control loop."""
        from analytics_zoo_tpu.serving.elastic import (
            AdaptiveBatchController, TierTable)
        if self.batch_policy not in AdaptiveBatchController.POLICIES:
            raise ValueError(
                f"params.batching.policy={self.batch_policy!r} is not "
                f"one of {'/'.join(AdaptiveBatchController.POLICIES)}")
        if self.deadline_ms is not None and self.deadline_ms <= 0:
            raise ValueError(
                f"params.batching.deadline_ms={self.deadline_ms} must "
                "be > 0")
        if self.batch_margin_ms < 0:
            raise ValueError(
                f"params.batching.margin_ms={self.batch_margin_ms} "
                "must be >= 0")
        if self.admission_tiers is not None:
            TierTable(self.admission_tiers)   # raises on empty/dupes
        if self.admission_max_backlog <= 0:
            raise ValueError(
                f"params.admission.max_backlog="
                f"{self.admission_max_backlog} must be > 0")
        if self.shed_backlog is not None and self.shed_backlog <= 0:
            raise ValueError(
                f"params.admission.shed_backlog={self.shed_backlog} "
                "must be > 0")
        if self.autoscale is not None:
            # ONE validator, shared with FleetAutoscaler.__init__ —
            # the bounds cannot drift between config load and the
            # gateway's construction
            from analytics_zoo_tpu.serving.fleet import validate_autoscale
            validate_autoscale(self.autoscale,
                               prefix="params.autoscale.")

    def build_admission(self, broker, registry=None):
        """The gateway-side `AdmissionController` this config declares
        (None when no tiers are configured)."""
        if not self.admission_tiers:
            return None
        from analytics_zoo_tpu.serving.elastic import AdmissionController
        return AdmissionController(
            broker, self.stream, self.admission_tiers,
            max_backlog=self.admission_max_backlog, registry=registry)

    def resolve_engine_id(self) -> Optional[str]:
        """The engine id `cmd_start` hands to ClusterServing: None when
        fleet mode is off, a unique generated id for 'auto', the
        configured string otherwise."""
        if self.engine_id is None:
            return None
        if str(self.engine_id).lower() == "auto":
            import os
            import uuid
            return f"engine-{os.getpid()}-{uuid.uuid4().hex[:6]}"
        return str(self.engine_id)

    def _validate_generative(self):
        """Decode-mode sizing errors fail the load like placement: a KV
        bucket larger than the pool, or a slot count < 1, would only
        surface as a mid-warmup shape error otherwise."""
        if self.decode_slots < 1:
            raise ValueError(
                f"params.generative.slots={self.decode_slots} must be >= 1")
        if self.decode_max_kv_len < 2:
            raise ValueError(
                f"params.generative.max_kv_len={self.decode_max_kv_len} "
                "must be >= 2")
        for name, ladder in (("kv_buckets", self.decode_kv_buckets),
                             ("prompt_buckets", self.decode_prompt_buckets)):
            if ladder is None:
                continue
            if not ladder or any(int(b) < 1 for b in ladder):
                raise ValueError(
                    f"params.generative.{name}={ladder!r} must be a "
                    "non-empty list of positive ints")
            if max(ladder) > self.decode_max_kv_len:
                raise ValueError(
                    f"params.generative.{name} max {max(ladder)} exceeds "
                    f"max_kv_len={self.decode_max_kv_len}")
        if self.decode_max_new_tokens < 1:
            raise ValueError(
                f"params.generative.max_new_tokens="
                f"{self.decode_max_new_tokens} must be >= 1")
        if self.decode_max_prefills < 1:
            raise ValueError(
                f"params.generative.max_prefills="
                f"{self.decode_max_prefills} must be >= 1")
        if self.decode_paged:
            if self.decode_block_len < 1:
                raise ValueError(
                    f"params.generative.block_len={self.decode_block_len} "
                    "must be >= 1")
            if self.decode_max_kv_len % self.decode_block_len:
                raise ValueError(
                    f"params.generative.max_kv_len="
                    f"{self.decode_max_kv_len} must be a multiple of "
                    f"block_len={self.decode_block_len} (the block table "
                    "covers the pool in whole blocks)")
            if self.decode_kv_buckets is not None:
                bad = [b for b in self.decode_kv_buckets
                       if int(b) % self.decode_block_len]
                if bad:
                    raise ValueError(
                        f"params.generative.kv_buckets {bad} must be "
                        f"multiples of block_len={self.decode_block_len} "
                        "(a paged attention window reads whole blocks)")
            if (self.decode_kv_blocks is not None
                    and self.decode_kv_blocks < 2):
                raise ValueError(
                    f"params.generative.kv_blocks={self.decode_kv_blocks} "
                    "must be >= 2 (scratch + one usable block)")
            if (self.decode_prefill_chunk is not None
                    and self.decode_prefill_chunk < 1):
                raise ValueError(
                    f"params.generative.prefill_chunk="
                    f"{self.decode_prefill_chunk} must be >= 1")
            if (self.decode_prefix_cache_blocks is not None
                    and self.decode_prefix_cache_blocks < 1):
                raise ValueError(
                    f"params.generative.prefix_cache_blocks="
                    f"{self.decode_prefix_cache_blocks} must be >= 1")
        if (self.decode_max_seq_wall_s is not None
                and self.decode_max_seq_wall_s <= 0):
            raise ValueError(
                f"params.generative.max_seq_wall_s="
                f"{self.decode_max_seq_wall_s} must be > 0 (or null to "
                "disable the per-sequence watchdog)")
        if self.decode_preempt_max < 0:
            raise ValueError(
                f"params.generative.preempt_max={self.decode_preempt_max} "
                "must be >= 0 (0 disables KV-pressure preemption)")
        if self.decode_writeback_buffer < 1:
            raise ValueError(
                f"params.generative.writeback_buffer_rows="
                f"{self.decode_writeback_buffer} must be >= 1")
        if (self.decode_keepalive_s is not None
                and self.decode_keepalive_s <= 0):
            raise ValueError(
                f"params.generative.keepalive_s={self.decode_keepalive_s} "
                "must be > 0 (or null for no keepalive comments)")

    def _validate_compile_cache(self):
        """Cache-setting errors belong at config load, like placement:
        a bad path or a non-positive byte budget must fail the start
        command, not surface mid-warmup."""
        d = self.compile_cache_dir
        if d is not None:
            if not isinstance(d, str) or not d.strip():
                raise ValueError(
                    f"params.compile_cache_dir={d!r} must be a non-empty "
                    "path string")
            expanded = os.path.abspath(os.path.expanduser(d))
            if os.path.exists(expanded) and not os.path.isdir(expanded):
                raise ValueError(
                    f"params.compile_cache_dir={d!r} exists and is not a "
                    "directory")
        mb = self.compile_cache_max_bytes
        if mb is not None:
            if not isinstance(mb, int) or mb <= 0:
                raise ValueError(
                    f"params.compile_cache_max_bytes={mb!r} must be a "
                    'positive byte count (int, or "512M"/"2G")')
            if d is None:
                raise ValueError(
                    "params.compile_cache_max_bytes is set but "
                    "params.compile_cache_dir is not; the budget bounds "
                    "the cache directory")

    def build_slo(self):
        """The `SLOObjectives` this config declares, validated (None
        when no objective is set); `cmd_start` hands it to
        `ClusterServing(slo=...)`."""
        if self.slo_latency_ms is None and self.slo_availability is None:
            return None
        from analytics_zoo_tpu.observability.slo import SLOObjectives
        return SLOObjectives(
            latency_ms=self.slo_latency_ms,
            latency_quantile=self.slo_latency_quantile,
            availability=self.slo_availability,
            window_s=self.slo_window_s).validate()

    def build_compile_cache(self, registry=None):
        """The `CompileCache` this config names (None when caching is
        off); `build_model` wires it into the InferenceModel."""
        if not self.compile_cache_dir:
            return None
        from analytics_zoo_tpu.compile_cache import CompileCache
        return CompileCache(self.compile_cache_dir,
                            max_bytes=self.compile_cache_max_bytes,
                            registry=registry)

    def build_generative_model(self):
        """Decode-mode model resolution: `model.class` must name a class
        exposing the generative contract (`init_params`/`init_kv`/
        `prefill_fn`/`step_fn` — see `models/generative.py`). Weights come
        from the instance's own `init_params()` (a model that loads from
        disk does so there); returns `(InferenceModel, instance)`."""
        from analytics_zoo_tpu.serving.inference_model import InferenceModel
        if not self.model_class:
            raise ValueError(
                "params.generative needs model.class naming a generative "
                "model (init_params/init_kv/prefill_fn/step_fn)")
        cls = _find_model_class(self.model_class)
        kwargs = (self.extra.get("model", {}) or {}).get("config") or {}
        inst = cls(**kwargs)
        needed = ["init_params", "init_kv", "prefill_fn", "step_fn"]
        if self.decode_paged:
            needed += ["init_kv_blocks", "paged_prefill_fn",
                       "paged_step_fn"]
        missing = [a for a in needed
                   if not callable(getattr(inst, a, None))]
        if missing:
            raise ValueError(
                f"model.class={self.model_class} lacks the "
                f"{'paged ' if self.decode_paged else ''}generative "
                f"contract: missing {', '.join(missing)}")
        im = InferenceModel(placement="replicated", num_replicas=1,
                            compile_cache=self.build_compile_cache())
        im.load_generative(
            inst.prefill_fn, inst.step_fn, inst.init_params(),
            paged_prefill_fn=getattr(inst, "paged_prefill_fn", None)
            if self.decode_paged else None,
            paged_step_fn=getattr(inst, "paged_step_fn", None)
            if self.decode_paged else None)
        return im, inst

    def build_model(self, broker=None):
        """Model resolution (`ClusterServingHelper` model-type dispatch):
        a ZooModel dir (config.json names the class), or bare weights plus
        `model: {class: ..., config: {...constructor kwargs...}}`.

        With `secure.model_encrypted`, blocks polling the broker for the
        secret/salt the frontend receives at POST /model-secure
        (`ClusterServingHelper.scala:302-310`), then decrypts
        `weights.enc` instead of reading plain weights."""
        import json
        from analytics_zoo_tpu.serving.inference_model import InferenceModel
        if not self.model_path:
            raise ValueError("config has no model.path")
        self._validate_placement()
        try:
            n = int(self.num_replicas)   # accepts YAML-quoted "4" too
        except (TypeError, ValueError):
            n = "auto"                   # None / "auto" (just validated)
        if n in (0, -1):
            n = "auto"
        mesh = None
        if self.mesh_axes is not None:
            from analytics_zoo_tpu.common.mesh import mesh_from_axes
            mesh = mesh_from_axes(self.mesh_axes)
        im = InferenceModel(concurrent_num=self.concurrent_num,
                            num_replicas=n, placement=self.placement,
                            mesh=mesh,
                            compile_cache=self.build_compile_cache())
        secret = salt = None
        if self.model_encrypted:
            if broker is None:
                from analytics_zoo_tpu.serving.broker import connect_broker
                broker = connect_broker(self.broker_url)
            if not self.scrub_secret:
                import logging
                logging.getLogger(__name__).warning(
                    "serving an encrypted model with secure.scrub_secret "
                    "off: the secret/salt stay readable on the broker for "
                    "restarts/replicas — any broker client can read them. "
                    "Set secure.scrub_secret: true for one-shot delivery.")
            secret, salt = wait_model_secret(broker, self.secret_timeout_s,
                                             scrub=self.scrub_secret)

        cfg_json = os.path.join(self.model_path, "config.json")
        if os.path.exists(cfg_json):
            if self.model_encrypted:
                with open(cfg_json) as fh:
                    blob = json.load(fh)
                cls = _find_model_class(blob["class"])
                inst = cls(**blob.get("config", {}))
                return im.load_keras_encrypted(
                    inst, os.path.join(self.model_path, "weights.enc"),
                    secret, salt)
            with open(cfg_json) as fh:
                cls_name = json.load(fh)["class"]
            cls = _find_model_class(cls_name)
            return im.load_zoo_model(cls, self.model_path,
                                     quantize=self.model_quantize)
        if self.model_class:
            cls = _find_model_class(self.model_class)
            kwargs = (self.extra.get("model", {}) or {}).get("config") or {}
            inst = cls(**kwargs)
            if self.model_encrypted:
                return im.load_keras_encrypted(
                    inst, os.path.join(self.model_path, "weights.enc"),
                    secret, salt)
            int8_artifact = os.path.join(self.model_path, "weights_int8.npz")
            if os.path.exists(int8_artifact):
                # pre-quantized artifact beside the arch config: serve it
                # directly (serving/quantization.save_quantized output)
                return im.load_quantized(inst, int8_artifact)
            inst.model.load_weights(os.path.join(self.model_path, "weights"))
            return im.load_keras(inst, quantize=self.model_quantize)
        raise ValueError(
            f"{self.model_path} is not a saved ZooModel directory "
            "(no config.json) and no model.class was given")


def _parse_bytes(raw) -> Optional[int]:
    """Byte counts from YAML: a plain int, or a "512K"/"128M"/"2G"
    string. Returns None for None; bad spellings raise at load time."""
    if raw is None:
        return None
    if isinstance(raw, bool):
        raise ValueError(f"byte count {raw!r} must be a number, "
                         'or a "512M"-style string')
    if isinstance(raw, int):
        return raw
    if isinstance(raw, float) and raw.is_integer():
        return int(raw)
    if isinstance(raw, str):
        s = raw.strip().upper()
        mult = {"K": 1 << 10, "M": 1 << 20, "G": 1 << 30}.get(s[-1:])
        try:
            if mult is not None:
                return int(float(s[:-1]) * mult)
            return int(s)
        except ValueError:
            pass
    raise ValueError(f"cannot parse byte count {raw!r} "
                     '(use an int, or "512K"/"128M"/"2G")')


def _parse_mesh_axes(raw) -> Optional[Dict[str, int]]:
    """Mesh factorization from config: a YAML map ``{data: 1, fsdp: 2,
    tensor: 4}`` or (bare-parser / CLI friendly) one "data=1,fsdp=2,
    tensor=4" string. Axis-name validation happens in
    `_validate_placement` (one vocabulary, one error site)."""
    if raw is None:
        return None
    if isinstance(raw, str):
        out: Dict[str, int] = {}
        for part in raw.split(","):
            part = part.strip()
            if not part:
                continue
            name, sep, value = part.partition("=")
            if not sep:
                raise ValueError(
                    f"params.mesh entry {part!r} must be axis=size "
                    '(e.g. "data=1,fsdp=2,tensor=4")')
            try:
                out[name.strip()] = int(value)
            except ValueError:
                raise ValueError(
                    f"params.mesh size {value!r} for axis "
                    f"{name.strip()!r} must be an integer") from None
        return out or None
    if isinstance(raw, dict):
        try:
            return {str(k): int(v) for k, v in raw.items()} or None
        except (TypeError, ValueError):
            raise ValueError(
                f"params.mesh sizes must be integers, got {raw!r}"
            ) from None
    raise ValueError(
        f"params.mesh={raw!r} must be a map of axis: size entries or "
        'one "data=1,fsdp=2,tensor=4" string')


def _parse_tiers(raw) -> Optional[list]:
    """Priority tiers from config, lowest first: a YAML list of names,
    or (bare-parser friendly) one comma-joined string "batch,standard,
    premium"."""
    if raw is None:
        return None
    if isinstance(raw, str):
        return [p.strip() for p in raw.split(",") if p.strip()] or None
    return [str(t) for t in raw] or None


def _parse_warmup_shapes(raw) -> Optional[list]:
    """Per-record warmup shapes from config: a YAML list of int lists or
    "32x32x3" strings, or (bare-parser friendly) one comma-joined string
    like "32x32x3,224x224x3"; "scalar" names the 0-d record shape ()."""
    def one(part: str) -> tuple:
        part = part.strip()
        return () if part == "scalar" else \
            tuple(int(d) for d in part.split("x"))

    if raw is None:
        return None
    if isinstance(raw, str):
        return [one(p) for p in raw.split(",") if p.strip()] or None
    if raw and all(isinstance(s, int) for s in raw):
        # flat int list `warmup_shapes: [32, 32, 3]` = ONE record shape
        return [tuple(int(d) for d in raw)]

    def elem(s) -> tuple:
        if isinstance(s, str):
            return one(s)
        if isinstance(s, int):
            raise ValueError(
                "warmup_shapes mixes bare ints with shapes — write one "
                'shape per element, e.g. [[32], [64, 64]] or "32,64x64"')
        return tuple(int(d) for d in s)

    return [elem(s) for s in raw] or None


def wait_model_secret(broker, timeout_s: float = 60.0,
                      poll_s: float = 0.2, scrub: bool = False):
    """Block until the frontend posts the model secret/salt to the broker
    (`ClusterServingHelper.scala:302-310` jedis.hget polling loop).

    The reference leaves the secret readable on the broker so serving
    restarts and extra replicas can pick it up without a fresh
    POST /model-secure; that is the default here too. Pass ``scrub=True``
    (config: ``secure.scrub_secret``) to delete it after the first read —
    then every serving (re)start needs the operator to re-POST."""
    import time as _time
    from analytics_zoo_tpu.serving.http_frontend import (
        MODEL_SECURED_KEY, MODEL_SECURED_SALT, MODEL_SECURED_SECRET)
    deadline = _time.time() + timeout_s
    while _time.time() < deadline:
        secret = broker.hget(MODEL_SECURED_KEY, MODEL_SECURED_SECRET)
        salt = broker.hget(MODEL_SECURED_KEY, MODEL_SECURED_SALT)
        if secret and salt:
            if scrub:
                broker.hdel(MODEL_SECURED_KEY, MODEL_SECURED_SECRET)
                broker.hdel(MODEL_SECURED_KEY, MODEL_SECURED_SALT)
            return secret, salt
        _time.sleep(poll_s)
    raise TimeoutError(
        f"No model secret/salt appeared on the broker within {timeout_s}s; "
        "POST secret=...&salt=... to the frontend's /model-secure")


def _find_model_class(name: str):
    from analytics_zoo_tpu.models import (anomalydetection, bert, generative,
                                          image, recommendation, seq2seq,
                                          textclassification, textmatching)
    for mod in (recommendation, anomalydetection, textclassification,
                textmatching, seq2seq, image, bert, generative):
        if hasattr(mod, name):
            return getattr(mod, name)
    raise ValueError(f"Unknown model class {name!r}")
