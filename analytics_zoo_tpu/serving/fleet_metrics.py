"""Fleet metrics aggregation: one Prometheus scrape sees every engine.

Monarch-style push-aggregate over the broker substrate (ISSUE 17
tentpole part 3). Engines cannot be scraped individually — they may sit
behind NAT, churn under the autoscaler, or share a host — so each
engine's `FleetMetricsPublisher` periodically publishes its registry as
one JSON blob into the `metrics:<stream>` broker hash (HSET overwrite:
bounded by construction, readable from every gateway replica without
consumer-group coordination, exactly the `engines:<stream>` heartbeat
discipline).

Blobs are **full cumulative snapshots**, not deltas: a restarting
engine's first blob is self-describing, a missed publish is healed by
the next one, and merging needs no per-source history. Histograms ship
their raw log-bucket counts plus geometry so the gateway can merge them
bucket-wise without losing percentile fidelity.

The gateway-side `FleetMetricsAggregator` builds a fresh merged
`MetricsRegistry` per scrape:

- every engine-published series carries an `engine` label (the
  publisher stamps it when absent), so per-engine series coexist;
- counters and histograms additionally roll up into a `scope="fleet"`
  series per label set (engine label stripped): counters summed,
  LogHistograms merged bucket-wise when geometry matches;
- gauges stay engine-labeled (summing levels is meaningless);
- local gateway series whose `engine` label names an engine that also
  published a blob are dropped in favour of the blob (the
  engine-and-gateway-in-one-process deployment would otherwise double
  count);
- `fleet_scrape_age_s{engine=...}` reports staleness from *seq
  progress observed on the aggregator's own monotonic clock* — never a
  cross-host wall-clock comparison (the FleetTracker discipline).
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Any, Callable, Dict, Optional, Set

from analytics_zoo_tpu.observability.registry import (Counter, Gauge,
                                                      Histogram,
                                                      LogHistogram,
                                                      MetricsRegistry,
                                                      _label_key)

logger = logging.getLogger(__name__)

METRICS_KEY_PREFIX = "metrics:"


def metrics_key(stream: str) -> str:
    """Broker hash holding one registry blob per publishing engine."""
    return METRICS_KEY_PREFIX + stream


# -- snapshot/export ---------------------------------------------------------

def registry_blob(registry: MetricsRegistry, engine: Optional[str],
                  seq: int) -> Dict[str, Any]:
    """Full cumulative export of a registry. When `engine` is given,
    every series lacking an `engine` label is stamped with it, so the
    fleet view can attribute and deduplicate per engine."""

    def _stamp(labels: Dict[str, str]) -> Dict[str, str]:
        if engine is not None and "engine" not in labels:
            labels = dict(labels)
            labels["engine"] = engine
        return labels

    counters: Dict[str, Any] = {}
    gauges: Dict[str, Any] = {}
    hists: Dict[str, Any] = {}
    for fam in registry.families():
        if isinstance(fam, Counter):
            counters[fam.name] = {
                "help": fam.description,
                "series": [[_stamp(s["labels"]), s["value"]]
                           for s in fam._series_snapshot()]}
        elif isinstance(fam, Gauge):
            gauges[fam.name] = {
                "help": fam.description,
                "series": [[_stamp(s["labels"]), s["value"]]
                           for s in fam._series_snapshot()]}
        elif isinstance(fam, Histogram):
            series = []
            for key in fam.label_keys():
                with fam._lock:
                    h = fam._series.get(key)
                    if h is None:
                        continue
                    sd = {"base": h.base, "growth": h.growth,
                          "n": h.n_buckets,
                          "counts": {str(i): c
                                     for i, c in enumerate(h.counts)
                                     if c},
                          "count": h.count, "total": h.total,
                          "vmin": h.vmin if h.count else 0.0,
                          "vmax": h.vmax}
                series.append([_stamp(dict(key)), sd])
            hists[fam.name] = {"help": fam.description, "series": series}
    return {"engine": engine, "seq": seq, "wall": time.time(),
            "counters": counters, "gauges": gauges, "hists": hists}


def _hist_from_blob(sd: Dict[str, Any]) -> Optional[LogHistogram]:
    try:
        h = LogHistogram(base=float(sd["base"]),
                         growth=float(sd["growth"]),
                         n_buckets=int(sd["n"]))
        for i, c in (sd.get("counts") or {}).items():
            h.counts[int(i)] = int(c)
        h.count = int(sd.get("count", 0))
        h.total = float(sd.get("total", 0.0))
        h.vmin = float(sd.get("vmin", 0.0)) if h.count else float("inf")
        h.vmax = float(sd.get("vmax", 0.0))
        return h
    except (KeyError, TypeError, ValueError, IndexError):
        return None


def _merge_hist(dst: LogHistogram, src: LogHistogram) -> bool:
    """Bucket-wise merge; refuses on geometry mismatch (adding counts
    across different bucket edges would fabricate percentiles)."""
    if (dst.base, dst.growth, dst.n_buckets) != \
            (src.base, src.growth, src.n_buckets):
        return False
    for i, c in enumerate(src.counts):
        if c:
            dst.counts[i] += c
    dst.count += src.count
    dst.total += src.total
    dst.vmin = min(dst.vmin, src.vmin)
    dst.vmax = max(dst.vmax, src.vmax)
    return True


# -- publisher (engine side) -------------------------------------------------

class FleetMetricsPublisher:
    """Background thread publishing this engine's registry snapshot into
    the fleet metrics hash every `interval_s`."""

    def __init__(self, broker, stream: str, engine: str,
                 registry: MetricsRegistry, interval_s: float = 2.0):
        self.broker = broker
        self.key = metrics_key(stream)
        self.engine = engine
        self.registry = registry
        self.interval_s = float(interval_s)
        self._seq = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._down = False

    def publish_once(self) -> bool:
        self._seq += 1
        blob = registry_blob(self.registry, self.engine, self._seq)
        try:
            self.broker.hset(self.key, self.engine, json.dumps(blob))
        except Exception as e:  # noqa: BLE001 — broker outage: warn
            if not self._down:  # once, keep serving, retry next tick
                logger.warning("fleet metrics %s: publish failed (%s); "
                               "retrying each interval", self.engine, e)
                self._down = True
            return False
        if self._down:
            logger.info("fleet metrics %s: broker back, publishing "
                        "resumed", self.engine)
            self._down = False
        return True

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.publish_once()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="serving-fleet-metrics", daemon=True)
        self._thread.start()

    def stop(self, flush: bool = True) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
        if flush:
            self.publish_once()


# -- aggregator (gateway side) -----------------------------------------------

class FleetMetricsAggregator:
    """Merges engine blobs (plus the gateway's own registry) into one
    scrape-ready registry. `alive_fn` (typically the gateway
    FleetTracker's alive set) filters dead engines' stale blobs out of
    the merge; when it returns None the filter degrades open."""

    def __init__(self, broker, stream: str, registry: MetricsRegistry,
                 alive_fn: Optional[Callable[[], Optional[Set[str]]]]
                 = None):
        self.broker = broker
        self.key = metrics_key(stream)
        self.registry = registry           # gateway-local registry
        self.alive_fn = alive_fn
        self._age_gauge = registry.gauge(
            "fleet_scrape_age_s",
            "seconds since each engine's fleet metrics blob last made "
            "seq progress, on this gateway's monotonic clock")
        # engine -> (last_seq, monotonic time the seq last advanced)
        self._progress: Dict[str, Any] = {}
        self._last: Dict[str, Dict[str, Any]] = {}

    # -- fetch -------------------------------------------------------------
    def _fetch(self) -> Dict[str, Dict[str, Any]]:
        try:
            raw = self.broker.hgetall(self.key) or {}
        except Exception as e:  # noqa: BLE001 — a scrape during a
            logger.warning("fleet metrics: hgetall failed: %s", e)
            return self._last   # broker blip serves the last view
        blobs: Dict[str, Dict[str, Any]] = {}
        now = time.monotonic()
        for eng, blob in raw.items():
            try:
                d = json.loads(blob)
            except (TypeError, ValueError):
                continue
            if not isinstance(d, dict):
                continue
            eng = str(eng)
            blobs[eng] = d
            seq = d.get("seq", 0)
            prev = self._progress.get(eng)
            if prev is None or prev[0] != seq:
                self._progress[eng] = (seq, now)
        for eng in blobs:
            self._age_gauge.set(now - self._progress[eng][1],
                                engine=eng)
        self._last = blobs
        return blobs

    # -- merge -------------------------------------------------------------
    def merged(self, local: Optional[MetricsRegistry] = None
               ) -> MetricsRegistry:
        """A fresh registry holding every alive engine's series plus
        the local registry's, with `scope="fleet"` rollups for counters
        and histograms."""
        blobs = self._fetch()
        alive = self.alive_fn() if self.alive_fn is not None else None
        if alive is not None:
            blobs = {e: b for e, b in blobs.items() if e in alive}
        published = set(blobs)
        merged = MetricsRegistry()
        if local is None:
            local = self.registry
        sources = [(True, registry_blob(local, None, 0))]
        sources.extend((False, b) for b in blobs.values())
        for is_local, blob in sources:
            for name, fam in (blob.get("counters") or {}).items():
                self._merge_counter(merged, name, fam, is_local,
                                    published)
            for name, fam in (blob.get("gauges") or {}).items():
                self._merge_gauge(merged, name, fam, is_local, published)
            for name, fam in (blob.get("hists") or {}).items():
                self._merge_hist_family(merged, name, fam, is_local,
                                        published)
        return merged

    @staticmethod
    def _skip_local(is_local: bool, labels: Dict[str, str],
                    published: Set[str]) -> bool:
        # blob wins over the local registry for engines that publish —
        # the engine-plus-gateway single-process deployment would
        # otherwise count its own series twice
        return is_local and labels.get("engine") in published

    def _merge_counter(self, merged, name, fam, is_local, published):
        try:
            c = merged.counter(name, fam.get("help", ""))
        except ValueError:
            return
        for labels, value in fam.get("series") or []:
            labels = dict(labels)
            if self._skip_local(is_local, labels, published):
                continue
            try:
                c.inc(float(value), **labels)
            except (TypeError, ValueError):
                continue
            if not is_local:
                roll = {k: v for k, v in labels.items() if k != "engine"}
                c.inc(float(value), scope="fleet", **roll)

    def _merge_gauge(self, merged, name, fam, is_local, published):
        try:
            g = merged.gauge(name, fam.get("help", ""))
        except ValueError:
            return
        for labels, value in fam.get("series") or []:
            labels = dict(labels)
            if self._skip_local(is_local, labels, published):
                continue
            try:
                g.set(float(value), **labels)
            except (TypeError, ValueError):
                continue

    def _merge_hist_family(self, merged, name, fam, is_local, published):
        try:
            hfam = merged.histogram(name, fam.get("help", ""))
        except ValueError:
            return
        for labels, sd in fam.get("series") or []:
            labels = dict(labels)
            if self._skip_local(is_local, labels, published):
                continue
            lh = _hist_from_blob(sd)
            if lh is None:
                continue
            self._insert_hist(hfam, labels, lh)
            if not is_local:
                roll = {k: v for k, v in labels.items() if k != "engine"}
                roll["scope"] = "fleet"
                self._insert_hist(hfam, roll, _hist_from_blob(sd))

    @staticmethod
    def _insert_hist(hfam: Histogram, labels: Dict[str, str],
                     lh: Optional[LogHistogram]) -> None:
        if lh is None:
            return
        key = _label_key(labels)
        with hfam._lock:
            existing = hfam._series.get(key)
            if existing is None:
                hfam._series[key] = lh
            elif not _merge_hist(existing, lh):
                logger.warning(
                    "fleet metrics: histogram %s%s geometry mismatch — "
                    "series skipped from the merge", hfam.name, labels)

    # -- views -------------------------------------------------------------
    def summary(self) -> Dict[str, Any]:
        blobs = self._fetch()
        now = time.monotonic()
        alive = self.alive_fn() if self.alive_fn is not None else None
        return {
            "published": len(blobs),
            "engines": {
                eng: {"seq": b.get("seq", 0),
                      "age_s": round(now - self._progress[eng][1], 3),
                      "alive": (None if alive is None
                                else eng in alive)}
                for eng, b in sorted(blobs.items())},
        }
