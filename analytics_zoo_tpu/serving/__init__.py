"""Inference service layer — the Cluster Serving analogue (SURVEY §2.9).

The reference runs a Flink job between Redis streams and a JNI-wrapped model
(`serving/ClusterServing.scala:70`); here a host-side serving loop batches
queue records into shape-bucketed jit'd forwards on the TPU. The client
protocol surface (`InputQueue`/`OutputQueue`, `pyzoo/zoo/serving/client.py`)
is preserved; the transport is a pluggable broker (in-memory, TCP, or Redis
when available) instead of a hard Redis dependency.
"""

from analytics_zoo_tpu.serving.inference_model import InferenceModel  # noqa: F401
from analytics_zoo_tpu.serving.broker import (  # noqa: F401
    MemoryBroker, TCPBroker, TCPBrokerServer, connect_broker)
from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue  # noqa: F401
from analytics_zoo_tpu.serving.server import ClusterServing  # noqa: F401
from analytics_zoo_tpu.serving.timer import Timer  # noqa: F401
from analytics_zoo_tpu.serving.http_frontend import FrontEnd  # noqa: F401
