"""Inference service layer — the Cluster Serving analogue (SURVEY §2.9).

The reference runs a Flink job between Redis streams and a JNI-wrapped model
(`serving/ClusterServing.scala:70`); here a host-side serving loop batches
queue records into shape-bucketed jit'd forwards on the TPU. The client
protocol surface (`InputQueue`/`OutputQueue`, `pyzoo/zoo/serving/client.py`)
is preserved; the transport is a pluggable broker (in-memory, TCP, or Redis
when available) instead of a hard Redis dependency.

Submodule attributes resolve lazily (PEP 562): `ZooConfig()` holds a
`ServingConfig` and must not drag the broker/server/HTTP stack into every
training-only import.
"""

_EXPORTS = {
    "InferenceModel": "analytics_zoo_tpu.serving.inference_model",
    "MemoryBroker": "analytics_zoo_tpu.serving.broker",
    "TCPBroker": "analytics_zoo_tpu.serving.broker",
    "TCPBrokerServer": "analytics_zoo_tpu.serving.broker",
    "connect_broker": "analytics_zoo_tpu.serving.broker",
    "InputQueue": "analytics_zoo_tpu.serving.client",
    "OutputQueue": "analytics_zoo_tpu.serving.client",
    "ClusterServing": "analytics_zoo_tpu.serving.server",
    "RedisBroker": "analytics_zoo_tpu.serving.broker",
    "MiniRedisServer": "analytics_zoo_tpu.serving.redis_server",
    "Timer": "analytics_zoo_tpu.serving.timer",
    "FrontEnd": "analytics_zoo_tpu.serving.http_frontend",
    "ServingConfig": "analytics_zoo_tpu.serving.config",
    "BackoffPolicy": "analytics_zoo_tpu.serving.breaker",
    "CircuitBreaker": "analytics_zoo_tpu.serving.breaker",
    "ResilientBroker": "analytics_zoo_tpu.serving.breaker",
    "ReplicaSupervisor": "analytics_zoo_tpu.serving.supervisor",
    "FleetTracker": "analytics_zoo_tpu.serving.fleet",
    "HeartbeatPublisher": "analytics_zoo_tpu.serving.fleet",
    "engines_key": "analytics_zoo_tpu.serving.fleet",
}

__all__ = list(_EXPORTS)


def __getattr__(name):
    if name in _EXPORTS:
        import importlib
        mod = importlib.import_module(_EXPORTS[name])
        value = getattr(mod, name)
        globals()[name] = value
        return value
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(__all__))
