"""Int8 post-training quantization for inference.

Parity target: the reference's int8 inference engine
(`zoo/src/main/scala/com/intel/analytics/zoo/pipeline/inference/
OpenVinoInferenceSupportive.scala:34-57` — `loadOpenVinoIRInt8*`, VNNI;
validated by `zoo/src/test/.../inference/OpenVINOInt8Suite.scala:301`).
TPU-native redesign: instead of a separate IR + runtime, the SAME keras
param pytree is rewritten in place — weight leaves become symmetric
per-output-channel int8 (`kernel_q` + f32 `kernel_scale`) and the layer's
own `call` dispatches to an int8 MXU path (`lax.dot_general` /
`conv_general_dilated` with int8 operands and `preferred_element_type=
int32`), with dynamic per-tensor activation quantization. Embedding
tables quantize per row (gather → dequantize only the touched rows).

Entry points:
- `quantize_model_params(model, params)` → quantized pytree for any
  Sequential/Model/ZooModel built from the stock layer library.
- `InferenceModel.load_keras(..., quantize="int8")` (serving façade).
- `write_int8_sidecar(run_dir, version, model, ...)` /
  `load_int8_sidecar(...)` — the post-training quantization pass as a
  CHECKPOINT SIDECAR (ISSUE 12): per-output-channel scales + int8
  weights persisted beside `model.<version>` so serving loads the
  pre-calibrated artifact instead of re-quantizing per restart
  (producers: `fit_keras(int8_sidecar=True)` and
  `scripts/quantize_checkpoint.py`).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

_EPS = 1e-12


# ---------------------------------------------------------------------------
# int8 compute paths (used by the layers' quantized dispatch)
# ---------------------------------------------------------------------------
def quantize_activations(x):
    """Dynamic symmetric per-tensor activation quantization: scalar scale
    from the batch's abs-max (data-dependent scalars are jit-safe)."""
    sx = jnp.maximum(jnp.max(jnp.abs(x)) / 127.0, _EPS)
    x_q = jnp.clip(jnp.round(x / sx), -127, 127).astype(jnp.int8)
    return x_q, sx


def int8_matmul(x, w_q, w_scale):
    """y ≈ x @ (w_q * w_scale): int8×int8→int32 on the MXU, dequantized
    with the product of the activation and per-channel weight scales."""
    x_q, sx = quantize_activations(x)
    y = jax.lax.dot_general(
        x_q, w_q, (((x_q.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    return y.astype(jnp.float32) * (sx * w_scale)


def int8_conv(x, w_q, w_scale, **conv_kwargs):
    """Weight-only int8 for convolutions: int8 weights dequantize to bf16
    at use (4× fewer weight bytes from HBM) and the conv itself runs on
    the bf16 MXU path. Measured on v5e: XLA's true int8×int8 conv
    lowering runs ~1.6× SLOWER than bf16 (no VNNI-style win to copy —
    `OpenVinoInferenceSupportive.scala:34` is an avx512-vnni play), while
    weight-only keeps full conv throughput; activations stay unquantized
    so conv accuracy is better than the Dense path's."""
    w = w_q.astype(jnp.bfloat16) * w_scale.astype(jnp.bfloat16)
    # same invariant as the f32 conv path (_match_param_dtype): float
    # inputs follow the weights; integer inputs error loudly rather than
    # silently serving on unscaled 0-255 pixel values
    if jnp.issubdtype(x.dtype, jnp.floating):
        x = x.astype(jnp.bfloat16)
    y = jax.lax.conv_general_dilated(x, w, **conv_kwargs)
    return y.astype(jnp.float32)


def dequantize_rows(table_q, scale, ids):
    """Embedding path: gather int8 rows, dequantize only what was read."""
    return table_q[ids].astype(jnp.float32) * scale[ids][..., None]


def maybe_int8_matmul(x, params, key: str):
    """`x @ params[key] `, taking the int8 MXU path when the quantized
    form (`<key>_q` + `<key>_scale`) is present — the dispatch hook for
    raw-matmul layers (transformer blocks, BERT task heads) that do not
    go through the keras Dense layer."""
    if key + "_q" in params:
        return int8_matmul(x, params[key + "_q"], params[key + "_scale"])
    return x @ params[key]


# raw (non-Dense-layer) matmul kernels that have a maybe_int8_matmul
# call site; ONLY these are rewritten — blanket *_kernel matching would
# break layers that read their kernels directly (e.g. Highway's
# transform_kernel)
_RAW_INT8_KERNELS = frozenset({
    "qkv_kernel", "out_kernel", "ffn_in_kernel", "ffn_out_kernel",
    "pooler_kernel", "cls_kernel", "ner_kernel", "qa_kernel",
})


def _quantize_raw_kernels(tree):
    """Recursively rewrite known raw matmul kernels ([in, out] leaves) in
    a param tree — reaches inside composite layers (transformer blocks)
    the layer-walk cannot see."""
    if not isinstance(tree, dict):
        return tree
    out: Dict[str, Any] = {}
    for k, v in tree.items():
        if k in _RAW_INT8_KERNELS and not isinstance(v, dict) \
                and np.ndim(v) == 2:
            q, scale = _quantize_tensor(v, (0,))
            out[k + "_q"], out[k + "_scale"] = q, scale
        elif k in _RAW_INT8_KERNELS and not isinstance(v, dict) \
                and np.ndim(v) == 3:
            # stacked encoder (`BERT(stacked=True)`): [L, in, out] — the
            # scan body slices dim 0, so quantize per (layer, out_channel)
            # and the sliced leaves ([in, out] int8 + [out] scale) hit
            # the same int8_matmul path as the unstacked form
            q, scale = _quantize_tensor(v, (1,))
            out[k + "_q"], out[k + "_scale"] = q, scale
        else:
            out[k] = _quantize_raw_kernels(v)
    return out


# ---------------------------------------------------------------------------
# param-tree rewrite
# ---------------------------------------------------------------------------
def _quantize_tensor(w, reduce_axes) -> Dict[str, Any]:
    """Symmetric int8 over `reduce_axes`; scale keeps the other axes."""
    w = np.asarray(w, np.float32)
    amax = np.maximum(np.abs(w).max(axis=reduce_axes, keepdims=True), _EPS)
    scale = (amax / 127.0).astype(np.float32)
    q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
    return q, np.squeeze(scale, axis=reduce_axes)


def _iter_layers(model):
    layers = getattr(model, "layers", None)
    if layers is None:
        layers = getattr(model, "_layers", None)
    return layers or []


def quantize_model_params(model, params) -> Dict[str, Any]:
    """Rewrite a built model's param pytree with int8 weights for every
    Dense / conv-family / Embedding layer (recursing into nested
    Sequential/Model containers). Layers with no int8 path (BatchNorm,
    recurrent cells, LayerNorm, ...) keep f32 — they are bandwidth-thin
    next to the matmuls."""
    from analytics_zoo_tpu.keras import transformer as tfm
    from analytics_zoo_tpu.keras.engine import Model, Sequential
    from analytics_zoo_tpu.keras.layers import Dense, Embedding, _ConvND

    out = dict(params)
    # BERT task models carry the encoder + raw head kernels with no
    # layer list (`models/bert._BERTTask._ordered_layers` is empty by
    # design): rewrite their subtrees structurally, not by global name
    # matching — a user layer with a same-named 2-D param elsewhere must
    # never be touched.
    from analytics_zoo_tpu.models.bert import _BERTTask
    if isinstance(model, _BERTTask):
        out[model.bert.name] = _quantize_raw_kernels(
            out.get(model.bert.name, {}))
        for head in ("cls_kernel", "ner_kernel", "qa_kernel"):
            if head in out and not isinstance(out[head], dict) \
                    and np.ndim(out[head]) == 2:
                q, scale = _quantize_tensor(out[head], (0,))
                del out[head]
                out[head + "_q"], out[head + "_scale"] = q, scale
    for layer in _iter_layers(model):
        sub = out.get(layer.name)
        if sub is None:
            continue
        if isinstance(layer, (Sequential, Model)):
            out[layer.name] = quantize_model_params(layer, sub)
        elif isinstance(layer, (tfm.MultiHeadSelfAttention,
                                tfm.TransformerEncoderBlock,
                                tfm.TransformerLayer, tfm.BERT)):
            out[layer.name] = _quantize_raw_kernels(sub)
        elif isinstance(layer, Dense):
            q, scale = _quantize_tensor(sub["kernel"], (0,))
            new = {k: v for k, v in sub.items() if k != "kernel"}
            new["kernel_q"], new["kernel_scale"] = q, scale
            out[layer.name] = new
        elif isinstance(layer, _ConvND):
            k = np.asarray(sub["kernel"])
            q, scale = _quantize_tensor(k, tuple(range(k.ndim - 1)))
            new = {kk: v for kk, v in sub.items() if kk != "kernel"}
            new["kernel_q"], new["kernel_scale"] = q, scale
            out[layer.name] = new
        elif isinstance(layer, Embedding):
            q, scale = _quantize_tensor(sub["embeddings"], (1,))
            out[layer.name] = {"embeddings_q": q,
                               "embeddings_scale": scale}
    return out


# ---------------------------------------------------------------------------
# int8 artifacts — quantize once, ship the small file
# ---------------------------------------------------------------------------
def save_quantized(model, path: str, params=None) -> Dict[str, Any]:
    """Quantize and persist as an int8 artifact: the counterpart of the
    reference SHIPPING int8 OpenVINO IR files rather than quantizing at
    every load (`OpenVinoInferenceSupportive.scala:34`). ~4× smaller
    than the f32 checkpoint; loads into a FRESH architecture instance
    via `load_quantized`. Reuses the engine's save_weights artifact
    protocol (npz + structure + layer-order sidecars)."""
    from analytics_zoo_tpu.models.common import ZooModel
    net = model.model if isinstance(model, ZooModel) else model
    if params is None:
        params = net.params
    if params is None:
        raise ValueError("Model has no parameters; fit or load first")
    q = quantize_model_params(net, jax.device_get(params))
    net.save_weights(path, params=q)
    return q


def sidecar_path(run_dir: str, version: int) -> str:
    """Canonical name of a checkpoint's int8 sidecar artifact (the
    `.npz` + `.structure.json` pair `learn/checkpoint.save_pytree`
    writes under this stem)."""
    import os
    return os.path.join(run_dir, f"model.{version}.int8")


def write_int8_sidecar(run_dir: str, version: int, model,
                       params=None) -> str:
    """The post-training quantization pass, persisted: calibrate
    symmetric per-output-channel scales from the checkpointed weights
    and write the rewritten (int8 + scale) pytree as a sidecar beside
    `model.<version>` — same atomic write-then-rename + CRC discipline
    as the checkpoint itself, so a torn sidecar is invisible and
    serving falls back to quantize-at-load. Returns the sidecar stem
    path. `params` defaults to the checkpoint's own params (loaded from
    disk), so the sidecar always describes exactly the version it sits
    beside."""
    from analytics_zoo_tpu.learn.checkpoint import (load_pytree,
                                                    save_pytree)
    from analytics_zoo_tpu.models.common import ZooModel
    net = model.model if isinstance(model, ZooModel) else model
    if params is None:
        import os
        params = load_pytree(os.path.join(run_dir, f"model.{version}"))
        # an offline pass (scripts/quantize_checkpoint.py) runs in a
        # fresh process whose auto-numbered layer names differ from the
        # checkpointing process's — remap onto this instance before the
        # layer walk (the trainer hook passes its own live params,
        # whose names already match)
        remap = getattr(net, "_remap_loaded", None)
        if remap is not None:
            params = remap(params)
    q = quantize_model_params(net, jax.device_get(params))
    path = sidecar_path(run_dir, version)
    save_pytree(path, q)
    try:
        from analytics_zoo_tpu.observability.registry import get_registry
        get_registry().counter(
            "quantized_checkpoints_total",
            "int8 checkpoint sidecars written by the post-training "
            "quantization pass").inc()
    except Exception:  # noqa: BLE001 — telemetry only
        pass
    return path


def load_int8_sidecar(run_dir: str, version: int):
    """The quantized pytree a `write_int8_sidecar` pass persisted, or
    None when the sidecar is absent or fails its CRC (the caller falls
    back to quantize-at-load — a torn sidecar costs a calibration, not
    the serve)."""
    import os

    from analytics_zoo_tpu.learn.checkpoint import (CorruptCheckpointError,
                                                    load_pytree)
    path = sidecar_path(run_dir, version)
    if not os.path.exists(path + ".npz"):
        return None
    try:
        return load_pytree(path)
    except (OSError, ValueError, KeyError, CorruptCheckpointError):
        return None


def load_quantized(model, path: str):
    """Load an int8 artifact onto `model`'s architecture → param pytree
    (remapped to this instance's layer names; the model itself is left
    untouched). Feed to `InferenceModel.load_keras(model, params=...)`
    or `model.apply` directly — layers dispatch on the quantized keys."""
    from analytics_zoo_tpu.models.common import ZooModel
    net = model.model if isinstance(model, ZooModel) else model
    return net.load_weights_tree(path)
