"""Paged KV memory — block pool, per-sequence block tables, prefix cache.

ISSUE 19: the PR 18 `KVSlotPool` reserves a full ``max_kv_len`` stripe
per sequence, so a 10-token request on a 128-position pool idles ~90%
of its bytes and concurrent capacity is capped at ``pool_bytes /
stripe_bytes`` however short the traffic runs. This module is the
PagedAttention discipline (vLLM, Kwon et al. 2023) on the same rails:

- ``KVBlockPool`` — the KV cache is ONE device buffer set of shape
  ``[num_blocks, heads, block_len, head_dim]`` per layer (built by the
  model's ``init_kv_blocks``). Sequences own an ordered list of block
  ids (their *block table*) and grow block-by-block; capacity is
  bounded by live TOKENS, not live sequences × max length. Blocks are
  ref-counted so the prefix cache can share one physical block across
  every sequence that opens with the same tokens. Block 0 is a
  reserved scratch row: dead decode lanes write their (discarded)
  KV there so a fixed-shape step executable never corrupts live
  blocks.
- ``PrefixCache`` — a trie keyed on token-id chunks of one block each
  (RadixAttention's structure at block granularity): a finished
  prefill publishes its FULL prompt blocks under their token path, and
  a new prompt walks the trie and adopts every matching block
  copy-free — that whole span of prefill compute is skipped, which is
  the TTFT win on instruction-prefix-heavy traffic. The cache holds
  one reference per published block; eviction is LRU over trie leaves
  and only actually frees a block when its refcount reaches zero (a
  block adopted by a live sequence survives eviction from the trie
  untouched).

Both structures are bookkeeping only: the device buffers are threaded
functionally through prefill/step calls by the engine (`decode.py`),
exactly like the slot pool before them.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple


class KVBlockPool:
    """Fixed pool of ref-counted KV blocks over ONE device buffer set.

    ``init_kv_blocks(num_blocks, block_len)`` builds the per-layer
    ``{"k","v"}: [num_blocks, heads, block_len, head_dim]`` pytree held
    in ``self.kv`` (rebound by the engine after every call, like the
    slot pool). The pool itself only tracks which blocks are leased and
    how many owners each has; block 0 is reserved as the scratch row
    for dead decode lanes and is never allocated."""

    SCRATCH = 0

    def __init__(self, init_kv_blocks: Callable[[int, int], Any],
                 num_blocks: int, block_len: int, registry=None,
                 labels: Optional[Dict[str, str]] = None):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (scratch + one usable block), "
                f"got {num_blocks}")
        if block_len < 1:
            raise ValueError(f"block_len must be >= 1, got {block_len}")
        self.num_blocks = int(num_blocks)
        self.block_len = int(block_len)
        self.kv = init_kv_blocks(self.num_blocks, self.block_len)
        # allocate low ids first (stable layouts in tests/benchmarks)
        self._free = list(range(self.num_blocks - 1, 0, -1))
        self._ref: Dict[int, int] = {}
        self._lock = threading.Lock()
        self._labels = dict(labels or {})
        if registry is None:
            from analytics_zoo_tpu.observability.registry import get_registry
            registry = get_registry()
        self._gauge = registry.gauge(
            "serving_kv_blocks_in_use",
            "KV-cache blocks currently referenced by in-flight sequences "
            "or the prefix cache (out of the engine's fixed block pool) "
            "— the paged decode engine's capacity signal")
        self._gauge.set(0.0, **self._labels)

    # -- allocation --------------------------------------------------------
    def alloc(self) -> Optional[int]:
        """Lease one free block (refcount 1), or None when exhausted —
        the caller decides whether to evict from the prefix cache and
        retry or to stop admitting."""
        with self._lock:
            if not self._free:
                return None
            block = self._free.pop()
            self._ref[block] = 1
            self._gauge.set(self.num_blocks - 1 - len(self._free),
                            **self._labels)
            return block

    def retain(self, block: int) -> None:
        """Add one owner to a live block (prefix-cache publish/adopt)."""
        with self._lock:
            if self._ref.get(block, 0) < 1:
                raise ValueError(f"retain of unleased block {block}")
            self._ref[block] += 1

    def release(self, block: int) -> None:
        """Drop one owner; the block returns to the free list only at
        refcount zero (shared prefix blocks survive their adopters)."""
        with self._lock:
            refs = self._ref.get(block, 0)
            if refs < 1:
                raise ValueError(f"release of unleased block {block}")
            if refs == 1:
                del self._ref[block]
                self._free.append(block)
                self._gauge.set(self.num_blocks - 1 - len(self._free),
                                **self._labels)
            else:
                self._ref[block] = refs - 1

    def refcount(self, block: int) -> int:
        with self._lock:
            return self._ref.get(block, 0)

    @property
    def capacity(self) -> int:
        """Usable blocks (the scratch row is not capacity)."""
        return self.num_blocks - 1

    @property
    def free_count(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def in_use(self) -> int:
        with self._lock:
            return self.num_blocks - 1 - len(self._free)


class _TrieNode:
    __slots__ = ("key", "block", "children", "parent", "last_use")

    def __init__(self, key, block, parent):
        self.key = key
        self.block = block
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_TrieNode"] = {}
        self.last_use = 0


class PrefixCache:
    """Ref-counted shared-prefix block cache over a `KVBlockPool`.

    Keys are tuples of ``block_len`` token ids — one trie edge per full
    prompt block — so a lookup is pure token-id comparison and a hit
    adopts the PHYSICAL blocks an earlier identical prefix already
    computed (copy-free: the adopter only gains references). Only fully
    written blocks are ever published — prompt spans at prefill
    completion, and (ISSUE 20) full CONTEXT spans (prompt ⊕ generated)
    when a sequence is preempted or resumes, since decode writes land
    strictly beyond a full block; a block that could still receive
    writes never enters the trie, so shared blocks are immutable by
    construction.

    Eviction (`evict_for`) is LRU over leaves, preferring blocks whose
    only owner is the cache itself — evicting a block a live sequence
    adopted removes it from future matching but frees no bytes until
    that sequence finishes."""

    def __init__(self, pool: KVBlockPool, registry=None,
                 labels: Optional[Dict[str, str]] = None,
                 max_blocks: Optional[int] = None):
        self.pool = pool
        self.block_len = pool.block_len
        self.max_blocks = int(max_blocks) if max_blocks else None
        self._root = _TrieNode((), None, None)
        self._nodes: List[_TrieNode] = []
        self._clock = 0
        self._lock = threading.Lock()
        labels = dict(labels or {})
        if registry is None:
            from analytics_zoo_tpu.observability.registry import get_registry
            registry = get_registry()
        self._hits = registry.counter(
            "serving_prefix_cache_hits_total",
            "prompts that adopted at least one cached prefix block "
            "(that span of prefill compute was skipped entirely)")
        self._misses = registry.counter(
            "serving_prefix_cache_misses_total",
            "prompts that adopted no cached prefix block and ran full "
            "prefill")
        self._blocks_gauge = registry.gauge(
            "serving_prefix_cache_blocks",
            "KV blocks currently published in the prefix-cache trie")
        self._pressure_evictions = registry.counter(
            "serving_kv_pressure_evictions_total",
            "prefix-cache blocks evicted under allocation pressure "
            "(`evict_for`: the pool ran dry and cold cached prefixes "
            "were dropped to make room for live sequences) — sustained "
            "growth means the block pool is undersized for the offered "
            "load")
        self._labels = labels
        self._blocks_gauge.set(0.0, **labels)

    def __len__(self) -> int:
        with self._lock:
            return len(self._nodes)

    def _key(self, tokens, i: int) -> Tuple[int, ...]:
        bl = self.block_len
        return tuple(int(t) for t in tokens[i * bl:(i + 1) * bl])

    def match(self, tokens: Sequence[int]) -> List[int]:
        """Longest cached prefix of `tokens`, as adopted block ids (one
        pool reference taken per block, owned by the caller). At most
        ``(len(tokens) - 1) // block_len`` blocks match — at least one
        prompt token must remain un-cached so prefill still has a real
        query to produce the first generated token."""
        out: List[int] = []
        with self._lock:
            node = self._root
            for i in range((len(tokens) - 1) // self.block_len):
                child = node.children.get(self._key(tokens, i))
                if child is None:
                    break
                self._clock += 1
                child.last_use = self._clock
                out.append(child.block)
                node = child
            for b in out:
                self.pool.retain(b)
        (self._hits if out else self._misses).inc(**self._labels)
        return out

    def insert(self, tokens: Sequence[int], blocks: Sequence[int]) -> int:
        """Publish a prompt's full blocks under their token path (the
        caller passes exactly its fully-written prompt blocks, in
        order). Existing path nodes are kept (first writer wins — the
        adopters already share them); each NEWLY published block gains
        one cache-owned reference. Returns the number of new nodes."""
        n = min(len(blocks), len(tokens) // self.block_len)
        added = 0
        with self._lock:
            node = self._root
            for i in range(n):
                key = self._key(tokens, i)
                child = node.children.get(key)
                if child is None:
                    if (self.max_blocks is not None
                            and len(self._nodes) >= self.max_blocks
                            and not self._evict_locked(1)):
                        break
                    child = _TrieNode(key, int(blocks[i]), node)
                    self.pool.retain(child.block)
                    node.children[key] = child
                    self._nodes.append(child)
                    added += 1
                self._clock += 1
                child.last_use = self._clock
                node = child
            self._blocks_gauge.set(float(len(self._nodes)), **self._labels)
        return added

    # -- eviction ----------------------------------------------------------
    def _leaves(self) -> List[_TrieNode]:
        return [n for n in self._nodes if not n.children]

    def _drop_locked(self, node: _TrieNode) -> None:
        del node.parent.children[node.key]
        self._nodes.remove(node)
        self.pool.release(node.block)

    def _evict_locked(self, want: int) -> int:
        """Drop up to `want` LRU leaves that would actually free bytes
        (cache is the sole owner); falls back to still-shared leaves
        only when nothing else is evictable, so pressure trims dead
        prefixes before it forgets live ones."""
        evicted = 0
        while evicted < want:
            leaves = self._leaves()
            if not leaves:
                break
            sole = [n for n in leaves if self.pool.refcount(n.block) == 1]
            pick = min(sole or leaves, key=lambda n: n.last_use)
            self._drop_locked(pick)
            evicted += 1
        self._blocks_gauge.set(float(len(self._nodes)), **self._labels)
        return evicted

    def evict_for(self, blocks_needed: int = 1) -> int:
        """Evict LRU sole-owner leaves until the pool has
        `blocks_needed` free blocks or none remain; returns nodes
        dropped. Shared leaves are left alone here — dropping a block a
        live sequence still references frees no bytes now, and it would
        only erase a prefix that is demonstrably hot."""
        dropped = 0
        with self._lock:
            while self.pool.free_count < blocks_needed:
                sole = [n for n in self._leaves()
                        if self.pool.refcount(n.block) == 1]
                if not sole:
                    break
                self._drop_locked(min(sole, key=lambda n: n.last_use))
                dropped += 1
            self._blocks_gauge.set(float(len(self._nodes)), **self._labels)
        if dropped:
            self._pressure_evictions.inc(dropped, **self._labels)
        return dropped
