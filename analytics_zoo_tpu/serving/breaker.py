"""Broker circuit breaker + reconnect backoff (ISSUE 5 tentpole, part 2).

The reference leans on Flink's restart strategy when Redis dies
(`FlinkRedisSource.scala` just throws; the job restarts); our engine's
stage threads must survive a dead broker themselves. Before this layer
the reader retried a dead broker in a hot-ish fixed 1 s loop and the
sink dropped straight to the at-least-once redelivery path. Now every
serving-side broker connection wears:

- a **CircuitBreaker** — closed → open after `failure_threshold`
  consecutive failures (every call fast-fails without touching the
  socket), open → half-open after `reset_timeout_s` (exactly one probe
  call is let through), half-open → closed on probe success / back to
  open on probe failure. State transitions land in the registry
  (`serving_broker_breaker_state` gauge, 0/1/2 =
  closed/open/half-open, plus a transitions counter) and log ONE line
  per transition — not one per failed attempt.
- a **BackoffPolicy** — capped exponential with jitter, used by the
  reader loop between reconnect attempts (replacing the fixed sleep)
  and by the sink's buffered-writeback flush.

`ResilientBroker` wraps any `Broker` with the breaker and carries the
`broker.<op>` fault-injection points the chaos suite drives.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Optional

from analytics_zoo_tpu.common import faults
from analytics_zoo_tpu.serving.broker import Broker

log = logging.getLogger("analytics_zoo_tpu.serving")

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"
_STATE_CODE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}


class CircuitOpenError(ConnectionError):
    """Fast-fail while the breaker is open: the broker was down moments
    ago and the reset window has not elapsed — callers must not pay a
    connect timeout per attempt."""


class BackoffPolicy:
    """Capped exponential backoff with jitter. `delay(attempt)` for
    attempt 1, 2, ... grows `initial_s * factor**(attempt-1)` up to
    `max_s`, then jitters ±`jitter` of the value so a fleet of
    reconnecting clients does not thundering-herd a restarting broker."""

    def __init__(self, initial_s: float = 0.05, max_s: float = 5.0,
                 factor: float = 2.0, jitter: float = 0.25):
        if initial_s <= 0 or max_s < initial_s or factor < 1:
            raise ValueError(
                f"bad backoff policy (initial={initial_s}, max={max_s}, "
                f"factor={factor})")
        self.initial_s = initial_s
        self.max_s = max_s
        self.factor = factor
        self.jitter = max(0.0, min(float(jitter), 1.0))

    def delay(self, attempt: int) -> float:
        base = min(self.initial_s * self.factor ** max(attempt - 1, 0),
                   self.max_s)
        if not self.jitter:
            return base
        return base * (1.0 + self.jitter * (2.0 * random.random() - 1.0))


class CircuitBreaker:
    """Thread-safe closed/open/half-open breaker. `allow()` gates a
    call; `record_success()`/`record_failure()` report its outcome."""

    def __init__(self, name: str = "broker", failure_threshold: int = 3,
                 reset_timeout_s: float = 1.0, registry=None):
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}")
        self.name = name
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self.registry = registry       # clones rebuild with the same sink
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False     # half-open admits exactly one probe
        self._lock = threading.Lock()
        if registry is None:
            from analytics_zoo_tpu.observability.registry import get_registry
            registry = get_registry()
        self._state_gauge = registry.gauge(
            "serving_broker_breaker_state",
            "circuit breaker state per serving broker connection "
            "(0=closed, 1=open, 2=half-open)")
        self._transitions = registry.counter(
            "serving_broker_breaker_transitions_total",
            "circuit breaker state transitions, by broker and new state")
        self._state_gauge.set(0, broker=name)

    @property
    def state(self) -> str:
        with self._lock:
            if self._state == OPEN and \
                    time.monotonic() - self._opened_at >= \
                    self.reset_timeout_s:
                return HALF_OPEN      # due for a probe
            return self._state

    def _transition(self, to: str):
        """Caller holds the lock. One log line + one metric update per
        transition — the log-spam cap the reader loop relies on."""
        if to == self._state:
            return
        log.warning("broker breaker %s: %s -> %s", self.name,
                    self._state, to)
        self._state = to
        self._state_gauge.set(_STATE_CODE[to], broker=self.name)
        self._transitions.inc(broker=self.name, to=to)

    def allow(self) -> bool:
        """True if a call may proceed now. While open, returns False
        until `reset_timeout_s` has elapsed, then admits exactly ONE
        half-open probe; further calls fast-fail until the probe
        reports back."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN and not self._probing and \
                    time.monotonic() - self._opened_at >= \
                    self.reset_timeout_s:
                self._transition(HALF_OPEN)
            if self._state == HALF_OPEN and not self._probing:
                self._probing = True
                return True
            return False

    def record_success(self):
        with self._lock:
            self._failures = 0
            self._probing = False
            self._transition(CLOSED)

    def record_failure(self):
        with self._lock:
            self._failures += 1
            self._probing = False
            if self._state == HALF_OPEN or \
                    self._failures >= self.failure_threshold:
                self._opened_at = time.monotonic()
                self._transition(OPEN)


class ResilientBroker(Broker):
    """A `Broker` wearing a circuit breaker, for the serving engine's
    own connections (reader/sink). Clients keep their raw brokers — a
    client-side timeout is already the right degradation there.

    Every op funnels through `_guard`: fast-fail while the breaker is
    open, record the outcome otherwise. `RESPError` (an application
    error over a WORKING transport) counts as success for breaker
    purposes. Carries the `broker.<op>` fault-injection points."""

    def __init__(self, inner: Broker, role: str = "serving",
                 breaker: Optional[CircuitBreaker] = None,
                 registry=None):
        self.inner = inner
        self.role = role
        self.breaker = breaker if breaker is not None else CircuitBreaker(
            name=role, registry=registry)

    def _guard(self, op: str, *args, **kwargs):
        if not self.breaker.allow():
            raise CircuitOpenError(
                f"{self.role} broker circuit open "
                f"(retry in <= {self.breaker.reset_timeout_s}s)")
        try:
            faults.fire(f"broker.{op}", role=self.role, op=op)
            result = getattr(self.inner, op)(*args, **kwargs)
        except Exception as e:
            from analytics_zoo_tpu.serving.broker import RESPError
            if isinstance(e, RESPError):
                # the transport answered; the command was bad — not a
                # connectivity failure, must not open the circuit
                self.breaker.record_success()
            else:
                self.breaker.record_failure()
            raise
        self.breaker.record_success()
        return result

    def clone(self) -> "ResilientBroker":
        # independent breaker STATE (a clone serves a different stage
        # whose connection fails independently) with the SAME breaker
        # configuration — discarding the configured thresholds/registry
        # here would silently reset a caller's knobs to defaults
        return ResilientBroker(
            self.inner.clone(), role=self.role,
            breaker=CircuitBreaker(
                self.breaker.name,
                failure_threshold=self.breaker.failure_threshold,
                reset_timeout_s=self.breaker.reset_timeout_s,
                registry=self.breaker.registry))

    def close(self):
        close = getattr(self.inner, "close", None)
        if close is not None:
            close()

    def xadd(self, stream, record):
        return self._guard("xadd", stream, record)

    def read_group(self, stream, group, consumer, count, block_ms=100):
        return self._guard("read_group", stream, group, consumer, count,
                           block_ms)

    def ack(self, stream, group, ids):
        return self._guard("ack", stream, group, ids)

    def claim_stale(self, stream, group, consumer, min_idle_ms, count):
        return self._guard("claim_stale", stream, group, consumer,
                           min_idle_ms, count)

    def pending_count(self, stream, group):
        return self._guard("pending_count", stream, group)

    def stream_depth(self, stream):
        return self._guard("stream_depth", stream)

    def writeback(self, key, mapping, stream, group, ids):
        return self._guard("writeback", key, mapping, stream, group, ids)

    def hset(self, key, field, value):
        return self._guard("hset", key, field, value)

    def hset_many(self, key, mapping):
        return self._guard("hset_many", key, mapping)

    def hget(self, key, field):
        return self._guard("hget", key, field)

    def hmget(self, key, fields):
        # the decode engine's recovery path reads a dead peer's token
        # rows through its resilient connection
        return self._guard("hmget", key, fields)

    def hgetall(self, key):
        return self._guard("hgetall", key)

    def hlen(self, key):
        return self._guard("hlen", key)

    def hdel(self, key, field):
        return self._guard("hdel", key, field)

    def hdel_many(self, key, fields):
        return self._guard("hdel_many", key, fields)
