"""HTTP frontend — the akka-http gateway analogue
(`serving/http/FrontEndApp.scala:126-232`).

Routes preserved: `POST /predict` (sync prediction: enqueue to the broker,
await the result — `FrontEndApp.scala:163`), `GET /metrics` (timer snapshots
as JSON, `:131,241` — with a pipelined ClusterServing attached this
includes per-stage decode/dispatch/sink p50/p95/p99 and live queue-depth
gauges, so an operator can see which stage is the bottleneck), `POST
/model-secure` ("secret=xxx&salt=yyy" stored on the broker for
encrypted-model loading, `:140-152`), plus `GET /` liveness
("welcome to analytics zoo web serving frontend").

Hardening, matching the reference's front-end options:
- token-bucket rate limiting (`FrontEndApp.scala:59-60` guava RateLimiter,
  `tryAcquire` at `:167`): `tokens_per_second` caps admission; a request
  that can't get a token within `token_acquire_timeout_ms` is rejected
  with 429.
- TLS (`:225-227` httpsEnabled/keystore): pass `tls_certfile`/`tls_keyfile`
  (PEM) and the listener speaks HTTPS via stdlib ssl.

Stdlib ThreadingHTTPServer: no extra dependency, one thread per in-flight
request, the TPU work itself is serialized by the serving loop behind the
broker."""

from __future__ import annotations

import json
import os
import ssl
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Union
from urllib.parse import parse_qs

import numpy as np

from analytics_zoo_tpu.observability.prometheus import (
    CONTENT_TYPE as PROMETHEUS_CONTENT_TYPE, render_prometheus)
from analytics_zoo_tpu.observability.registry import (MetricsRegistry,
                                                      get_registry)
from analytics_zoo_tpu.serving.broker import Broker, connect_broker
from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue
from analytics_zoo_tpu.serving.server import ClusterServing
from analytics_zoo_tpu.serving.timer import Timer

# broker keys for the model-secure flow (`Conventions.scala:33-35`)
MODEL_SECURED_KEY = "model_secured"
MODEL_SECURED_SECRET = "secret"
MODEL_SECURED_SALT = "salt"

# route tables: a known route hit with the wrong method answers 405 with
# an Allow header (silent 404s made method typos indistinguishable from
# wrong URLs); unknown paths stay 404
ROUTES_GET = ("/", "/metrics", "/trace", "/healthz", "/rollout/status")
ROUTES_POST = ("/predict", "/model-secure", "/profile", "/rollout")


class TokenBucket:
    """Continuous-refill token bucket (the guava RateLimiter role,
    `FrontEndApp.scala:59`). Thread-safe; `try_acquire` waits up to the
    given timeout for a token."""

    def __init__(self, tokens_per_second: float,
                 capacity: Optional[float] = None):
        if tokens_per_second <= 0:
            raise ValueError("tokens_per_second must be > 0")
        self.rate = float(tokens_per_second)
        self.capacity = float(capacity if capacity is not None
                              else max(1.0, tokens_per_second))
        self._tokens = self.capacity
        self._t = time.monotonic()
        self._lock = threading.Lock()

    def _refill(self, now: float) -> None:
        self._tokens = min(self.capacity,
                           self._tokens + (now - self._t) * self.rate)
        self._t = now

    def try_acquire(self, timeout_ms: float = 0.0) -> bool:
        deadline = time.monotonic() + timeout_ms / 1000.0
        while True:
            with self._lock:
                now = time.monotonic()
                self._refill(now)
                if self._tokens >= 1.0:
                    self._tokens -= 1.0
                    return True
                wait = min((1.0 - self._tokens) / self.rate,
                           deadline - now)
            if wait <= 0:
                return False
            time.sleep(wait)


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *args):  # quiet
        pass

    def _count_request(self, code: int):
        counter = getattr(self.server, "http_requests", None)
        if counter is not None:
            route = self.path.split("?", 1)[0]
            if route.startswith("/trace/"):
                # per-request trace ids must not explode label
                # cardinality — every /trace/<id>[/summary] hit counts
                # as the one /trace route
                route = "/trace"
            if route not in ROUTES_GET and route not in ROUTES_POST:
                route = "other"   # bound label cardinality against scans
            counter.inc(route=route, code=str(code),
                        method=self.command or "GET")

    def _send_bytes(self, code: int, body: bytes, content_type: str,
                    allow: Optional[str] = None,
                    extra_headers: Optional[dict] = None):
        self._count_request(code)
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        if allow:
            self.send_header("Allow", allow)
        for name, value in (extra_headers or {}).items():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(body)

    def _send(self, code: int, payload, allow: Optional[str] = None,
              extra_headers: Optional[dict] = None):
        self._send_bytes(code, json.dumps(payload).encode(),
                         "application/json", allow=allow,
                         extra_headers=extra_headers)

    def _method_not_allowed(self, allow: str):
        self._send(405, {"error": f"method {self.command} not allowed; "
                                  f"allowed: {allow}"}, allow=allow)

    def do_GET(self):
        path = self.path.split("?", 1)[0]
        if path == "/":
            payload = {"message": "welcome to analytics zoo web "
                                  "serving frontend"}
            serving = self.server.serving
            # deployment at a glance: replicated-vs-sharded, replica
            # count, device count (mesh axes when sharded); guarded like
            # server.py — the engine only requires predict_async, so a
            # duck-typed model must not break the liveness probe
            info = getattr(getattr(serving, "model", None),
                           "placement_info", None)
            if info is not None:
                payload["placement"] = info()
            self._send(200, payload)
        elif path == "/metrics":
            self._metrics()
        elif path == "/trace":
            self._trace()
        elif path.startswith("/trace/"):
            self._trace_request(path)
        elif path == "/healthz":
            self._healthz()
        elif path == "/rollout/status":
            self._rollout_status()
        elif path in ROUTES_POST:
            self._method_not_allowed("POST")
        else:
            self._send(404, {"error": "not found"})

    def _rollout_status(self):
        """Live rollout view (ISSUE 14): the controller's state machine
        on a gateway, the agent's last-swap record on an engine; 404
        when no rollout is wired."""
        rollout = self.server.rollout
        if rollout is None:
            self._send(404, {"error": "rollout not configured; start "
                                      "with params.rollout.model_dir "
                                      "(engine) or gateway "
                                      "--rollout-dir (controller)"})
            return
        try:
            self._send(200, rollout.status())
        except Exception as e:  # noqa: BLE001 — a probe must answer
            self._send(500, {"error": f"{type(e).__name__}: {e}"})

    def _rollout(self):
        """`POST /rollout` (ISSUE 14): ask the controller to converge
        the fleet — body `{"version": N}` pins a published version
        (manual roll-forward OR rollback); an empty body just pokes the
        watcher. 409 on a quarantined version, 404 on an unpublished
        one or when no controller runs here."""
        rollout = self.server.rollout
        if rollout is None or not hasattr(rollout, "request"):
            self._send(404, {"error": "no rollout controller on this "
                                      "frontend (engines follow "
                                      "directives; POST to the "
                                      "gateway)"})
            return
        version = None
        unpin = False
        try:
            body = self._read_body()
            if body.strip():
                req = json.loads(body)
                if isinstance(req, dict):
                    if req.get("version") is not None:
                        version = int(req["version"])
                    unpin = bool(req.get("unpin"))
        except (TypeError, ValueError) as e:
            self._send(400, {"error": f"bad body: {e}"})
            return
        try:
            status = rollout.request(version, unpin=unpin)
        except ValueError as e:       # quarantined
            self._send(409, {"error": str(e)})
            return
        except FileNotFoundError as e:
            self._send(404, {"error": str(e)})
            return
        except Exception as e:  # noqa: BLE001 — frontend must not die
            self._send(500, {"error": f"{type(e).__name__}: {e}"})
            return
        self._send(202, status)

    def _metrics(self):
        """Content negotiation: `Accept: text/plain` (Prometheus scrape)
        gets 0.0.4 exposition text of the process-wide registry —
        serving per-stage histograms, queue gauges, HTTP counters, and
        any training metrics published in-process; everything else keeps
        the original JSON timer snapshot (now with the registry snapshot
        alongside)."""
        accept = self.headers.get("Accept", "") or ""
        registry: MetricsRegistry = self.server.registry
        # freshen the SLO gauges before ANY exposition: a Prometheus-only
        # deployment (text scrape) must see slo_burn_rate/slo_met move
        # without anything polling /healthz (the tracker rate-limits
        # itself, so per-scrape evaluation is one window sample)
        slo = getattr(self.server.serving, "slo", None) \
            if self.server.serving else None
        if slo is not None:
            try:
                slo.evaluate()
            except Exception:  # noqa: BLE001 — scrape must answer
                pass
        if "text/plain" in accept or "openmetrics" in accept:
            agg = self.server.fleet_metrics
            if agg is not None:
                # fleet scrape (ISSUE 17): merge every alive engine's
                # published registry blob with the gateway's own —
                # counters summed into scope="fleet" rollups, histograms
                # merged bucket-wise, gauges engine-labeled. A merge
                # failure degrades to the local registry: the scrape
                # must always answer.
                try:
                    registry = agg.merged(registry)
                except Exception:  # noqa: BLE001
                    pass
            self._send_bytes(200, render_prometheus(registry).encode(),
                             PROMETHEUS_CONTENT_TYPE)
            return
        serving: Optional[ClusterServing] = self.server.serving
        timers = {"frontend": self.server.request_timer.snapshot()}
        if serving is not None:
            timers.update(serving.metrics())
        if self.server.fleet is not None:
            # gateway view (ISSUE 10): per-engine heartbeat rows plus
            # the alive/ready counts the `serving_engines_*` families
            # export to Prometheus
            timers["fleet"] = self.server.fleet.summary()
        if self.server.fleet_metrics is not None:
            timers["fleet_metrics"] = self.server.fleet_metrics.summary()
        timers["registry"] = registry.snapshot()
        self._send(200, timers)

    def _healthz(self):
        """Readiness probe (ISSUE 6/10): with a LOCAL engine attached,
        aggregates its supervisor/quarantine/breaker/SLO state via
        `ClusterServing.health()` — 200 while the engine can accept
        traffic, 503 (with Retry-After on a quarantined pool) when it
        cannot. With FLEET tracking configured (the gateway role), the
        claim is about the fleet: 200 while >= 1 engine heartbeats
        alive+ready, 503 + Retry-After when none do — or when the
        broker itself is unreachable, since then the gateway can
        neither know the fleet nor move a record. Only a truly
        standalone frontend (no engine, no fleet) keeps the legacy
        unconditional 200 with `engine: null` — it is alive as a
        gateway; readiness of engines it doesn't track is not its
        claim to make."""
        serving = self.server.serving
        fleet = self.server.fleet
        gateway = self._gateway_block()
        health_fn = getattr(serving, "health", None) if serving else None
        if not callable(health_fn):
            if fleet is None:
                payload = {"ready": True, "engine": None}
                if gateway is not None:
                    payload["gateway"] = gateway
                self._send(200, payload)
                return
            summary = fleet.summary()
            ready = summary.get("ready")
            payload = {"ready": bool(ready), "engine": None,
                       "fleet": summary}
            if gateway is not None:
                payload["gateway"] = gateway
            if ready:
                self._send(200, payload)
                return
            payload["reason"] = "broker unreachable" \
                if summary.get("broker") == "unreachable" \
                else "no serving engine alive"
            self._send(503, payload, extra_headers={
                "Retry-After": str(fleet.retry_after_s)})
            return
        try:
            h = health_fn()
        except Exception as e:  # noqa: BLE001 — a probe must answer
            self._send(503, {"ready": False,
                             "reason": f"{type(e).__name__}: {e}"})
            return
        if fleet is not None:
            h["fleet"] = fleet.summary()
        if gateway is not None:
            h["gateway"] = gateway
        if h.get("ready"):
            self._send(200, h)
        else:
            retry_s = getattr(serving, "retry_after_s", 1)
            self._send(503, h,
                       extra_headers={"Retry-After": str(retry_s)})

    def _gateway_block(self) -> Optional[dict]:
        """Replicated-gateway identity for /healthz (ISSUE 16): which
        replica answered, its current role, and who it believes leads.
        None on a frontend running without a gateway_id."""
        lease = getattr(self.server, "leader_lease", None)
        if lease is None:
            return None
        return {"id": lease.gateway_id,
                "role": "leader" if lease.is_leader() else "follower",
                "leader": lease.leader()}

    def _profile(self):
        """`POST /profile?seconds=N` (ISSUE 6): one bounded jax.profiler
        capture into the frontend's rotated artifact dir, with the
        host-side stack-sampler report for the serving pipeline threads
        alongside. Single-flight: a second POST while one runs gets 409
        (two concurrent profiler sessions would corrupt each other).
        Blocks the requesting connection for the capture window — that
        is the point; other requests ride their own handler threads."""
        from analytics_zoo_tpu.observability.capture import (
            MAX_CAPTURE_SECONDS, CaptureActiveError)
        qs = parse_qs(self.path.partition("?")[2])
        try:
            seconds = float(qs.get("seconds", ["2"])[0])
        except ValueError:
            self._send(400, {"error": "seconds must be a number"})
            return
        if not (0 < seconds <= MAX_CAPTURE_SECONDS):
            self._send(400, {"error": f"seconds must be in "
                                      f"(0, {MAX_CAPTURE_SECONDS:g}]"})
            return
        capture = self.server.profile_capture
        if capture is None:
            self._send(404, {"error": "profiling disabled "
                                      "(params.profile_enabled: false)"})
            return
        try:
            manifest = capture.capture(seconds, tag="http")
        except CaptureActiveError as e:
            self._send(409, {"error": str(e)})
            return
        except Exception as e:  # noqa: BLE001 — frontend must not die
            self._send(500, {"error": f"{type(e).__name__}: {e}"})
            return
        self._send(200, manifest)

    def _trace(self):
        """Chrome trace-event JSON of the serving pipeline's spans
        (open in Perfetto); 404 when no tracer is attached."""
        serving: Optional[ClusterServing] = self.server.serving
        tracer = getattr(serving, "tracer", None) if serving else None
        if tracer is None:
            self._send(404, {"error": "tracing not enabled; attach a "
                                      "Tracer to ClusterServing"})
            return
        self._send(200, tracer.chrome_trace())

    def _trace_request(self, path: str):
        """`GET /trace/<request_id>` (ISSUE 17): ONE merged
        cross-process Chrome timeline for the request, assembled from
        every engine's published span blobs — served from broker state,
        so ANY gateway replica answers identically.
        `GET /trace/<request_id>/summary` instead returns the
        critical-path breakdown (wire / queue / decode / device /
        writeback milliseconds) plus span coverage of the request
        window."""
        from urllib.parse import unquote
        collector = self.server.trace_collector
        if collector is None:
            self._send(404, {"error": "trace collection not available "
                                      "on this frontend"})
            return
        rest = path[len("/trace/"):]
        want_summary = False
        if rest.endswith("/summary"):
            want_summary = True
            rest = rest[:-len("/summary")]
        request_id = unquote(rest)
        if not request_id:
            self._send(400, {"error": "empty request id"})
            return
        try:
            out = (collector.summary(request_id) if want_summary
                   else collector.assemble(request_id))
        except Exception as e:  # noqa: BLE001 — frontend must not die
            self._send(500, {"error": f"{type(e).__name__}: {e}"})
            return
        if out is None:
            self._send(404, {
                "error": f"no published spans cover request id "
                         f"{request_id!r} (not sampled, expired from "
                         "the export window, or not yet published)"})
            return
        self._send(200, out)

    def _read_body(self) -> bytes:
        length = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(length)

    def do_POST(self):
        path = self.path.split("?", 1)[0]
        if path == "/model-secure":
            self._model_secure()
            return
        if path == "/profile":
            self._profile()
            return
        if path == "/rollout":
            self._rollout()
            return
        if path != "/predict":
            if path in ROUTES_GET:
                self._method_not_allowed("GET")
            else:
                self._send(404, {"error": "not found"})
            return
        limiter: Optional[TokenBucket] = self.server.rate_limiter
        if limiter is not None and not limiter.try_acquire(
                self.server.token_acquire_timeout_ms):
            # `FrontEndApp.scala:167` tryAcquire failure → reject
            self._send(429, {"error": "too many requests"})
            return
        # tiered admission (ISSUE 11): the cheap early 429. The tier
        # arrives in the header (wins) or the "tier" body field —
        # "cheap" means the record never touches the broker and no
        # engine capacity is spent; the body is parsed early ONLY when
        # admission needs the field spelling (with no admission
        # configured, the quarantine/dead-fleet 503 gates below keep
        # answering without paying a body parse). Backlog past the
        # requester's tier threshold → reject with a Retry-After; the
        # expensive 503s below stay the last line, and a batch job's
        # burst throttles long before a premium tenant feels it.
        tier = self.headers.get(self.server.admission_header) or None
        req = None
        admission = self.server.admission
        if admission is not None:
            if tier is None:
                try:
                    req = json.loads(self._read_body())
                except Exception as e:  # noqa: BLE001 — must not die
                    self._send(400, {"error": f"{type(e).__name__}: {e}"})
                    return
                if isinstance(req, dict):
                    tier = req.pop("tier", None)
            ok, retry_s = admission.admit(tier)
            if not ok:
                self._send(429, {
                    "error": "backlog over this tier's admission "
                             "threshold; retry shortly",
                    "tier": admission.tiers.name(
                        admission.tiers.level(tier))},
                    extra_headers={
                        "Retry-After": str(max(1, int(round(retry_s))))})
                return
        # every model replica quarantined (ISSUE 5): answer 503 +
        # Retry-After sized to the canary-probe cadence instead of
        # letting the request hang to its timeout behind a fully-sick
        # pool. The records already in the pipeline wait for revival;
        # new admissions are the frontend's to refuse.
        serving = self.server.serving
        if serving is not None:
            healthy_fn = getattr(serving, "healthy_replicas", None)
            if callable(healthy_fn) and healthy_fn() == 0:
                retry_s = getattr(serving, "retry_after_s", 1)
                self._send(503, {"error": "every model replica is "
                                          "quarantined; retry shortly"},
                           extra_headers={"Retry-After": str(retry_s)})
                return
        elif self.server.fleet is not None:
            # gateway role (ISSUE 10): with zero engines alive the
            # record would sit in the stream until its client timeout —
            # refuse admission up front, like the quarantined-pool 503
            if not self.server.fleet.alive_count():
                self._send(503, {"error": "no serving engine alive; "
                                          "retry shortly"},
                           extra_headers={"Retry-After": str(
                               self.server.fleet.retry_after_s)})
                return
        qs = parse_qs(self.path.split("?", 1)[1]) \
            if "?" in self.path else {}
        with self.server.request_timer.timing():
            try:
                if req is None:
                    req = json.loads(self._read_body())
                if tier is None and isinstance(req, dict):
                    # field spelling still rides to the engine's tiered
                    # scheduler even without gateway admission
                    tier = req.pop("tier", None)
                if qs.get("stream", ["0"])[0] in ("1", "true"):
                    # generative streaming (ISSUE 18): SSE per token
                    self._predict_stream(req, tier)
                    return
                # {"instances": [[...], ...]} tf-serving-style (each
                # instance is ONE serving record — they batch inside the
                # serving loop), or {"b64","dtype","shape"} raw tensor
                if "instances" in req:
                    arr = np.asarray(req["instances"], np.float32)
                    uris, t_ing, t0 = self._request_ids(len(arr))
                    results = self.server.input_queue.predict_batch(
                        arr, timeout_s=self.server.timeout_s, tier=tier,
                        uris=uris)
                    self._gateway_span(uris, t_ing, t0)
                    if any(r == "SHED" for r in results
                           if isinstance(r, str)):
                        self._shed_response(
                            shed=sum(1 for r in results if isinstance(
                                r, str) and r == "SHED"),
                            total=len(results))
                    elif any(isinstance(r, float) and np.isnan(r)
                             for r in results):
                        self._send(500, {"error": "inference failure (NaN)"})
                    else:
                        payload = {"predictions": np.asarray(results)
                                   .tolist()}
                        if uris is not None:
                            payload["request_ids"] = uris
                        self._send(200, payload)
                    return
                from analytics_zoo_tpu.serving.broker import decode_ndarray
                arr = decode_ndarray(req)
                uris, t_ing, t0 = self._request_ids(1)
                result = self.server.input_queue.predict(
                    arr, timeout_s=self.server.timeout_s, tier=tier,
                    uri=uris[0] if uris else None)
                self._gateway_span(uris, t_ing, t0)
                if isinstance(result, str) and result == "SHED":
                    self._shed_response()
                elif isinstance(result, float) and np.isnan(result):
                    self._send(500, {"error": "inference failure (NaN)"})
                else:
                    payload = {"predictions": np.asarray(result)
                               .tolist()}
                    if uris is not None:
                        payload["request_ids"] = uris
                    self._send(200, payload)
            except Exception as e:  # noqa: BLE001 — frontend must not die
                self._send(400, {"error": f"{type(e).__name__}: {e}"})

    def _predict_stream(self, req, tier):
        """`POST /predict?stream=1` — server-sent events for one
        generative request (decode-mode engines, ISSUE 18). The body
        carries ``{"prompt": [token ids...], "max_new": N, "eos": id}``;
        the record is enqueued with the ``stream`` flag so the engine
        writes per-token rows, and this handler relays each row as one
        ``data:`` event the moment its poll sweep sees it, closing with
        an ``event: done`` carrying the full token array (exactly what
        the non-streaming path would have returned). One request per
        SSE response — batching streams would interleave sequences on
        one ordered connection.

        Streaming continuity (ISSUE 20): every token frame carries an
        SSE ``id:`` line (the token index), idle gaps emit periodic
        ``: keepalive`` comments so proxies hold the connection open,
        and a dropped client reconnects by POSTing its ``request_id``
        with a ``Last-Event-ID`` header (or ``last_event_id`` body
        field) — the record is NOT re-enqueued; the relay resumes from
        the durable token rows at ``last + 1``, so every index is
        observed exactly once across connections. When no row lands for
        the stall window AND the fleet's heartbeats flatline, the relay
        closes with ``event: error`` (``engine-dead``) instead of
        hanging to the timeout."""
        last_id = self.headers.get("Last-Event-ID")
        if last_id is None and isinstance(req, dict):
            last_id = req.get("last_event_id")
        resume_uri = req.get("request_id") if isinstance(req, dict) \
            else None
        start = 0
        if resume_uri is not None:
            # reconnect: the stream already exists under this uri —
            # re-enqueueing would decode the prompt a second time
            if last_id is not None:
                try:
                    start = int(last_id) + 1
                except (TypeError, ValueError):
                    self._send(400, {
                        "error": "Last-Event-ID must be the integer "
                                 "index of the last token frame "
                                 "received"})
                    return
            uri = str(resume_uri)
            uris, t_ing, t0 = None, 0.0, 0.0
        else:
            prompt = req.get("prompt") if isinstance(req, dict) else None
            if prompt is None and isinstance(req, dict) \
                    and len(req.get("instances") or []) == 1:
                prompt = req["instances"][0]
            if prompt is None:
                self._send(400, {"error": "streaming /predict needs a "
                                          "\"prompt\" token-id list "
                                          "(or one-element \"instances\")"})
                return
            arr = np.asarray(prompt, np.int32).reshape(-1)
            uris, t_ing, t0 = self._request_ids(1)
            uri = uris[0] if uris else str(uuid.uuid4())
            extra = {}
            if isinstance(req, dict) and "max_new" in req:
                extra["max_new"] = int(req["max_new"])
            if isinstance(req, dict) and "eos" in req:
                extra["eos"] = int(req["eos"])
            self.server.input_queue.enqueue(uri, tier=tier, t=arr,
                                            stream=1, **extra)
        self._count_request(200)
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        # the reconnect handle, known BEFORE any frame arrives (the
        # done payload repeats it, but a dropped connection never saw
        # that)
        self.send_header("X-Request-Id", uri)
        self.end_headers()
        replayed = 0
        try:
            for evt in self.server.output_queue.stream_tokens(
                    uri, timeout_s=self.server.timeout_s, start=start,
                    keepalive_s=self.server.stream_keepalive_s,
                    stall_timeout_s=self.server.stream_stall_timeout_s):
                if evt.get("keepalive"):
                    # SSE comment: ignored by clients, resets proxy
                    # idle timers, never advances Last-Event-ID
                    self.wfile.write(b": keepalive\n\n")
                elif evt.get("done"):
                    if evt.get("error"):
                        payload = {"error": evt["error"],
                                   "request_id": uri}
                        name = b"error" if evt["error"] == "engine-dead" \
                            else b"done"
                        self.wfile.write(
                            b"event: " + name + b"\ndata: "
                            + json.dumps(payload).encode() + b"\n\n")
                    else:
                        payload = {"tokens":
                                   np.asarray(evt["tokens"]).tolist(),
                                   "gen": evt.get("gen", {}),
                                   "request_id": uri}
                        self.wfile.write(
                            b"event: done\ndata: "
                            + json.dumps(payload).encode() + b"\n\n")
                else:
                    if resume_uri is not None:
                        replayed += 1
                    self.wfile.write(
                        b"id: " + str(evt["i"]).encode() + b"\ndata: "
                        + json.dumps(evt).encode() + b"\n\n")
                self.wfile.flush()
            if uris:
                self._gateway_span(uris, t_ing, t0)
        except TimeoutError:
            self.wfile.write(b"event: error\ndata: "
                             b"{\"error\": \"timeout\"}\n\n")
            self.wfile.flush()
        finally:
            if replayed:
                self.server.token_replays.inc(replayed,
                                              surface="frontend")

    def _request_ids(self, n: int):
        """Pre-generated request ids (= trace ids) for a traced
        `/predict`: returned to the client as `request_ids` so
        `GET /trace/<id>` is addressable, and used as the enqueued
        records' uris so every engine span carries the same id.
        `(None, ..)` when gateway tracing is off — the wire payload
        stays byte-identical to the untraced frontend."""
        t_ing = time.time()
        t0 = time.perf_counter()
        if self.server.gateway_tracer is None:
            return None, t_ing, t0
        return [str(uuid.uuid4()) for _ in range(n)], t_ing, t0

    def _gateway_span(self, uris, t_ing: float, t0: float):
        """The gateway's own hop on the request timeline: enqueue →
        result readback, anchored on the ingest wall clock (`t_ingest`
        is the collector's skew-safe anchor for this process)."""
        tracer = self.server.gateway_tracer
        if tracer is None or not uris:
            return
        tracer.add_span("gateway_request", t0, time.perf_counter(),
                        cat="serving.gateway", trace_ids=uris,
                        args={"t_ingest": t_ing})

    def _shed_response(self, shed=None, total=None):
        """The engine shed this record under overload (ISSUE 11): an
        explicit 503 with Retry-After — the record was answered, not
        lost, and the client should back off like any overload. For a
        multi-instance request the shed/total counts say how much of
        the batch was actually refused — a retry of the whole request
        recomputes the served siblings too, so clients under overload
        should shrink their batches (or raise their tier)."""
        admission = self.server.admission
        retry_s = admission.retry_after_s if admission is not None else 1
        payload = {"error": "record shed under overload; retry shortly"}
        if shed is not None:
            payload["shed"] = shed
            payload["total"] = total
        self._send(503, payload,
                   extra_headers={
                       "Retry-After": str(max(1, int(round(retry_s))))})

    def _unsupported_method(self):
        path = self.path.split("?", 1)[0]
        if path in ROUTES_GET:
            self._method_not_allowed("GET")
        elif path in ROUTES_POST:
            self._method_not_allowed("POST")
        else:
            self._send(404, {"error": "not found"})

    do_PUT = _unsupported_method
    do_DELETE = _unsupported_method
    do_PATCH = _unsupported_method

    def _model_secure(self):
        """`FrontEndApp.scala:140-152`: body `secret=xxx&salt=yyy` → broker
        hash, where the serving side polls for it before decrypting an
        encrypted model."""
        try:
            fields = parse_qs(self._read_body().decode(),
                              strict_parsing=True)
            secret = fields["secret"][0]
            salt = fields["salt"][0]
            broker: Broker = self.server.broker
            broker.hset(MODEL_SECURED_KEY, MODEL_SECURED_SECRET, secret)
            broker.hset(MODEL_SECURED_KEY, MODEL_SECURED_SALT, salt)
            self._send(200, {"message": "model secured secret and salt "
                                        "succeed to put on broker"})
        except Exception as e:  # noqa: BLE001
            self._send(500, {"error": f"{type(e).__name__}: {e}; please "
                             "post a content like secret=xxx&salt=xxxx"})


class _FrontEndServer(ThreadingHTTPServer):
    """TLS is wrapped per-connection in the handler thread (not on the
    listening socket): a client that connects and never handshakes must
    stall only its own thread, not the accept loop."""

    ssl_context: Optional[ssl.SSLContext] = None
    handshake_timeout_s: float = 10.0

    def finish_request(self, request, client_address):
        if self.ssl_context is not None:
            request.settimeout(self.handshake_timeout_s)
            try:
                request = self.ssl_context.wrap_socket(request,
                                                       server_side=True)
            except (ssl.SSLError, OSError):
                # bad/absent handshake (port scan, slow-loris, plain HTTP
                # against the TLS port): drop the connection quietly
                request.close()
                return
            request.settimeout(None)
        self.RequestHandlerClass(request, client_address, self)


class FrontEnd:
    """`FrontEndApp` equivalent: HTTP(S) server in front of a broker
    stream, with optional token-bucket admission control."""

    def __init__(self, broker: Union[Broker, str, None] = None,
                 serving: Optional[ClusterServing] = None,
                 host: str = "0.0.0.0", port: int = 10020,
                 timeout_s: float = 30.0,
                 tokens_per_second: Optional[float] = None,
                 token_bucket_capacity: Optional[float] = None,
                 token_acquire_timeout_ms: float = 100.0,
                 tls_certfile: Optional[str] = None,
                 tls_keyfile: Optional[str] = None,
                 registry: Optional[MetricsRegistry] = None,
                 profile_dir: Optional[str] = None,
                 profile_max_artifacts: int = 8,
                 profile_enabled: bool = True,
                 fleet_stream: Optional[str] = None,
                 engine_ttl_s: float = 6.0,
                 admission=None,
                 admission_header: str = "X-Priority",
                 rollout=None,
                 partitions: int = 1,
                 gateway_id: Optional[str] = None,
                 leader_ttl_s: float = 3.0,
                 trace_sample: float = 0.0,
                 trace_buffer_spans: int = 20000,
                 trace_export_interval_s: float = 0.5,
                 stream_keepalive_s: Optional[float] = None,
                 stream_stall_timeout_s: Optional[float] = None):
        """`fleet_stream` (ISSUE 10) turns the frontend into a fleet
        gateway: a `FleetTracker` watches engine heartbeats on
        `engines:<fleet_stream>`, `/healthz` answers for the FLEET
        (200 while >= 1 engine is alive+ready, 503 + Retry-After when
        none are), and `serving_engines_alive`/`serving_engines_total`
        appear on `/metrics`. An engine is alive while its heartbeat
        keeps progressing within `engine_ttl_s` (observed on this
        host's clock — cross-host skew can't flap the fleet).

        `admission` (ISSUE 11): an `elastic.AdmissionController` for
        tiered early 429s on `/predict` — the requester's priority
        class arrives in the `admission_header` header (or a "tier"
        body field) and is forwarded on the enqueued record for the
        engine's tiered scheduler.

        `partitions` (ISSUE 16) routes enqueued records across the
        partitioned request plane — it must match the engines'
        partition count (the broker-persisted meta row is the
        authority; engines validate it on startup).

        `gateway_id` (ISSUE 16) makes this frontend one REPLICA of a
        replicated gateway: a `GatewayLeaderLease` on
        `gateway:<fleet_stream>` elects one leader among the replicas.
        Every replica serves `/predict`, `/healthz`, `/metrics`,
        `/rollout` and `/rollout/status` from broker-derived state;
        only the leader's control loops (rollout convergence,
        autoscaling) act — wire `leader_fn=frontend.is_leader` into
        `RolloutController`/`FleetAutoscaler`. Kill the leader and a
        surviving replica takes the lease within ~`leader_ttl_s`.

        `trace_sample` (ISSUE 17) turns on the fleet trace plane at
        this gateway: `/predict` pre-generates request ids (returned as
        `request_ids`), stamps trace context on every enqueued record,
        and the gateway's own `gateway_request` spans export to the
        broker alongside the engines'. `GET /trace/<request_id>` serves
        the merged cross-process timeline from ANY replica (the
        collector is broker-state only, so it works even with
        `trace_sample=0` as long as engines sample)."""
        if not 0.0 <= float(trace_sample) <= 1.0:
            raise ValueError(
                f"trace_sample must be in [0, 1], got {trace_sample}")
        self.trace_sample = float(trace_sample)
        self.broker = broker if isinstance(broker, Broker) \
            else connect_broker(broker)
        self._srv = _FrontEndServer((host, port), _Handler)
        self._srv.daemon_threads = True
        self._srv.input_queue = InputQueue(self.broker,
                                           partitions=partitions,
                                           trace_sample=self.trace_sample,
                                           trace_parent="gateway_request")
        self._srv.broker = self.broker
        # generative streaming (ISSUE 18): SSE on /predict?stream=1
        # polls token rows straight off the result hash
        self._srv.output_queue = OutputQueue(self.broker)
        # streaming continuity (ISSUE 20): keepalive comment cadence and
        # heartbeat-aware stall cutoff for the SSE relay, plus the
        # counter the Last-Event-ID reconnect path bumps
        self._srv.stream_keepalive_s = stream_keepalive_s
        self._srv.stream_stall_timeout_s = stream_stall_timeout_s
        self._srv.serving = serving
        self._srv.request_timer = Timer("http_predict")
        self.registry = registry if registry is not None else get_registry()
        self._srv.registry = self.registry
        self._srv.http_requests = self.registry.counter(
            "http_requests_total",
            "frontend responses by route, method and status code")
        self._srv.token_replays = self.registry.counter(
            "serving_token_replays_total",
            "token rows replayed instead of served fresh — surface="
            "engine: deterministic re-decode of already-durable tokens "
            "when a resume context outruns the prefill ladder; surface="
            "frontend: rows re-sent to a reconnecting SSE client "
            "honoring Last-Event-ID")
        req_hist = self.registry.histogram(
            "http_request_ms", "frontend /predict round-trip duration")
        self._srv.request_timer.add_observer(
            lambda s: req_hist.observe(s * 1e3))
        # on-demand profiler capture (POST /profile): bounded + rotated
        # under one root; inert (zero request-path cost) until a capture
        # request arrives. `profile_enabled=False` (config:
        # params.profile_enabled) leaves the endpoint answering 404 —
        # a capture pins a handler thread for its whole window, which an
        # internet-facing frontend may not want to offer
        self._srv.profile_capture = None
        if profile_enabled:
            import tempfile
            from analytics_zoo_tpu.observability.capture import \
                ProfileCapture
            root = profile_dir or os.environ.get("ZOO_PROFILE_DIR") \
                or os.path.join(tempfile.gettempdir(), "zoo_profiles")
            self._srv.profile_capture = ProfileCapture(
                root, max_artifacts=profile_max_artifacts,
                registry=self.registry)
        # fleet tracking (gateway role): reads heartbeats over the same
        # broker the data plane uses — one shared dependency, no second
        # membership service
        self.fleet = None
        if fleet_stream:
            from analytics_zoo_tpu.serving.fleet import FleetTracker
            self.fleet = FleetTracker(self.broker, fleet_stream,
                                      ttl_s=engine_ttl_s,
                                      registry=self.registry)
        self._srv.fleet = self.fleet
        # replicated gateway (ISSUE 16): leader election over the same
        # broker as everything else. The lease thread gets its own
        # connection (clone) so a long /predict poll on the shared
        # socket can never delay a renewal past the ttl
        self.leader_lease = None
        self.gateway_id = gateway_id
        if gateway_id is not None:
            from analytics_zoo_tpu.serving.client import STREAM
            from analytics_zoo_tpu.serving.partitions import \
                GatewayLeaderLease
            clone = getattr(self.broker, "clone", None)
            lease_broker = clone() if callable(clone) else self.broker
            self.leader_lease = GatewayLeaderLease(
                lease_broker, fleet_stream or STREAM, gateway_id,
                ttl_s=leader_ttl_s, registry=self.registry)
        self._srv.leader_lease = self.leader_lease
        # fleet trace plane (ISSUE 17). The collector is UNCONDITIONAL:
        # it reads only broker state, so any replica — even one started
        # with tracing off — can serve GET /trace/<id> for requests the
        # engines sampled.
        from analytics_zoo_tpu.serving.trace_plane import (SpanExporter,
                                                           TraceCollector)
        # engines publish under their DATA stream's key; in a fleet
        # deployment that is the same name the heartbeat plane uses
        obs_stream = fleet_stream or self._srv.input_queue.stream
        self._srv.trace_collector = TraceCollector(self.broker, obs_stream)
        self.gateway_tracer = None
        self.trace_exporter = None
        self._te_broker = None
        if self.trace_sample > 0:
            from analytics_zoo_tpu.observability.tracing import Tracer
            gw_name = gateway_id or f"gateway-{os.getpid()}"
            self.gateway_tracer = Tracer(
                max_spans=int(trace_buffer_spans),
                registry=self.registry, engine=gw_name)
            clone = getattr(self.broker, "clone", None)
            if callable(clone):
                # own connection: a publish must never queue behind a
                # handler thread's blocking result poll
                self._te_broker = clone()
            self.trace_exporter = SpanExporter(
                self._te_broker or self.broker, obs_stream, gw_name,
                self.gateway_tracer, sample=self.trace_sample,
                interval_s=float(trace_export_interval_s),
                buffer_spans=int(trace_buffer_spans),
                registry=self.registry)
        self._srv.gateway_tracer = self.gateway_tracer
        # fleet metrics aggregation (ISSUE 17): /metrics on any replica
        # exposes the whole fleet's registry, not just this process
        self.fleet_metrics = None
        if fleet_stream:
            from analytics_zoo_tpu.serving.fleet_metrics import \
                FleetMetricsAggregator
            self.fleet_metrics = FleetMetricsAggregator(
                self.broker, fleet_stream, self.registry,
                alive_fn=self._alive_engines)
        self._srv.fleet_metrics = self.fleet_metrics
        self.admission = admission
        self._srv.admission = admission
        self._srv.admission_header = admission_header
        # versioned rollout (ISSUE 14): a RolloutController (gateway
        # role — POST /rollout accepted) or an EngineRolloutAgent
        # (engine role — status only); attach later via set_rollout
        # when the controller is built after the frontend
        self.rollout = rollout
        self._srv.rollout = rollout
        self._srv.timeout_s = timeout_s
        self._srv.rate_limiter = (
            TokenBucket(tokens_per_second, token_bucket_capacity)
            if tokens_per_second else None)
        self._srv.token_acquire_timeout_ms = token_acquire_timeout_ms
        self.tls = bool(tls_certfile)
        if tls_certfile:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(tls_certfile, tls_keyfile)
            self._srv.ssl_context = ctx
        self.host, self.port = self._srv.server_address[:2]
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)

    def set_rollout(self, rollout):
        """Attach the rollout controller/agent after construction (the
        gateway builds the controller with the frontend's own
        FleetTracker, which exists only once the frontend does)."""
        self.rollout = rollout
        self._srv.rollout = rollout

    def _alive_engines(self):
        """Alive-engine id set for the fleet metrics merge; None (the
        filter degrades open) while the broker view is unknown or no
        fleet tracking is configured."""
        if self.fleet is None:
            return None
        engines = self.fleet.poll()
        if engines is None:
            return None
        return {eid for eid, row in engines.items() if row.get("alive")}

    def is_leader(self) -> bool:
        """True when this replica's control loops should act. A
        frontend started WITHOUT a gateway_id is the only gateway
        there is — trivially the leader — so `leader_fn=...is_leader`
        is always safe to wire."""
        return self.leader_lease is None or self.leader_lease.is_leader()

    def start(self) -> "FrontEnd":
        if self.leader_lease is not None:
            self.leader_lease.start()
        if self.trace_exporter is not None:
            self.trace_exporter.start()
        self._thread.start()
        return self

    def stop(self, release_lease: bool = True):
        """`release_lease=False` is the kill-the-leader chaos analogue:
        the HTTP listener dies but the lease row stays unreleased in
        the broker, exactly as a SIGKILLed gateway would leave it — a
        surviving replica must win it only by expiry."""
        self._srv.shutdown()
        self._srv.server_close()
        if self.trace_exporter is not None:
            self.trace_exporter.stop(flush=True)
        if self._te_broker is not None:
            try:
                self._te_broker.close()
            except Exception:  # noqa: BLE001 — stopping regardless
                pass
        if self.leader_lease is not None:
            self.leader_lease.stop(release=release_lease)
        if self.fleet is not None:
            self.fleet.close()
