"""HTTP frontend — the akka-http gateway analogue
(`serving/http/FrontEndApp.scala:126-232`).

Routes preserved: `POST /predict` (sync prediction: enqueue to the broker,
await the result — `FrontEndApp.scala:163`), `GET /metrics` (timer snapshots
as JSON, `:131,241`), plus `GET /` liveness ("welcome to analytics zoo web
serving frontend"). Stdlib ThreadingHTTPServer: no extra dependency, one
thread per in-flight request, the TPU work itself is serialized by the
serving loop behind the broker."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Union

import numpy as np

from analytics_zoo_tpu.serving.broker import Broker, connect_broker
from analytics_zoo_tpu.serving.client import InputQueue
from analytics_zoo_tpu.serving.server import ClusterServing
from analytics_zoo_tpu.serving.timer import Timer


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *args):  # quiet
        pass

    def _send(self, code: int, payload):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path == "/":
            self._send(200, {"message": "welcome to analytics zoo web "
                                        "serving frontend"})
        elif self.path == "/metrics":
            serving: Optional[ClusterServing] = self.server.serving
            timers = {"frontend": self.server.request_timer.snapshot()}
            if serving is not None:
                timers.update(serving.metrics())
            self._send(200, timers)
        else:
            self._send(404, {"error": "not found"})

    def do_POST(self):
        if self.path != "/predict":
            self._send(404, {"error": "not found"})
            return
        with self.server.request_timer.timing():
            try:
                length = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(length))
                # {"instances": [[...], ...]} tf-serving-style, or
                # {"b64","dtype","shape"} raw tensor
                if "instances" in req:
                    arr = np.asarray(req["instances"], np.float32)
                else:
                    from analytics_zoo_tpu.serving.broker import \
                        decode_ndarray
                    arr = decode_ndarray(req)
                result = self.server.input_queue.predict(
                    arr, timeout_s=self.server.timeout_s)
                if isinstance(result, float) and np.isnan(result):
                    self._send(500, {"error": "inference failure (NaN)"})
                else:
                    self._send(200, {"predictions": np.asarray(result)
                                     .tolist()})
            except Exception as e:  # noqa: BLE001 — frontend must not die
                self._send(400, {"error": f"{type(e).__name__}: {e}"})


class FrontEnd:
    """`FrontEndApp` equivalent: HTTP server in front of a broker stream."""

    def __init__(self, broker: Union[Broker, str, None] = None,
                 serving: Optional[ClusterServing] = None,
                 host: str = "0.0.0.0", port: int = 10020,
                 timeout_s: float = 30.0):
        self.broker = broker if isinstance(broker, Broker) \
            else connect_broker(broker)
        self._srv = ThreadingHTTPServer((host, port), _Handler)
        self._srv.daemon_threads = True
        self._srv.input_queue = InputQueue(self.broker)
        self._srv.serving = serving
        self._srv.request_timer = Timer("http_predict")
        self._srv.timeout_s = timeout_s
        self.host, self.port = self._srv.server_address
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)

    def start(self) -> "FrontEnd":
        self._thread.start()
        return self

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()
