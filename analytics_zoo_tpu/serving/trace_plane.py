"""Fleet trace plane: span export + cross-process assembly (ISSUE 17).

The PR 2 `Tracer` is strictly process-local — spans die with the
process that produced them, and a request that crossed a gateway and an
engine has no single timeline anywhere. This module is the Dapper-style
glue over the broker substrate:

- `should_sample(trace_id, rate)` — deterministic head sampling keyed
  on the trace id (salted CRC32), so every process reaches the *same*
  keep/drop decision without propagating a sampled bit on the wire.
- `SpanExporter` — taps a `Tracer`'s span flow into a bounded local
  ring (overflow counted in `serving_trace_dropped_total`), and a
  background thread publishes the sampled window as one JSON blob per
  engine into the `traces:<stream>` broker hash (HSET overwrite: the
  structure is bounded by construction, and — unlike a consumer-group
  stream — every gateway replica can read it without racing an ack).
  `force(uris)` adds engine-local forced sampling for failed or
  SLO-violating requests, on top of the head-sampled set.
- `TraceCollector` — reads every engine's blob from any replica and
  assembles one merged timeline per request. Clock-skew safety follows
  the FleetTracker discipline: never compare wall clocks across hosts
  directly. Each engine's spans are internally consistent on its own
  monotonic clock; its "wire" spans carry the client ingest wall time
  and the engine read wall time, and the collector anchors each
  engine's span group on the client timeline at
  ``t_ingest + (delta_r - min_delta_e)`` where ``delta_r`` is that
  request's read-minus-ingest delta and ``min_delta_e`` the minimum
  delta observed for the engine across its published window — the
  per-engine skew term cancels, leaving a non-negative wire+queue
  estimate. Output is a merged Chrome trace (tid namespaced
  ``engine:thread``) plus a `wire / queue / decode / device /
  writeback` critical-path breakdown.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Sequence

from analytics_zoo_tpu.observability.tracing import (Span, Tracer,
                                                     span_coverage,
                                                     span_to_dict)

logger = logging.getLogger(__name__)

TRACES_KEY_PREFIX = "traces:"

# Stage vocabulary → critical-path column for /trace/<id>/summary.
# "device" covers the dispatch (host launch) plus the result wait; the
# residual inside "sink" (encode, buffering) is visible in the full
# trace but not a column of its own.
_CRITICAL_PATH = {
    "wire": "wire",
    "decode_q_wait": "queue",
    "dispatch_q_wait": "queue",
    "sink_q_wait": "queue",
    "decode": "decode",
    "dispatch": "device",
    "device": "device",
    "writeback": "writeback",
}

SUMMARY_COLUMNS = ("wire", "queue", "decode", "device", "writeback")


def traces_key(stream: str) -> str:
    """Broker hash holding one spans blob per publishing process."""
    return TRACES_KEY_PREFIX + stream


def should_sample(trace_id: str, rate: float) -> bool:
    """Deterministic head sampling: same id + rate → same decision in
    every process. The hash is salted so the decision decorrelates from
    `partitions.stream_for`'s routing hash (both use CRC32 of the
    uri)."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    h = zlib.crc32(b"trace:" + str(trace_id).encode("utf-8", "replace"))
    return (h % 10000) < rate * 10000


class SpanExporter:
    """Ships a tracer's spans into the `traces:<stream>` broker hash.

    Retention and sampling are separate: *every* span lands in the
    bounded local ring (so a failure detected at the sink — the last
    stage — can still force-export the request's earlier spans), while
    head sampling plus the forced set gate what goes on the wire. The
    publish is a rolling window (HSET overwrite of this engine's field),
    so a lost publish is healed by the next one and replicated readers
    never contend."""

    def __init__(self, broker, stream: str, engine: str, tracer: Tracer,
                 sample: float = 0.01, interval_s: float = 0.5,
                 buffer_spans: int = 20000, max_publish_spans: int = 2000,
                 registry=None):
        self.broker = broker
        self.key = traces_key(stream)
        self.engine = engine
        self.tracer = tracer
        self.sample = float(sample)
        self.interval_s = float(interval_s)
        self.max_publish_spans = int(max_publish_spans)
        self._lock = threading.Lock()
        # ring entries: [span, head_sampled, counted_as_sampled]
        self._entries: "collections.deque[list]" = collections.deque(
            maxlen=max(16, int(buffer_spans)))
        self._forced: "collections.OrderedDict[str, None]" = \
            collections.OrderedDict()
        self._seq = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._down = False
        self._labels = {"engine": engine}
        reg = registry
        self._spans_total = self._sampled_total = self._dropped_total = None
        if reg is not None:
            self._spans_total = reg.counter(
                "serving_trace_spans_total",
                "spans observed by the fleet span exporter")
            self._sampled_total = reg.counter(
                "serving_trace_sampled_total",
                "spans selected for fleet export (head-sampled or "
                "force-sampled failed/SLO-violating requests)")
            self._dropped_total = reg.counter(
                "serving_trace_dropped_total",
                "spans evicted from the exporter's bounded local ring "
                "before they could be published")
        self._dropped = 0
        tracer.add_sink(self._on_span)

    # -- span intake -------------------------------------------------------
    def _head_sampled(self, span: Span) -> bool:
        if span.trace_id is not None:
            if should_sample(span.trace_id, self.sample):
                return True
        if span.trace_ids:
            return any(should_sample(t, self.sample)
                       for t in span.trace_ids)
        if span.trace_id is None and not span.trace_ids:
            # id-less spans (user/scoped spans) follow the global rate
            return self.sample >= 1.0
        return False

    def _on_span(self, span: Span) -> None:
        if self._spans_total is not None:
            self._spans_total.inc(**self._labels)
        head = self._head_sampled(span)
        with self._lock:
            if len(self._entries) == self._entries.maxlen:
                self._dropped += 1
                if self._dropped_total is not None:
                    self._dropped_total.inc(**self._labels)
            self._entries.append([span, head, False])

    def force(self, trace_ids: Sequence[str]) -> None:
        """Force-sample every span covering any of `trace_ids` (failed
        or SLO-violating requests), regardless of the head decision."""
        with self._lock:
            for t in trace_ids:
                self._forced[str(t)] = None
            while len(self._forced) > 8192:
                self._forced.popitem(last=False)

    def _is_forced(self, span: Span) -> bool:
        if span.trace_id is not None and span.trace_id in self._forced:
            return True
        if span.trace_ids:
            return any(t in self._forced for t in span.trace_ids)
        return False

    # -- publishing --------------------------------------------------------
    def publish_once(self) -> bool:
        with self._lock:
            selected: List[Span] = []
            for entry in self._entries:
                span, head, counted = entry
                if head or self._is_forced(span):
                    if not counted:
                        entry[2] = True
                        if self._sampled_total is not None:
                            self._sampled_total.inc(**self._labels)
                    selected.append(span)
            selected = selected[-self.max_publish_spans:]
            dropped = self._dropped
            self._seq += 1
            seq = self._seq
        epoch = self.tracer.epoch
        blob = {
            "engine": self.engine,
            "pid": os.getpid(),
            "seq": seq,
            "wall": time.time(),
            # wall time corresponding to the tracer's perf_counter
            # epoch: a *rough* anchor for blobs with no wire span —
            # cross-host comparisons go through the delta model instead
            "epoch_wall": time.time() - (time.perf_counter() - epoch),
            "dropped": dropped,
            "spans": [span_to_dict(s, epoch=epoch) for s in selected],
        }
        try:
            self.broker.hset(self.key, self.engine, json.dumps(blob))
        except Exception as e:  # noqa: BLE001 — broker outage: warn
            if not self._down:  # once, keep serving, retry next tick
                logger.warning("span exporter %s: publish failed (%s); "
                               "retrying each interval", self.engine, e)
                self._down = True
            return False
        if self._down:
            logger.info("span exporter %s: broker back, publishing "
                        "resumed", self.engine)
            self._down = False
        return True

    def stats(self) -> Dict[str, Any]:
        """Engine `/metrics` JSON section: the exporter's own health."""
        with self._lock:
            return {"sample": self.sample, "seq": self._seq,
                    "buffered_spans": len(self._entries),
                    "forced_ids": len(self._forced),
                    "dropped": self._dropped}

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.publish_once()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="serving-trace-exporter", daemon=True)
        self._thread.start()

    def stop(self, flush: bool = True) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
        self.tracer.remove_sink(self._on_span)
        if flush:
            self.publish_once()


def _covers(sd: Dict[str, Any], trace_id: str) -> bool:
    return (sd.get("id") == trace_id
            or trace_id in (sd.get("ids") or ()))


class TraceCollector:
    """Assembles one merged cross-process timeline per request from the
    `traces:<stream>` hash. Stateless over the broker — any gateway
    replica (or an engine's own frontend) can serve `GET /trace/<id>`
    with nothing but a broker handle."""

    def __init__(self, broker, stream: str):
        self.broker = broker
        self.key = traces_key(stream)

    # -- fetch -------------------------------------------------------------
    def blobs(self) -> Dict[str, Dict[str, Any]]:
        try:
            raw = self.broker.hgetall(self.key) or {}
        except Exception as e:  # noqa: BLE001 — a scrape during a
            logger.warning("trace collector: hgetall failed: %s", e)
            return {}           # broker blip degrades to "no spans"
        out = {}
        for eng, blob in raw.items():
            try:
                d = json.loads(blob)
            except (TypeError, ValueError):
                continue
            if isinstance(d, dict):
                out[str(eng)] = d
        return out

    # -- assembly ----------------------------------------------------------
    def _groups(self, trace_id: str):
        """Per publishing process: (engine, pid, [(span_dict, wall_start,
        wall_dur)]) with every span placed on the client wall
        timeline via the min-delta skew model."""
        groups = []
        for eng, blob in self.blobs().items():
            all_spans = [s for s in blob.get("spans", [])
                         if isinstance(s, dict)]
            mine = [s for s in all_spans if _covers(s, trace_id)]
            if not mine:
                continue
            # engine-wide minimum read-minus-ingest delta ≈ skew plus
            # the minimum wire latency this window observed
            deltas = []
            for s in all_spans:
                a = s.get("args") or {}
                if s.get("name") == "wire" and "t_ingest" in a \
                        and "t_read_wall" in a:
                    try:
                        deltas.append(float(a["t_read_wall"])
                                      - float(a["t_ingest"]))
                    except (TypeError, ValueError):
                        pass
            min_delta = min(deltas) if deltas else 0.0
            offset = None          # engine-relative seconds -> wall
            wire_fix = {}          # id(span dict) -> (start, dur) override
            for s in mine:
                a = s.get("args") or {}
                if s.get("name") == "wire" and "t_ingest" in a \
                        and "t_read_wall" in a:
                    t_ing = float(a["t_ingest"])
                    delta_r = float(a["t_read_wall"]) - t_ing
                    skew_free = max(0.0, delta_r - min_delta)
                    read_rel = float(s["s"]) + float(s["d"])
                    offset = (t_ing + skew_free) - read_rel
                    wire_fix[id(s)] = (read_rel - skew_free, skew_free)
                    break
            if offset is None:
                for s in mine:
                    a = s.get("args") or {}
                    if s.get("name") == "gateway_request" \
                            and "t_ingest" in a:
                        offset = float(a["t_ingest"]) - float(s["s"])
                        break
            if offset is None:
                # no anchor: fall back to the blob's rough wall epoch
                offset = float(blob.get("epoch_wall", 0.0))
            placed = []
            for s in mine:
                start_rel, dur = float(s["s"]), float(s["d"])
                if id(s) in wire_fix:
                    start_rel, dur = wire_fix[id(s)]
                placed.append((s, offset + start_rel, dur))
            groups.append((eng, blob.get("pid", eng), placed))
        return groups

    def assemble(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """Merged Chrome trace for one request, or None when no process
        published a span covering it. `anchor_wall` is the wall-clock
        second the trace's `ts=0` corresponds to (on the client/ingest
        clock), so callers can line events up against their own
        measurements."""
        groups = self._groups(trace_id)
        if not groups:
            return None
        anchor = min(w for _, _, placed in groups for _, w, _ in placed)
        events = []
        engines = []
        for eng, pid, placed in groups:
            engines.append(eng)
            for sd, wall, dur in placed:
                args = dict(sd.get("args") or {})
                if sd.get("id") is not None:
                    args["trace_id"] = sd["id"]
                if sd.get("ids"):
                    args["trace_ids"] = list(sd["ids"])
                if sd.get("parent") is not None:
                    args["parent"] = sd["parent"]
                events.append({
                    "name": sd.get("name", ""),
                    "cat": sd.get("cat", "serving"),
                    "ph": "X",
                    "ts": round((wall - anchor) * 1e6, 3),
                    "dur": round(dur * 1e6, 3),
                    "pid": pid,
                    # satellite: tid namespaced by (engine, thread) so
                    # merged views never collide across processes
                    "tid": f"{eng}:{sd.get('tid', '')}",
                    "args": args,
                })
        events.sort(key=lambda e: e["ts"])
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "request_id": trace_id, "anchor_wall": anchor,
                "engines": sorted(engines)}

    def summary(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """Critical-path breakdown (`wire / queue / decode / device /
        writeback` milliseconds) plus coverage of the gateway-observed
        request window."""
        groups = self._groups(trace_id)
        if not groups:
            return None
        cols = {c: 0.0 for c in SUMMARY_COLUMNS}
        placed_all = []
        gw_window = None
        n_spans = 0
        for eng, _pid, placed in groups:
            for sd, wall, dur in placed:
                n_spans += 1
                placed_all.append(Span(sd.get("name", ""),
                                       sd.get("cat", "serving"),
                                       wall, dur))
                col = _CRITICAL_PATH.get(sd.get("name", ""))
                if col is not None:
                    cols[col] += dur * 1e3
                if sd.get("name") == "gateway_request":
                    gw_window = (wall, wall + dur)
        lo = min(s.start for s in placed_all)
        hi = max(s.end for s in placed_all)
        window = gw_window or (lo, hi)
        out = {
            "request_id": trace_id,
            "engines": sorted(e for e, _, _ in groups),
            "spans": n_spans,
            "e2e_ms": round((window[1] - window[0]) * 1e3, 3),
            "critical_path_ms": {c: round(v, 3)
                                 for c, v in cols.items()},
            "coverage": round(span_coverage(placed_all, *window), 4),
        }
        return out
