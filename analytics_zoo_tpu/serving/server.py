"""ClusterServing — the serving engine.

Reference: Flink job `RedisSource -> inference map -> RedisSink`
(`ClusterServing.scala:55-68`), batching up to core count
(`ClusterServingInference.scala:152` batchInput), singleton model per task
manager (`FlinkInference.scala:41-52`), per-record failures degrade to "NaN"
(`:71-79`).

TPU redesign, pipelined (the default): the reference gets throughput from
Flink scheduling its source/map/sink operators concurrently; here the same
overlap comes from three explicit stages connected by bounded queues —

    reader ──▶ decode pool ──▶ dispatch ──▶ sink
         _decode_q        _dispatch_q   _sink_q

- **reader**: drains the broker stream (up to `batch_size` records within
  `batch_timeout_ms`) and hands raw record lists to the decode pool.
- **decode** (`decode_workers` threads): b64 → ndarray per record, grouped
  into shape-homogeneous host batches; a record that fails to decode turns
  into a "NaN" result batch without touching the device.
- **dispatch** (one thread): stacks each shape group straight to its
  power-of-two bucket (stacking to the bucket is free — the stack copies
  every record anyway) and calls `InferenceModel.predict_async`, which
  returns WITHOUT materializing: the device computes batch N while this
  thread stacks and dispatches batch N+1. With a multi-device model
  (`num_replicas>1`) this stage is the ROUTER: predict_async picks the
  least-outstanding-work replica under a per-replica in-flight bound, so
  N batches compute on N chips concurrently; per-replica dispatch counts
  land in `serving_replica_batches_total` and each dispatch span is
  tagged with its replica.
- **sink** (one thread): materializes completed results (the only blocking
  `np.asarray`) in COMPLETION order — a slow or poisoned replica never
  dams finished work from the others — encodes per-record values, and
  writes a whole batch back with ONE broker round trip (`hset_many`)
  plus one batched ack — instead of the old one `hset` per record.

Backpressure is the bounded queues: a slow device fills `_sink_q` and
stalls dispatch; a slow broker fills `_decode_q` and stalls the reader.
`stop()` drains: each stage is poisoned only after the previous stage has
joined, so in-flight work flows out before threads exit. Per-record
failure degradation ("NaN", batch survives) is preserved in every stage.

`pipelined=False` keeps the old single-thread drain→batch→predict→sink
loop — the baseline `bench_serving.py --concurrent` compares against.
"""

from __future__ import annotations

import collections
import json
import logging
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Union

import numpy as np

from analytics_zoo_tpu.observability.registry import (MetricsRegistry,
                                                      get_registry)
from analytics_zoo_tpu.observability.tracing import Tracer
from analytics_zoo_tpu.serving.breaker import (BackoffPolicy, CircuitBreaker,
                                               ResilientBroker)
from analytics_zoo_tpu.serving.broker import (Broker, connect_broker,
                                              decode_ndarray, encode_ndarray,
                                              new_consumer_name)
from analytics_zoo_tpu.serving.inference_model import (InferenceModel,
                                                       NoHealthyReplicaError)
from analytics_zoo_tpu.serving.timer import Timer

log = logging.getLogger("analytics_zoo_tpu.serving")

GROUP = "serving_group"

_STOP = object()          # stage poison pill


def _record_uris(records) -> List[str]:
    """Request ids (the result-hash uris) for a raw read batch — the
    trace ids every stage span is tagged with. Malformed records fall
    back to the broker record id, matching `_decode_records`."""
    out = []
    for rid, rec in records:
        out.append(rec.get("uri", rid) if isinstance(rec, dict)
                   else str(rid))
    return out


class _Batch:
    """One shape-homogeneous unit of pipeline work."""

    __slots__ = ("ids", "uris", "arrays", "t0", "pending", "nan", "t_enq",
                 "stacked", "valid_n", "shed", "bucket", "t_dispatch",
                 "stream")

    def __init__(self, ids, uris, arrays, t0, nan=False, stacked=None,
                 valid_n=None, shed=False, stream=None):
        self.ids = ids            # broker record ids (for the batched ack)
        self.uris = uris          # result-hash fields
        self.arrays = arrays      # decoded host arrays (None once stacked)
        self.t0 = t0              # read timestamp: end-to-end latency base
        self.pending = None       # PendingPrediction after dispatch
        self.nan = nan            # failure batch: sink writes "NaN"
        self.t_enq = t0           # last enqueue timestamp (queue-wait spans)
        self.stacked = stacked    # bucket-shaped buffer (zero-copy decode)
        self.valid_n = valid_n    # real rows in `stacked` (rest is pad)
        self.shed = shed          # admission-shed batch: sink writes "SHED"
        self.bucket = None        # dispatched bucket (cost-model key)
        self.t_dispatch = None    # dispatch timestamp (cost-model base)
        self.stream = stream      # source partition stream (None = base)


class ClusterServing:
    def __init__(self, model: InferenceModel,
                 broker: Union[Broker, str, None] = None,
                 stream: str = "serving_stream",
                 batch_size: int = 32, batch_timeout_ms: int = 5,
                 output_filter: Optional[str] = None,
                 pipelined: bool = True, decode_workers: int = 2,
                 queue_depth: int = 8,
                 registry: Optional[MetricsRegistry] = None,
                 tracer: Optional[Tracer] = None,
                 supervise: bool = True,
                 failure_threshold: int = 3,
                 probe_interval_s: float = 0.5,
                 latency_factor: float = 8.0,
                 latency_floor_ms: float = 50.0,
                 breaker_failure_threshold: int = 3,
                 breaker_reset_s: float = 1.0,
                 sink_buffer_batches: int = 256,
                 slo=None, zero_copy_decode: bool = True,
                 engine_id: Optional[str] = None,
                 claim_min_idle_s: float = 30.0,
                 claim_interval_s: float = 5.0,
                 heartbeat_interval_s: float = 2.0,
                 batch_policy: str = "adaptive",
                 deadline_ms: Optional[float] = None,
                 batch_margin_ms: float = 2.0,
                 admission_tiers=None,
                 admission_field: str = "tier",
                 shed_backlog: Optional[int] = None,
                 model_version: Optional[int] = None,
                 partitions: int = 1,
                 reshard: bool = False,
                 partition_lease_ttl_s: float = 5.0,
                 trace_sample: float = 0.0,
                 trace_buffer_spans: int = 20000,
                 trace_export_interval_s: float = 0.5,
                 fleet_metrics_interval_s: float = 2.0):
        """Fault-tolerance knobs (ISSUE 5; the rest is PR 1-4 surface):
        `supervise` starts a `ReplicaSupervisor` over a replica pool
        (quarantine after `failure_threshold` consecutive failures or
        `failure_threshold` latency outliers past `latency_factor`× the
        pool median; canary-probe revival every `probe_interval_s`).
        The engine's reader/sink broker connections wear a circuit
        breaker (`breaker_*`), and failed sink writebacks buffer up to
        `sink_buffer_batches` before the oldest is shed (shed records
        stay unacked and redeliver).

        `slo` (ISSUE 6): declarative objectives — an
        `observability.slo.SLOObjectives` — evaluated over the engine's
        own latency/outcome metrics; the tracker feeds `health()` / the
        frontend's `/healthz` and publishes burn-rate gauges.

        `zero_copy_decode` (ISSUE 9): decode writes records straight
        into preallocated bucket-shaped batch buffers (no per-record
        ndarray allocation, no dispatch-stage np.stack). False restores
        the per-record decode + stack path — kept ONLY as the
        bench_serving A/B baseline.

        Fleet mode (ISSUE 10): `engine_id` names this engine as ONE of
        N co-consumers of the stream. It becomes the consumer-group
        consumer name, an `engine` label on the `serving_*` metric
        series and pipeline spans, and the heartbeat identity published
        to `engines:<stream>` every `heartbeat_interval_s` (the fleet
        gateway's liveness source; a clean stop deregisters). The
        reader additionally runs a stale-pending claim sweep every
        `claim_interval_s`: entries another consumer read but never
        acked — a killed peer's in-flight batches — become claimable
        after `claim_min_idle_s` and redeliver HERE (XAUTOCLAIM on
        Redis, window-parity on the in-process brokers), so an engine
        crash costs capacity, never accepted records. The sweep runs
        even with `engine_id=None` (single-engine redelivery after a
        restart is the same mechanism); heartbeats and metric labels
        are fleet-mode only, keeping the standalone metric schema
        byte-identical.

        Elastic serving (ISSUE 11): `batch_policy` selects the reader's
        micro-batching controller — "adaptive" (default) plans each
        dispatch from the live per-bucket cost model and the oldest
        queued record's `deadline_ms` budget (no deadline configured ⇒
        behaves exactly like the legacy policy; with `slo.latency_ms`
        set the deadline defaults to it), "fixed" is the legacy
        straggler sweep, "static" always pads to the largest reachable
        bucket (the bench A/B strawman). `admission_tiers` (lowest
        priority first) makes the reader tier-aware: records carry a
        tier name in `admission_field`, higher tiers dispatch first,
        and past `shed_backlog` stream depth the reader sheds
        lowest-tier records with an explicit "SHED" result (committed
        and acked — an answered rejection, never a silent drop; the
        top tier is never shed). The stack's own producers (frontend,
        `InputQueue`) always write the native "tier" record key;
        `admission_field` points the reader at a FOREIGN producer's
        spelling, with "tier" kept as the fallback so mixed traffic
        never inverts priorities.

        Partitioned request plane (ISSUE 16): `partitions` shards the
        stream N ways (`<stream>.p<i>`, records routed by uri hash —
        see serving/partitions.py). The engine owns a partition SET via
        a lease table in the broker; the reader renews/acquires/sheds
        leases inline (paced like the claim sweep) and round-robins
        reads across the streams it owns. Lease expiry generalizes the
        PR 10 claim sweep from records to whole partitions: a dead
        peer's partitions move here after `partition_lease_ttl_s` of
        silence, then its unacked records redeliver through the
        ordinary per-stream sweep. `partitions=1` (default) keeps the
        legacy single-stream behavior byte-identical. Changing the
        count against a live lease table is refused unless `reshard`
        is set (records already routed under the old count would
        strand).

        Fleet observability plane (ISSUE 17): `trace_sample` > 0 turns
        on cross-process tracing — the engine continues each stamped
        record's trace (a "wire" span from the client's ingest
        timestamp to the reader claim, then the existing stage spans
        plus "device"/"writeback"), embeds a compact per-hop timing
        summary in every result row, and a `SpanExporter` ships the
        head-sampled window (plus force-sampled failed / SLO-violating
        requests) into the `traces:<stream>` broker hash every
        `trace_export_interval_s` for gateway-side assembly. The local
        span ring is bounded at `trace_buffer_spans`. Independently,
        a fleet engine (`engine_id` set) publishes its full registry
        snapshot into `metrics:<stream>` every
        `fleet_metrics_interval_s` (0 disables) so a gateway scrape
        aggregates the whole fleet."""
        self.model = model
        self.broker = broker if isinstance(broker, Broker) \
            else connect_broker(broker)
        self.registry = registry if registry is not None else get_registry()
        # the reader sits in a blocking read for up to ~50ms per cycle
        # and the sink writes results concurrently: on single-socket
        # transports each needs its own connection, and the caller's
        # broker stays free for frontends/clients sharing it. Both wear
        # a circuit breaker: a dead broker fast-fails instead of paying
        # a connect timeout per pipeline cycle.
        if pipelined:
            # a caller may already hand us a ResilientBroker — wrap its
            # INNER transport rather than double-wrapping (two breakers
            # would fight and the broker.<op> fault points would fire
            # twice per call)
            base = self.broker.inner \
                if isinstance(self.broker, ResilientBroker) else self.broker
            self.reader_broker: Broker = ResilientBroker(
                base.clone(), role="reader",
                breaker=CircuitBreaker(
                    "reader", failure_threshold=breaker_failure_threshold,
                    reset_timeout_s=breaker_reset_s,
                    registry=self.registry))
            self.sink_broker: Broker = ResilientBroker(
                base.clone(), role="sink",
                breaker=CircuitBreaker(
                    "sink", failure_threshold=breaker_failure_threshold,
                    reset_timeout_s=breaker_reset_s,
                    registry=self.registry))
        else:
            self.reader_broker = self.broker
            self.sink_broker = self.broker
        self.stream = stream
        # e.g. "topN(5)" — the reference's PostProcessing filter grammar;
        # validated here so a bad spec fails at construction, not as
        # per-record NaNs mid-stream
        if output_filter is not None:
            from analytics_zoo_tpu.serving.pre_post import apply_filter
            apply_filter(np.zeros(2, np.float32), output_filter)
        self.output_filter = output_filter
        self.result_key = f"result:{stream}"
        self.batch_size = batch_size
        self.batch_timeout_ms = batch_timeout_ms
        # fleet identity: the engine id doubles as the consumer-group
        # consumer name, so XPENDING/XAUTOCLAIM attribute in-flight work
        # to a nameable engine (a fresh uuid per restart would orphan
        # nothing — claims go by idle time — but operators read these)
        self.engine_id = engine_id
        self.consumer = engine_id or new_consumer_name()
        self._labels = {"engine": engine_id} if engine_id else {}
        # serving precision (ISSUE 12): a NON-default dtype (int8
        # quantized serving, bf16 weights) labels every serving_*
        # series and span this engine publishes, same convention as the
        # fleet `engine` label — the default-f32 schema stays
        # byte-identical, and an int8-vs-bf16 A/B separates by label
        self.serving_dtype = getattr(model, "serving_dtype", "float32")
        if self.serving_dtype != "float32":
            self._labels["serving_dtype"] = self.serving_dtype
        self.claim_min_idle_s = float(claim_min_idle_s)
        self.claim_interval_s = float(claim_interval_s)
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        # partitioned request plane (ISSUE 16)
        from analytics_zoo_tpu.serving.partitions import (
            PartitionLeaseTable, validate_partitions)
        self.partitions = validate_partitions(partitions)
        self.lease_table = None
        if self.partitions > 1:
            if not pipelined:
                raise ValueError(
                    "partitions > 1 needs the pipelined engine (the "
                    "legacy serve_once loop reads one stream)")
            if engine_id is None:
                raise ValueError(
                    "partitions > 1 needs an engine_id: partition "
                    "leases are owned by a nameable engine")
            # lease I/O rides the reader's broker connection: polls run
            # in the reader thread (paced like the claim sweep) and the
            # final release runs after the reader joins — never two
            # threads on one socket
            self.lease_table = PartitionLeaseTable(
                self.reader_broker, stream, self.partitions,
                owner=engine_id, ttl_s=partition_lease_ttl_s,
                registry=self.registry)
            # the resharding gate: refuse a partition count that
            # disagrees with the live lease table unless the operator
            # explicitly asked to reshard
            self.lease_table.ensure_meta(reshard=reshard)
        self._lease_poll_s = max(0.05, float(partition_lease_ttl_s) / 3.0)
        self._killed = False
        self.pipelined = pipelined
        self.zero_copy_decode = zero_copy_decode
        self.decode_workers = max(1, decode_workers)
        self.queue_depth = max(1, queue_depth)
        # versioned serving (ISSUE 14): which checkpoint version the
        # model currently serves (None = unversioned weights). The
        # rollout agent advances it AFTER a successful canary, and the
        # heartbeat row carries it — reporting the new version IS the
        # engine's "converted" signal to the rollout controller.
        self.model_version = model_version
        self._stop = threading.Event()
        # intake pause (rollout drain): while set, the reader neither
        # reads nor claim-sweeps — in-hand work flows out, the broker
        # queues (or peers drain) new work, and a swap sees no mixed-
        # version batches
        self._intake_paused = threading.Event()
        self._threads: List[threading.Thread] = []
        self._decode_q: "queue.Queue" = queue.Queue(maxsize=self.queue_depth)
        self._dispatch_q: "queue.Queue" = queue.Queue(
            maxsize=self.queue_depth)
        self._sink_q: "queue.Queue" = queue.Queue(maxsize=self.queue_depth)
        self.batch_timer = Timer("batch")          # end-to-end per batch
        self.decode_timer = Timer("decode")
        self.dispatch_timer = Timer("dispatch")
        self.sink_timer = Timer("sink")
        self.records_served = 0
        self.records_read = 0
        self._counter_lock = threading.Lock()
        self.tracer = tracer
        # reconnect backoff for the reader loop (capped exponential with
        # jitter — replaces the fixed 1s warn-loop)
        self.reader_backoff = BackoffPolicy()
        # failed sink writebacks, oldest first: (mapping, ids, t0, t_work)
        # entries awaiting a live broker. Sink-thread-only; the registry
        # gauge reads len() which is safe anywhere.
        self.sink_buffer_batches = max(1, int(sink_buffer_batches))
        self._wb_buffer: "collections.deque" = collections.deque()
        self._sink_down = False
        # record ids this engine has read/claimed but not yet acked:
        # the claim sweep (and the in-process brokers' redelivery
        # window) must not hand the engine its OWN in-flight work back
        # while a slow batch computes. Reader adds, sink removes on ack
        # — and on shed, where redelivery (to a peer) is the contract.
        self._inflight_ids: set = set()
        self._inflight_lock = threading.Lock()
        self.probe_interval_s = probe_interval_s
        self._wire_registry()
        self.slo = None
        if slo is not None:
            from analytics_zoo_tpu.observability.slo import (SLOObjectives,
                                                             SLOTracker)
            objectives = slo if isinstance(slo, SLOObjectives) \
                else SLOObjectives(**slo)
            if not objectives.empty:
                self.slo = SLOTracker(objectives, registry=self.registry)
        # adaptive micro-batching (ISSUE 11): the controller that
        # replaces the fixed batch_size/batch_timeout_ms policy. With no
        # explicit deadline the SLO latency objective (what the operator
        # already promised) is the natural budget.
        from analytics_zoo_tpu.serving.elastic import (
            AdaptiveBatchController, TierTable)
        if deadline_ms is None and self.slo is not None \
                and self.slo.objectives.latency_ms is not None:
            deadline_ms = self.slo.objectives.latency_ms
        self.batcher = AdaptiveBatchController(
            self.model.buckets, self.batch_size, self.batch_timeout_ms,
            policy=batch_policy, deadline_ms=deadline_ms,
            margin_ms=batch_margin_ms, registry=self.registry,
            labels=self._labels)
        # tiered admission (ISSUE 11): reader-side tier ordering + shed
        self.admission_field = admission_field
        self.tier_table = None
        if admission_tiers:
            self.tier_table = admission_tiers \
                if isinstance(admission_tiers, TierTable) \
                else TierTable(admission_tiers)
        self.shed_backlog = int(shed_backlog) if shed_backlog else None
        self._admission_out = self.registry.counter(
            "serving_admission_total",
            "admission decisions by outcome (accepted, rejected, shed) "
            "and tier")
        # rate-limited backlog probe (reader thread only)
        self._backlog_cache: Optional[int] = None
        self._backlog_t = 0.0
        self.supervisor = None
        if supervise and self._multi_replica:
            from analytics_zoo_tpu.serving.supervisor import \
                ReplicaSupervisor
            self.supervisor = ReplicaSupervisor(
                model, failure_threshold=failure_threshold,
                latency_factor=latency_factor,
                latency_floor_ms=latency_floor_ms,
                probe_interval_s=probe_interval_s,
                registry=self.registry)
        # fleet heartbeat (ISSUE 10): its own broker connection — the
        # reader sits in XREADGROUP block windows and the sink may be
        # mid-writeback; membership must never queue behind either
        self.heartbeat = None
        if engine_id is not None and self.heartbeat_interval_s > 0:
            from analytics_zoo_tpu.serving.fleet import HeartbeatPublisher
            base = self.broker.inner \
                if isinstance(self.broker, ResilientBroker) else self.broker
            self.heartbeat = HeartbeatPublisher(
                base.clone(), self.stream, engine_id,
                self._heartbeat_payload,
                interval_s=self.heartbeat_interval_s,
                registry=self.registry)
        # fleet observability plane (ISSUE 17): span exporter + fleet
        # metrics publisher, each on its OWN broker connection — the
        # reader blocks in XREADGROUP windows and the sink may be
        # mid-writeback; telemetry must never queue behind either
        if not 0.0 <= float(trace_sample) <= 1.0:
            raise ValueError(
                f"trace_sample must be in [0, 1], got {trace_sample}")
        self.trace_sample = float(trace_sample)
        self.trace_exporter = None
        self.fleet_metrics = None
        obs_base = self.broker.inner \
            if isinstance(self.broker, ResilientBroker) else self.broker
        if self.trace_sample > 0:
            if self.tracer is None:
                self.tracer = Tracer(max_spans=int(trace_buffer_spans),
                                     registry=self.registry,
                                     engine=self.consumer)
            elif self.tracer.engine is None:
                self.tracer.engine = self.consumer
            from analytics_zoo_tpu.serving.trace_plane import SpanExporter
            self.trace_exporter = SpanExporter(
                obs_base.clone(), self.stream, self.consumer,
                self.tracer, sample=self.trace_sample,
                interval_s=float(trace_export_interval_s),
                buffer_spans=int(trace_buffer_spans),
                registry=self.registry)
        if engine_id is not None and float(fleet_metrics_interval_s) > 0:
            from analytics_zoo_tpu.serving.fleet_metrics import \
                FleetMetricsPublisher
            self.fleet_metrics = FleetMetricsPublisher(
                obs_base.clone(), self.stream, engine_id, self.registry,
                interval_s=float(fleet_metrics_interval_s))

    def _heartbeat_payload(self) -> dict:
        """What each beat tells the gateway: readiness (the same
        aggregation /healthz would compute locally) plus the throughput
        counters a fleet dashboard sums — and, with SLO objectives
        configured, the engine's current burn rate, which is the
        autoscaler's scale-up signal (ISSUE 11: the gateway cannot see
        this engine's latency histograms across the process boundary;
        the heartbeat is the telemetry bus)."""
        h = self.health()
        out = {"ready": bool(h.get("ready")),
               "healthy_replicas": h.get("healthy_replicas"),
               "records_served": self.records_served,
               "records_read": self.records_read}
        if self.model_version is not None:
            # the rollout controller's convergence signal (ISSUE 14):
            # an engine reports a new version ONLY after the swap's
            # canary passed — the beat is the commit
            out["model_version"] = self.model_version
        if self.lease_table is not None:
            # the gateway's partition-coverage view (ISSUE 16): which
            # partitions this engine reads right now — summed across
            # beats, an operator sees holes before clients do
            out["partitions_owned"] = self.lease_table.owned()
        slo = h.get("slo")
        if isinstance(slo, dict):
            burns = [v.get("burn_rate", 0.0) for v in slo.values()
                     if isinstance(v, dict) and "burn_rate" in v]
            if burns:
                out["slo_burn"] = max(burns)
            out["slo_met"] = bool(slo.get("met", True))
        return out

    def _wire_registry(self):
        """Mirror the engine's private Timers into the process-wide
        registry (the telemetry spine): per-stage histograms via Timer
        observers, record counters by outcome, and live queue-depth
        gauges evaluated at snapshot/scrape time."""
        reg = self.registry
        stage_hist = reg.histogram(
            "serving_stage_ms",
            "per-stage serving pipeline duration (decode, dispatch, sink, "
            "predict)")
        batch_hist = reg.histogram(
            "serving_batch_ms",
            "end-to-end latency per pipeline batch, broker read to result "
            "writeback")
        self._records_total = reg.counter(
            "serving_records_total",
            "records through the serving engine, by outcome (read, "
            "served, failed, duplicate, shed)")
        # multi-device router telemetry: families register unconditionally
        # (stable /metrics schema); series appear only when a replica pool
        # is actually routing, so single-replica output stays unchanged
        self._replica_batches = reg.counter(
            "serving_replica_batches_total",
            "batches dispatched to each model replica, by replica index")
        replica_gauge = reg.gauge(
            "serving_replica_inflight",
            "routed-but-unmaterialized batches per model replica (live)")
        # every closure this engine installs is remembered so stop() can
        # compare-and-release exactly these — never a newer engine's
        self._gauge_installs = []       # (gauge, fn, labels, freeze)
        self._multi_replica = getattr(self.model, "num_replicas", 1) > 1
        if self._multi_replica:
            for i in range(self.model.num_replicas):
                fn = (lambda _i=i: self.model.replica_inflight(_i))
                replica_gauge.set_function(fn, replica=str(i))
                self._gauge_installs.append(
                    (replica_gauge, fn, {"replica": str(i)}, False))
        # fleet mode threads the engine id through every serving series
        # (self._labels is {} standalone, so the default schema is
        # byte-identical); a fleet-aggregate view is the label-summed
        # family, a per-engine view is one series
        labels = self._labels
        for timer, stage in ((self.decode_timer, "decode"),
                             (self.dispatch_timer, "dispatch"),
                             (self.sink_timer, "sink")):
            timer.add_observer(
                lambda s, _st=stage: stage_hist.observe(
                    s * 1e3, stage=_st, **labels))
        self.batch_timer.add_observer(
            lambda s: batch_hist.observe(s * 1e3, **labels))
        # the model (and its predict Timer) may outlive/be shared across
        # ClusterServing instances — attach the mirror exactly once.
        # Fleet mode labels the predict series like every other stage
        # (the fleet aggregator needs per-engine attribution); the
        # standalone schema stays byte-identical.
        if not getattr(self.model.timer, "_registry_mirrored", False):
            self.model.timer.add_observer(
                lambda s, _l=dict(labels): stage_hist.observe(
                    s * 1e3, stage="predict", **_l))
            self.model.timer._registry_mirrored = True
        qd = reg.gauge("serving_queue_depth",
                       "live depth of each inter-stage pipeline queue")
        for q, fn in (("decode", self._decode_q.qsize),
                      ("dispatch", self._dispatch_q.qsize),
                      ("sink", self._sink_q.qsize)):
            qd.set_function(fn, queue=q)
            # frozen (not removed) on stop: post-run readers (the bench)
            # still see the drained depths
            self._gauge_installs.append((qd, fn, {"queue": q}, True))
        # fleet telemetry (ISSUE 10): cross-engine redelivery + the
        # idempotent-writeback duplicate ledger
        self._claimed_records = reg.counter(
            "serving_claimed_records_total",
            "stale pending records claimed from dead peer consumers by "
            "this engine's claim sweep")
        # fault-tolerance telemetry (ISSUE 5)
        self._reconnects = reg.counter(
            "serving_broker_reconnects_total",
            "successful broker reconnects after an outage, by role")
        self._shed_records = reg.counter(
            "serving_sink_shed_records_total",
            "result records shed from the sink's writeback buffer at "
            "its bound (unacked; the broker redelivers them)")
        wb_gauge = reg.gauge(
            "serving_sink_buffered_batches",
            "writeback batches buffered while the broker is down (live)")
        wb_fn = (lambda buf=self._wb_buffer: len(buf))
        wb_gauge.set_function(wb_fn)
        self._gauge_installs.append((wb_gauge, wb_fn, {}, True))
        # quantized serving (ISSUE 12): the honest weight-byte price
        # per precision — an int8 model reads ~4x under its f32 source
        # here, which is the HBM-bandwidth story behind the speedup
        weight_fn = getattr(self.model, "weight_bytes", None)
        if callable(weight_fn):
            wtg = reg.gauge(
                "serving_weight_bytes",
                "logical bytes of the served model's weight leaves, "
                "labeled by serving dtype (int8 quantization prices "
                "weights at 1 byte/element)")
            # engine label included like every other serving_* series
            # (fleet aggregation must separate per-engine weight bytes)
            wlabels = dict(self._labels,
                           serving_dtype=self.serving_dtype)
            wtg.set_function(weight_fn, **wlabels)
            self._gauge_installs.append((wtg, weight_fn, wlabels, True))
        # versioned serving (ISSUE 14): the live checkpoint version.
        # Family registers unconditionally (stable schema); the series
        # appears only once a versioned model serves, value = version
        # number — a scrape sees the fleet converge as every engine's
        # series reaches the same value
        self._version_gauge = reg.gauge(
            "serving_model_version",
            "checkpoint version this engine currently serves (value is "
            "the version number; absent for unversioned weights)")
        if self.model_version is not None:
            self._version_gauge.set(float(self.model_version),
                                    **self._labels)

    def _enqueue(self, q: "queue.Queue", batch: _Batch):
        """Stamp the enqueue time (the consumer's queue-wait span starts
        here — a blocking put under backpressure counts as wait) and put.
        The put blocks in bounded slices (the backpressure contract is
        unchanged — drain still clears it) so a wedged consumer is a
        visible timed loop, never an unbounded block."""
        batch.t_enq = time.perf_counter()
        while True:
            try:
                q.put(batch, timeout=0.25)
                return
            except queue.Full:
                continue

    # -- health (frontend 503 gate + supervisor view) ----------------------
    def healthy_replicas(self) -> Optional[int]:
        """Replicas currently accepting work; None when the model has no
        notion of health (a duck-typed model without the pool API)."""
        fn = getattr(self.model, "healthy_replicas", None)
        return fn() if callable(fn) else None

    @property
    def retry_after_s(self) -> int:
        """What a 503 should tell clients: revival happens on the canary
        probe cadence, so retrying sooner than that is wasted."""
        return max(1, int(round(self.probe_interval_s + 0.5)))

    def health(self) -> dict:
        """Readiness aggregation for `/healthz` (ISSUE 6): the engine is
        READY when its stage threads run, at least one replica accepts
        work, and neither broker breaker is open. SLO status rides along
        in the payload (a burning error budget is an alarm, not a
        reason to eject the pod from rotation — operators page on
        `slo_burn_rate`, load balancers act on `ready`)."""
        healthy = self.healthy_replicas()
        replicas_ok = healthy is None or healthy > 0
        breakers = {}
        breakers_ok = True
        for role, br in (("reader", self.reader_broker),
                         ("sink", self.sink_broker)):
            breaker = getattr(br, "breaker", None)
            if breaker is not None:
                state = breaker.state
                breakers[role] = state
                breakers_ok = breakers_ok and state != "open"
        running = bool(self._threads) and not self._stop.is_set() \
            and self.is_alive()
        out = {
            "ready": bool(running and replicas_ok and breakers_ok),
            "running": running,
            "healthy_replicas": healthy,
            "breakers": breakers,
        }
        if self.model_version is not None:
            out["model_version"] = self.model_version
        if not running:
            out["reason"] = "engine not running"
        elif not replicas_ok:
            out["reason"] = "every model replica is quarantined"
        elif not breakers_ok:
            out["reason"] = "broker circuit open"
        if self.supervisor is not None:
            out["supervisor"] = self.supervisor.stats()
        if self.slo is not None:
            try:
                out["slo"] = self.slo.evaluate()
            except Exception:  # noqa: BLE001 — health must always answer
                out["slo"] = None
        return out

    # -- rollout hooks (ISSUE 14; driven by serving/rollout.py) ------------
    def set_model_version(self, version: int):
        """Advance the served version (rollout agent, post-canary): the
        gauge and the next heartbeat both report it — the heartbeat is
        what tells the controller this engine converted."""
        self.model_version = int(version)
        self._version_gauge.set(float(version), **self._labels)

    def pause_intake(self):
        """Stop the reader pulling NEW work (reads and claim sweeps);
        everything already in hand keeps flowing to the sink. The
        broker buffers — or, in a fleet, live peers drain — what
        arrives meanwhile. The rollout agent's drain barrier."""
        self._intake_paused.set()

    def resume_intake(self):
        self._intake_paused.clear()

    def quiesce(self, timeout_s: float = 10.0) -> bool:
        """Block (bounded) until every record this engine has read is
        committed — in-flight set empty and the stage queues drained.
        Call after `pause_intake()`; True = the pipeline is empty and a
        swap sees no mixed-version batch. False (timeout / engine
        stopping) means the caller may still swap: a batch dispatched
        pre-swap holds its own params reference, so the tail of the
        old version simply finishes on the old weights."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        while time.monotonic() < deadline:
            with self._inflight_lock:
                inflight = len(self._inflight_ids)
            if inflight == 0 and self._decode_q.empty() \
                    and self._dispatch_q.empty() and self._sink_q.empty():
                return True
            if self._stop.wait(0.02):
                return False
        return False

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ClusterServing":
        if self.supervisor is not None:
            self.supervisor.start()
        if self.slo is not None:
            # self-driving evaluation: violation detection must not
            # depend on an external scrape happening more often than
            # the SLO window
            self.slo.start_auto()
        if self.pipelined:
            specs = [("serving-reader", self._reader_loop)]
            specs += [(f"serving-decode-{i}", self._decode_loop)
                      for i in range(self.decode_workers)]
            specs += [("serving-dispatch", self._dispatch_loop),
                      ("serving-sink", self._sink_loop)]
            for name, target in specs:
                t = threading.Thread(target=target, name=name, daemon=True)
                t.start()
                self._threads.append(t)
        else:
            t = threading.Thread(target=self.run, daemon=True)
            t.start()
            self._threads.append(t)
        if self.heartbeat is not None:
            # after the stage threads: the first beat already reports
            # ready=True instead of a one-interval false negative
            self.heartbeat.start()
        if self.trace_exporter is not None:
            self.trace_exporter.start()
        if self.fleet_metrics is not None:
            self.fleet_metrics.start()
        return self

    def is_alive(self) -> bool:
        """True while every stage thread is still running."""
        return bool(self._threads) and all(
            t.is_alive() for t in self._threads)

    def stop(self):
        """Drain and join: each stage is poisoned only after every thread
        feeding it has exited, so work already read from the broker flows
        through to the sink before shutdown."""
        self._stop.set()
        if self.heartbeat is not None:
            # first: deregister from the fleet so the gateway routes
            # around this engine before its drain even starts
            self.heartbeat.stop(deregister=True)
        if self.slo is not None:
            self.slo.stop_auto()
        if self.supervisor is not None:
            # first: a mid-drain revival would reshuffle routing under
            # the draining dispatcher for no benefit
            self.supervisor.stop()
        if not self.pipelined:
            for t in self._threads:
                t.join(timeout=10)
            self._threads = []
            self._unwire_gauges()
            return
        readers = [t for t in self._threads if "reader" in t.name]
        decoders = [t for t in self._threads if "decode" in t.name]
        dispatchers = [t for t in self._threads if "dispatch" in t.name]
        sinks = [t for t in self._threads if "sink" in t.name]
        for t in readers:
            t.join(timeout=10)
        if self.lease_table is not None:
            # after the reader joins (its thread owns the lease broker
            # connection): give the partitions back so peers rebalance
            # now instead of waiting out the ttl
            try:
                self.lease_table.release()
            except Exception:  # noqa: BLE001 — peers expire the leases
                pass
        self._poison(self._decode_q, len(decoders))
        for t in decoders:
            t.join(timeout=10)
        self._poison(self._dispatch_q, len(dispatchers))
        for t in dispatchers:
            t.join(timeout=10)
        self._poison(self._sink_q, len(sinks))
        for t in sinks:
            t.join(timeout=10)
        self._threads = []
        self._unwire_gauges()
        # observability plane: final flush AFTER the sink joined (the
        # last batch's spans and counters are in), BEFORE the broker
        # handles close
        if self.trace_exporter is not None:
            self.trace_exporter.stop(flush=True)
        if self.fleet_metrics is not None:
            self.fleet_metrics.stop(flush=True)
        hb_broker = self.heartbeat.broker if self.heartbeat else None
        te_broker = self.trace_exporter.broker \
            if self.trace_exporter else None
        fm_broker = self.fleet_metrics.broker \
            if self.fleet_metrics else None
        for br in (self.reader_broker, self.sink_broker, hb_broker,
                   te_broker, fm_broker):
            if br is not None and br is not self.broker \
                    and hasattr(br, "close"):
                try:
                    br.close()
                except Exception:  # noqa: BLE001 — shutdown best effort
                    pass

    def kill(self):
        """Crash analogue for chaos tests (ISSUE 16): stop every stage
        WITHOUT the drain, the heartbeat deregistration, or the lease
        release a clean `stop()` performs. Work in hand is abandoned
        uncommitted — its records stay in the broker PEL and this
        engine's partition leases sit in the table until they age out,
        exactly the state a SIGKILLed engine leaves behind for peer
        takeover (lease expiry + claim sweep) to recover."""
        self._killed = True
        self._stop.set()
        if self.heartbeat is not None:
            self.heartbeat.stop(deregister=False)
        # no flush: a SIGKILLed process publishes nothing on the way
        # out — whatever the last interval shipped is what survives
        if self.trace_exporter is not None:
            self.trace_exporter.stop(flush=False)
        if self.fleet_metrics is not None:
            self.fleet_metrics.stop(flush=False)
        if self.slo is not None:
            self.slo.stop_auto()
        if self.supervisor is not None:
            self.supervisor.stop()
        for q in (self._decode_q, self._dispatch_q, self._sink_q):
            self._poison(q, self.decode_workers + 2)
        for t in self._threads:
            t.join(timeout=10)
        self._threads = []
        if self.lease_table is not None:
            # unhook the local gauge only; the broker rows are the
            # corpse the takeover path must find
            self.lease_table.abandon()
        self._unwire_gauges()

    def _unwire_gauges(self):
        """Post-drain registry cleanup (runs AFTER the stage joins, so
        values reflect the drained engine, not a mid-drain snapshot):
        every closure this engine installed is compare-and-released —
        left in the process-wide registry they would pin this engine
        (the replica closures hold N device-resident param copies) for
        the process lifetime and keep exporting series that read a
        stopped engine, while a series a NEWER engine has since claimed
        is left alone. Replica series disappear; queue depths freeze at
        their drained values for post-run readers (the bench)."""
        installs, self._gauge_installs = self._gauge_installs, []
        for gauge, fn, labels, freeze in installs:
            gauge.release_function(fn, freeze=freeze, **labels)

    @staticmethod
    def _poison(q: "queue.Queue", n: int):
        """Deliver `n` stop pills without ever wedging stop(): if the
        queue stays full (its consumer is stuck, e.g. a stalled device
        under dispatch), drop queued work and keep trying for a bounded
        window — unacked records redeliver, and a bounded shutdown beats
        the drain guarantee once a stage is already wedged."""
        for _ in range(n):
            deadline = time.monotonic() + 5.0
            while True:
                try:
                    q.put(_STOP, timeout=0.25)
                    break
                except queue.Full:
                    if time.monotonic() > deadline:
                        break
                    try:
                        dropped = q.get_nowait()
                    except queue.Empty:
                        pass
                    else:
                        # a dropped batch may hold a routed pending whose
                        # replica permit only releases on consumption —
                        # abandon it (records redeliver; the permit must
                        # not leak into the engine-outliving model)
                        abandon = getattr(
                            getattr(dropped, "pending", None),
                            "abandon", None)
                        if abandon is not None:
                            abandon()

    def _filter_inflight(self, records, stream=None):
        """Drop records this engine already holds un-acked (its own
        slow in-flight work coming back through the claim sweep or a
        redelivery window) and register the rest. The sink releases ids
        on ack — and on shed, where redelivering (ideally to a peer)
        is exactly the contract. Ids key by (stream, rid): partition
        streams assign record ids independently, so a bare rid is not
        unique across the partition set."""
        if not records:
            return []
        stream = stream or self.stream
        out = []
        with self._inflight_lock:
            for rid, rec in records:
                if (stream, rid) in self._inflight_ids:
                    continue
                self._inflight_ids.add((stream, rid))
                out.append((rid, rec))
        return out

    def _release_inflight(self, ids, stream=None):
        stream = stream or self.stream
        with self._inflight_lock:
            self._inflight_ids.difference_update(
                (stream, rid) for rid in ids)

    def _read_streams(self) -> List[str]:
        """The streams this engine reads right now: the single base
        stream, or (partitioned) the set it currently holds leases on
        — possibly empty while a newcomer waits for incumbents to shed
        its fair share."""
        if self.lease_table is None:
            return [self.stream]
        return self.lease_table.owned_streams()

    def _stream_backlog(self) -> Optional[int]:
        """Rate-limited broker stream depth MINUS this engine's own
        in-flight records (the stream keeps a record until sink commit,
        so raw depth would read our own pipeline back as other
        people's load and misclassify a light trickle as heavy — the
        adaptive batcher would then re-add the padding wait it exists
        to remove). Partitioned engines sum across the streams they
        own — the load THIS engine must plan for. Reader-thread only.
        None = unknown (transport without XLEN, or a mid-outage read)
        — the controller then plans conservatively."""
        now = time.monotonic()
        if now - self._backlog_t >= 0.2:
            self._backlog_t = now
            try:
                depth = sum(int(self.reader_broker.stream_depth(s))
                            for s in self._read_streams())
            except Exception:  # noqa: BLE001 — load signal, not a fault
                depth = None
            self._backlog_cache = depth
        if self._backlog_cache is None:
            return None
        with self._inflight_lock:
            own = len(self._inflight_ids)
        return max(0, self._backlog_cache - own)

    def _tier_order_and_shed(self, records, t0, src=None):
        """Tiered scheduling in the reader (ISSUE 11): higher-tier
        records decode and dispatch first (a stable sort — FIFO within
        a tier), and under overload (stream depth past `shed_backlog`)
        the lowest-tier records in hand are shed with an explicit
        "SHED" result — committed and acked through the normal sink
        path, so the client gets an answer instead of a timeout and the
        record never redelivers to eat capacity twice. The top tier is
        never shed: a fleet drowning in premium traffic scales (the
        autoscaler's job), it does not drop."""
        levels = [self.tier_table.level(
            (rec.get(self.admission_field) or rec.get("tier"))
            if isinstance(rec, dict) else None)
            for _rid, rec in records]
        order = sorted(range(len(records)), key=lambda i: -levels[i])
        records = [records[i] for i in order]
        levels = [levels[i] for i in order]
        if self.shed_backlog is None:
            return records
        backlog = self._stream_backlog()
        if backlog is None or backlog <= self.shed_backlog:
            return records
        lowest = min(levels)
        if lowest >= self.tier_table.top:
            return records
        keep, shed = [], []
        for (rid, rec), lvl in zip(records, levels):
            (shed if lvl == lowest else keep).append((rid, rec))
        if shed:
            tier = self.tier_table.name(lowest)
            self._admission_out.inc(len(shed), outcome="shed",
                                    tier=tier, **self._labels)
            log.warning(
                "overload (backlog %d > %d): shedding %d %r-tier "
                "record(s) with SHED results", backlog,
                self.shed_backlog, len(shed), tier)
            self._enqueue(self._sink_q, _Batch(
                [rid for rid, _ in shed],
                [rec.get("uri", rid) if isinstance(rec, dict)
                 else str(rid) for rid, rec in shed],
                None, t0, shed=True, stream=src))
        return keep

    def _trace_wire(self, records):
        """Continue the client's trace context (ISSUE 17): a record
        stamped with ``{"trace": {"ts": <wall>}}`` gets a "wire" span
        from its client-side ingest to this reader's claim. Duration
        comes from wall-clock DELTA on both ends (skew-bounded by
        `max(0, ...)`); the collector re-anchors it against the
        engine's minimum observed delta, so cross-host skew cancels
        instead of corrupting the merged timeline."""
        t_read = time.perf_counter()
        wall = time.time()
        for rid, rec in records:
            if not isinstance(rec, dict):
                continue
            ctx = rec.get("trace")
            if not isinstance(ctx, dict):
                continue
            try:
                t_ing = float(ctx["ts"])
            except (KeyError, TypeError, ValueError):
                continue
            d = max(0.0, wall - t_ing)
            args: Dict[str, Any] = {"t_ingest": t_ing,
                                    "t_read_wall": wall}
            if ctx.get("parent"):
                args["parent"] = ctx["parent"]
            if self._labels:
                args.update(self._labels)
            self.tracer.add_span(
                "wire", t_read - d, t_read,
                trace_id=rec.get("uri", str(rid)),
                cat="serving.wire", args=args)

    # -- stage: reader -----------------------------------------------------
    def _reader_loop(self):
        # idle wait is LONG (an XADD wakes a blocked XREADGROUP
        # immediately, so latency doesn't suffer): a short block here
        # would hammer the broker with nil reads that contend with the
        # sink's writes and the clients' polls for the whole run
        idle_block = max(self.batch_timeout_ms, 50)
        failures = 0
        last_logged = None         # (breaker state) at last warning
        # claim pacing is PER STREAM: one global clock aliases against
        # the rotation when the rotation period divides the claim
        # interval (2 owned streams x half the idle block == exactly
        # claim_interval_s), and every sweep then lands on the SAME
        # partition — a dead peer's other partitions never drain
        next_claim: Dict[str, float] = {}
        first_claim = time.monotonic() + self.claim_interval_s
        next_lease = 0.0           # first pass acquires immediately
        rr = 0                     # round-robin cursor over owned streams
        while not self._stop.is_set():
            # partition lease upkeep (ISSUE 16), BEFORE the pause gate:
            # a rollout drain must keep renewing or the pause itself
            # would forfeit this engine's partitions to its peers
            if self.lease_table is not None \
                    and time.monotonic() >= next_lease:
                next_lease = time.monotonic() + self._lease_poll_s
                try:
                    self.lease_table.poll()
                except Exception as e:  # noqa: BLE001 — ttl absorbs it
                    log.warning(
                        "partition lease poll failed (%s: %s); "
                        "retrying next interval", type(e).__name__, e)
            if self._intake_paused.is_set():
                # rollout drain (ISSUE 14): no reads, no claim sweeps —
                # in-hand work flows out while the swap waits on
                # quiesce(); a timed wait so stop() still cuts through
                self._stop.wait(0.05)
                continue
            streams = self._read_streams()
            if not streams:
                # newcomer awaiting its fair share: the next lease poll
                # acquires what incumbents shed
                self._stop.wait(0.05)
                continue
            # one source stream per cycle (rotating): a read batch —
            # and every _Batch cut from it — belongs to exactly one
            # partition, so the sink acks against the right PEL. The
            # idle block splits across owned streams to keep worst-case
            # first-byte latency at one full block window.
            src = streams[rr % len(streams)]
            rr += 1
            block = idle_block if len(streams) == 1 \
                else max(5, idle_block // len(streams))
            try:
                records = self.reader_broker.read_group(
                    src, GROUP, self.consumer, self.batch_size,
                    block_ms=block)
                if failures:
                    # back from an outage: ONE info line + the counter,
                    # mirroring the one-warning-per-transition cap below
                    self._reconnects.inc(role="reader")
                    log.info("reader reconnected after %d failed "
                             "attempt(s)", failures)
                    failures = 0
                    last_logged = None
                if time.monotonic() >= next_claim.get(src, first_claim):
                    # stale-pending claim sweep (ISSUE 10): a killed
                    # peer's delivered-but-unacked entries become this
                    # engine's work once idle past the claim window.
                    # Paced by the read block above (never a busy loop)
                    # and its OWN failure domain, like the straggler
                    # sweep: brokers without the claim op, or a claim
                    # that dies mid-outage, must not cost the records
                    # already in hand.
                    # partitioned engines sweep the cycle's source
                    # stream (per-stream pacing covers the set; takeover
                    # of a dead peer's WHOLE partition is the lease
                    # table's job, after which this sweep drains its PEL)
                    next_claim[src] = time.monotonic() \
                        + self.claim_interval_s
                    try:
                        claimed = self.reader_broker.claim_stale(
                            src, GROUP, self.consumer,
                            int(self.claim_min_idle_s * 1000),
                            self.batch_size)
                    except NotImplementedError:
                        claimed = []
                    except Exception as e:  # noqa: BLE001 — keep batch
                        claimed = []
                        log.warning(
                            "claim sweep failed (%s: %s); retrying next "
                            "interval", type(e).__name__, e)
                    if claimed:
                        claimed = self._filter_inflight(claimed, src)
                    if claimed:
                        self._claimed_records.inc(len(claimed),
                                                  **self._labels)
                        log.info("claimed %d stale pending record(s) "
                                 "from dead peer consumer(s)",
                                 len(claimed))
                else:
                    claimed = []
                records = claimed + self._filter_inflight(records, src)
                if not records:
                    continue
                # adaptive accumulation (ISSUE 11; the straggler sweep,
                # generalized): the controller plans how many records
                # this dispatch should carry and how long the reader may
                # keep collecting — under a tight deadline or an empty
                # backlog that is "none, dispatch now"; under load it is
                # "grow toward the throughput-optimal bucket". Collection
                # reads run in their OWN failure domain: a broker that
                # dies mid-sweep must not drop the records already in
                # hand into a redeliver loop.
                t_first = time.perf_counter()
                plan = self.batcher.plan(len(records), 0.0,
                                         self._stream_backlog())
                sweep_deadline = t_first + plan.wait_ms / 1e3
                while len(records) < plan.target:
                    remaining_ms = (sweep_deadline
                                    - time.perf_counter()) * 1e3
                    if remaining_ms <= 0:
                        break
                    try:
                        more = self._filter_inflight(
                            self.reader_broker.read_group(
                                src, GROUP, self.consumer,
                                plan.target - len(records),
                                block_ms=max(1, int(min(remaining_ms,
                                                        50)))), src)
                    except Exception as e:  # noqa: BLE001 — keep batch
                        log.warning(
                            "batch-collection read failed (%s: %s); "
                            "continuing with %d record(s) in hand",
                            type(e).__name__, e, len(records))
                        break
                    if more:
                        records += more
                        # replan: the budget shrinks as the oldest
                        # record ages, so this loop always terminates
                        age_ms = (time.perf_counter() - t_first) * 1e3
                        plan = self.batcher.plan(
                            len(records), age_ms, self._stream_backlog())
                        sweep_deadline = min(
                            sweep_deadline,
                            time.perf_counter() + plan.wait_ms / 1e3)
                with self._counter_lock:
                    self.records_read += len(records)
                self._records_total.inc(len(records), outcome="read",
                                        **self._labels)
                if self.tier_table is not None:
                    records = self._tier_order_and_shed(records, t_first,
                                                        src)
                    if not records:
                        continue
                if self.tracer is not None:
                    self._trace_wire(records)
                item = (t_first, records, src)
                while not self._stop.is_set():
                    try:
                        self._decode_q.put(item, timeout=0.25)
                        break
                    except queue.Full:
                        continue
                # stop while blocked: records stay unacked → redeliver
            except Exception as e:  # noqa: BLE001 — the Flink-restart role
                # transient broker failures (redis stall/restart) must
                # not kill the stage; the breaker owns fast-failing and
                # the backoff paces reconnect attempts. Log spam is
                # capped to one warning per breaker state transition.
                failures += 1
                breaker = getattr(self.reader_broker, "breaker", None)
                state = breaker.state if breaker is not None else None
                if state != last_logged:
                    log.warning(
                        "reader cycle failed (%s: %s); breaker %s, "
                        "backing off", type(e).__name__, e,
                        state or "n/a")
                    last_logged = state
                self._stop.wait(self.reader_backoff.delay(failures))

    # -- stage: decode -----------------------------------------------------
    def _decode_records(self, records):
        """Per-record decode straight into PREALLOCATED bucket-shaped
        batch buffers, shared by the pipelined decode stage and the
        legacy synchronous loop (ISSUE 9 serving satellite).

        Records group by (shape, dtype) read off the codec HEADER —
        no payload decode yet — then each group sizes ONE
        ``[bucket, *shape]`` buffer (`batcher.pad_bucket` — policy-aware
        since ISSUE 11; padding included)
        and every payload decodes directly into its row
        (`pre_post.decode_record_into`): the hot path allocates zero
        per-record ndarrays and the dispatch stage's separate np.stack
        pass is gone. Headerless codecs (arrow/image/list) decode
        first and pay one row copy — same cost as the old path.

        Returns ``(batches, failed)``: [(ids, uris, buf, n_real)] with
        rows [n_real:] pre-padded, plus the [(rid, uri)] records that
        failed to decode (degrade to "NaN")."""
        from analytics_zoo_tpu.serving.pre_post import (decode_record_field,
                                                        decode_record_into,
                                                        record_meta)
        groups: dict = {}
        failed = []
        for rid, rec in records:
            try:
                data = rec["data"]
                # single-tensor fast path: field "t" or "image"
                field = "t" if "t" in data else (
                    "image" if "image" in data else next(iter(data)))
                value = data[field]
                meta = record_meta(value)
                if meta is None:
                    value = decode_record_field(value)
                    meta = (value.shape, value.dtype.str)
                groups.setdefault(meta, []).append((rid, rec["uri"],
                                                    value))
            except Exception as e:  # noqa: BLE001 — degrade per record
                # rec itself may be malformed (a foreign producer can
                # XADD any JSON): the failure path must not raise, or one
                # poison record would drop its whole read batch into a
                # redeliver loop
                uri = rec.get("uri", rid) if isinstance(rec, dict) \
                    else str(rid)
                log.warning("decode failure for %s: %s", uri, e)
                failed.append((rid, uri))
        batches = []
        for (shape, dtype), items in groups.items():
            bucket = self.batcher.pad_bucket(len(items))
            try:
                # header shape/dtype are UNTRUSTED producer input (a
                # foreign client can XADD shape [-1] or an absurd dim):
                # an allocation failure degrades THIS group to NaN —
                # well-formed records in other groups must still serve
                buf = np.empty((max(bucket, len(items)),) + tuple(shape),
                               np.dtype(dtype))
            except Exception as e:  # noqa: BLE001 — degrade per group
                for rid, uri, _ in items:
                    log.warning("decode failure for %s: %s", uri, e)
                    failed.append((rid, uri))
                continue
            ids, uris = [], []
            for rid, uri, value in items:
                try:
                    # rows compact on failure: the row cursor advances
                    # only when a payload lands
                    if isinstance(value, np.ndarray):
                        buf[len(ids)] = value
                    else:
                        decode_record_into(value, buf[len(ids)])
                except Exception as e:  # noqa: BLE001 — degrade per rec
                    log.warning("decode failure for %s: %s", uri, e)
                    failed.append((rid, uri))
                    continue
                ids.append(rid)
                uris.append(uri)
            if not ids:
                continue
            buf[len(ids):] = buf[len(ids) - 1]   # stack-free bucket pad
            batches.append((ids, uris, buf, len(ids)))
        return batches, failed

    def _decode_records_legacy(self, records):
        """The pre-ISSUE-9 per-record decode (one ndarray allocation per
        record; the dispatch stage stacks). Kept ONLY as the
        `zero_copy_decode=False` baseline the bench_serving decode A/B
        measures against. Returns ``(by_shape, failed)``."""
        from analytics_zoo_tpu.serving.pre_post import decode_record_field
        by_shape: dict = {}
        failed = []
        for rid, rec in records:
            try:
                data = rec["data"]
                field = "t" if "t" in data else (
                    "image" if "image" in data else next(iter(data)))
                arr = decode_record_field(data[field])
                by_shape.setdefault(arr.shape, []).append(
                    (rid, rec["uri"], arr))
            except Exception as e:  # noqa: BLE001 — degrade per record
                uri = rec.get("uri", rid) if isinstance(rec, dict) \
                    else str(rid)
                log.warning("decode failure for %s: %s", uri, e)
                failed.append((rid, uri))
        return by_shape, failed

    def _decode_loop(self):
        while True:
            try:
                item = self._decode_q.get(timeout=1.0)
            except queue.Empty:
                continue               # exit is by pill, not timeout
            if item is _STOP:
                return
            t0, records, src = item
            tr = self.tracer
            uris = _record_uris(records) if tr is not None else None
            if tr is not None:
                # queue wait: broker read (t0) -> this dequeue
                tr.add_span("decode_q_wait", t0, time.perf_counter(),
                            cat="serving.queue", trace_ids=uris)
            try:
                t_work = time.perf_counter()
                if self.zero_copy_decode:
                    batches, failed = self._decode_records(records)
                else:
                    by_shape, failed = self._decode_records_legacy(records)
                    batches = None
                if failed:
                    self._enqueue(self._sink_q, _Batch(
                        [rid for rid, _ in failed],
                        [uri for _, uri in failed], None, t0, nan=True,
                        stream=src))
                    if self.trace_exporter is not None:
                        # failures export their traces regardless of
                        # head sampling — the requests worth debugging
                        self.trace_exporter.force(
                            [uri for _, uri in failed])
                if batches is not None:
                    for ids, uris, buf, n in batches:
                        self._enqueue(self._dispatch_q, _Batch(
                            ids, uris, None, t0, stacked=buf, valid_n=n,
                            stream=src))
                else:
                    for items in by_shape.values():
                        self._enqueue(self._dispatch_q, _Batch(
                            [rid for rid, _, _ in items],
                            [uri for _, uri, _ in items],
                            [a for _, _, a in items], t0, stream=src))
                t_end = time.perf_counter()
                self.decode_timer.record(t_end - t_work)
                if tr is not None:
                    tr.add_span("decode", t_work, t_end, trace_ids=uris,
                                args=dict(self._labels) or None)
            except Exception as e:  # noqa: BLE001 — stage must survive
                # the dropped batch stays unacked, so the broker WILL
                # redeliver it — release its ids or _filter_inflight
                # would suppress that redelivery forever
                self._release_inflight([rid for rid, _ in records], src)
                log.error("decode stage failed for a read batch: %s", e)

    # -- stage: dispatch ---------------------------------------------------
    def _dispatch_loop(self):
        while True:
            try:
                batch = self._dispatch_q.get(timeout=1.0)
            except queue.Empty:
                continue               # exit is by pill, not timeout
            if batch is _STOP:
                return
            tr = self.tracer
            if tr is not None:
                tr.add_span("dispatch_q_wait", batch.t_enq,
                            time.perf_counter(), cat="serving.queue",
                            trace_ids=batch.uris)
            try:
                t_work = time.perf_counter()
                if batch.stacked is not None:
                    # zero-copy decode already assembled the
                    # bucket-shaped buffer — nothing to stack here
                    n = batch.valid_n
                    stacked = batch.stacked
                    batch.stacked = None
                else:
                    n = len(batch.arrays)
                    bucket = self.batcher.pad_bucket(n)
                    arrs = batch.arrays
                    if bucket > n:
                        # stack straight to the bucket: padding costs
                        # nothing extra (the stack copies anyway) and
                        # predict_async skips its device-side pad
                        arrs = arrs + [arrs[-1]] * (bucket - n)
                    stacked = np.stack(arrs)
                    batch.arrays = None
                # async: returns before the device finishes — the
                # sink materializes while we stack the next batch.
                # With EVERY replica quarantined the router fails fast;
                # the batch PARKS here (capacity loss, not correctness
                # loss) until a canary revival — or NaN-degrades if the
                # engine is stopping.
                while True:
                    try:
                        batch.pending = self.model.predict_async(
                            stacked, valid_n=n)
                        break
                    except NoHealthyReplicaError:
                        if self._stop.is_set():
                            raise
                        self._stop.wait(0.05)
                t_end = time.perf_counter()
                self.dispatch_timer.record(t_end - t_work)
                # elastic telemetry (ISSUE 11): the chosen bucket and
                # how much deadline budget queueing+batching consumed
                # before this dispatch — what the controller's next
                # plans and the bench's queue-age story read
                batch.bucket = int(stacked.shape[0])
                batch.t_dispatch = t_end
                self.batcher.record_dispatch(
                    batch.bucket, (t_end - batch.t0) * 1e3)
                replica = getattr(batch.pending, "replica", 0)
                if self._multi_replica and replica is not None:
                    self._replica_batches.inc(replica=str(replica))
                if tr is not None:
                    # replica tag only in multi-device mode, engine tag
                    # only in fleet mode: the default single-replica
                    # standalone trace schema stays unchanged
                    span_args = dict(self._labels)
                    if self._multi_replica and replica is not None:
                        span_args["replica"] = replica
                    tr.add_span("dispatch", t_work, t_end,
                                trace_ids=batch.uris,
                                args=span_args or None)
                self._enqueue(self._sink_q, batch)
            except Exception as e:  # noqa: BLE001 — stream must survive
                log.error("dispatch failure for batch of %d: %s",
                          len(batch.uris), e)
                batch.arrays = None
                batch.stacked = None
                batch.nan = True
                self._enqueue(self._sink_q, batch)

    # -- stage: sink -------------------------------------------------------
    def _sink_loop(self):
        """Materialize and write back in COMPLETION order, not dispatch
        order: with a replica pool, batch N+1 on an idle device finishes
        while batch N still computes elsewhere — FIFO materialization
        would park the sink on the slowest replica and stall every other
        chip's finished work (and one poisoned replica would dam the
        stream). Batches are pulled greedily off the queue into a waiting
        set; whichever `PendingPrediction` reports `done()` first is
        written first. Per-batch writeback, NaN degradation, and ack
        semantics are unchanged."""
        waiting: List[_Batch] = []
        stop_seen = False
        # the completion-scan window is bounded at queue_depth: past the
        # cap the sink stops pulling, _sink_q fills, and dispatch blocks
        # on its put — the documented sink backpressure survives the
        # completion-order rework (without the cap, a fast dispatcher on
        # an async backend would pile unbounded un-materialized device
        # results into this list). On stop the cap lifts to drain.
        cap = max(2, self.queue_depth)
        while True:
            batch = None
            try:
                if not (waiting or stop_seen):
                    # idle: block in bounded slices so buffered
                    # writebacks still get flush attempts while no new
                    # work arrives (a broker that comes back during a
                    # quiet period must not wait for the next request)
                    batch = self._sink_q.get(timeout=0.1)
                elif stop_seen or len(waiting) < cap:
                    batch = self._sink_q.get_nowait()
            except queue.Empty:
                if self._wb_buffer:
                    self._flush_writebacks()
                if not (waiting or stop_seen):
                    continue
            if batch is not None:
                if batch is _STOP:
                    stop_seen = True
                else:
                    if self.tracer is not None:
                        self.tracer.add_span(
                            "sink_q_wait", batch.t_enq,
                            time.perf_counter(), cat="serving.queue",
                            trace_ids=batch.uris)
                    # sink span base: from here on, time spent is the
                    # device wait + materialize + writeback for this
                    # batch
                    batch.t_enq = time.perf_counter()
                    waiting.append(batch)
                continue
            ready = [b for b in waiting
                     if b.nan or b.pending is None or b.pending.done()]
            if not ready and waiting and \
                    (stop_seen or not self._multi_replica
                     or (len(waiting) == 1 and self._sink_q.empty())):
                # block in result() on the oldest instead of polling:
                # on stop (drain), with a single device stream (one
                # replica / sharded — completion order IS dispatch
                # order, so this is exactly the pre-router sink, no
                # poll tax on the default path), or when only one
                # batch is in flight anyway
                ready = [waiting[0]]
            for b in ready:
                waiting.remove(b)
                self._sink_one(b)
            if stop_seen and not waiting:
                # one last flush: results computed during an outage
                # land if the broker is back; the rest stay unacked
                # for redelivery after restart
                if self._wb_buffer:
                    self._flush_writebacks()
                    if self._wb_buffer:
                        log.warning(
                            "stopping with %d writeback batch(es) "
                            "still unflushed; their records are "
                            "unacked and will redeliver",
                            len(self._wb_buffer))
                return
            if waiting and not ready:
                time.sleep(0.0005)     # all in flight; poll done() soon

    def _sink_one(self, batch: _Batch):
        """Materialize one batch, then write back — or buffer the
        writeback when the broker is down. Materialization errors
        degrade to "NaN" inside `_materialize`; from here on the only
        failure mode is the broker, and the buffer owns that."""
        if self._killed:
            # kill() (crash analogue): a dead process commits nothing —
            # the batch's records stay unacked for peer takeover. A
            # routed pending still holds a replica permit that only
            # consumption releases; abandon it like _poison does.
            abandon = getattr(batch.pending, "abandon", None)
            if abandon is not None:
                abandon()
            return
        t_work = batch.t_enq
        values = self._materialize(batch)
        if self.tracer is not None and not (batch.nan or batch.shed):
            # the device wait + readback half of the sink: what the
            # critical-path "device" column reads (dispatch only SUBMITS;
            # this is where the batch's result actually lands on host)
            self.tracer.add_span("device", t_work, time.perf_counter(),
                                 cat="serving.device",
                                 trace_ids=batch.uris,
                                 args=dict(self._labels) or None)
        if batch.bucket is not None and batch.t_dispatch is not None \
                and not (batch.nan or batch.shed):
            # feed the live cost model: dispatch → materialized is what
            # a queued record pays once it boards this bucket
            self.batcher.observe_service(
                batch.bucket,
                (time.perf_counter() - batch.t_dispatch) * 1e3)
        entry = (dict(zip(batch.uris, values)), list(batch.ids),
                 batch.t0, t_work, batch.shed,
                 batch.stream or self.stream)
        if self._wb_buffer:
            # keep writeback order: flush the backlog first, and if any
            # of it still can't go out, queue behind it
            self._flush_writebacks()
        if self._wb_buffer or not self._write_entry(entry):
            self._buffer_writeback(entry)

    def _write_entry(self, entry, own_retry: bool = False) -> bool:
        """One batched writeback + ack; False (no raise) on a broker
        failure. Counters/timers record only on success — a buffered
        batch records its FULL latency (outage included) when it
        finally lands. `own_retry` marks a flush of THIS engine's
        buffered entry: an ambiguous partial commit (HSET applied,
        reply lost, pipeline raised) leaves the fields present, so the
        retry's new-field count reads 0 — but the records were served
        exactly once by this engine's compute and must count as
        served, not duplicate."""
        mapping, ids, t0, t_work, shed = entry[:5]
        # pre-partition entries (tests, a buffer that survived an
        # upgrade) carry no stream element: they mean the base stream
        stream = entry[5] if len(entry) > 5 else self.stream
        t_wb = time.perf_counter()
        try:
            # the whole batch commits as ONE broker interaction —
            # results + ack in a single (pipelined) round trip, not
            # N+1, not even 3: round-trip latency is what caps sink
            # throughput when the broker host is loaded
            added = self.sink_broker.writeback(
                self.result_key, mapping, stream, GROUP, ids)
            self._release_inflight(ids, stream)
        except Exception as e:  # noqa: BLE001 — the buffer owns retries
            if not self._sink_down:
                # one warning per outage, not per batch (the breaker
                # logs its own transitions)
                log.warning(
                    "sink writeback failed for %d records (%s: %s); "
                    "buffering until the broker returns",
                    len(mapping), type(e).__name__, e)
                self._sink_down = True
            return False
        t_end = time.perf_counter()
        self.sink_timer.record(t_end - t_work)
        if self.tracer is not None:
            # includes the device wait inside _materialize — the
            # only blocking readback in the pipeline
            tr_ids = list(mapping)
            self.tracer.add_span("sink", t_work, t_end,
                                 trace_ids=tr_ids,
                                 args=dict(self._labels) or None)
            # the broker-commit tail on its own row: the critical-path
            # "writeback" column (results + ack round trip)
            self.tracer.add_span("writeback", t_wb, t_end,
                                 cat="serving.sink", trace_ids=tr_ids,
                                 args=dict(self._labels) or None)
        # idempotent writeback (ISSUE 10): HSET reports how many fields
        # were NEW. A redelivered record whose result another engine (or
        # an earlier life of this one) already wrote is an overwrite of
        # the same deterministic value — correct data, but it must not
        # double-count as served. The broker's own new-field count is
        # the only dedup that works ACROSS engines. An own-buffered
        # retry is the exception (see docstring): its records count as
        # served regardless of the overwrite count. (If a peer ALSO
        # claimed and wrote them during a long outage, the fleet sum
        # over-counts that overlap — a double fault traded for not
        # silently deflating every single-engine outage recovery.)
        if own_retry:
            added = len(mapping)
        n_new = added if isinstance(added, int) else len(mapping)
        n_dup = len(mapping) - n_new
        if shed:
            # an answered rejection is NOT service (ISSUE 11): counting
            # shed commits as "served" — and their near-zero commit
            # times into the batch timer — would read overload as
            # improved availability/latency and suppress the very SLO
            # burn the autoscaler scales up on. Distinct outcome, no
            # latency sample, no served count.
            if n_new:
                self._records_total.inc(n_new, outcome="shed",
                                        **self._labels)
            return True
        with self._counter_lock:
            self.records_served += n_new
        if n_new:
            self._records_total.inc(n_new, outcome="served",
                                    **self._labels)
        if n_dup:
            self._records_total.inc(n_dup, outcome="duplicate",
                                    **self._labels)
        # NaN-degraded records count as "failed" alongside (not instead
        # of) "served" — the SLO availability window reads
        # (served - failed) / served. A fully-duplicate batch (a
        # redelivery whose results were all already written) skips the
        # count: its NaNs were counted by the first writer, and
        # re-counting them would skew availability down on every
        # crash-redelivery. (A partially-new batch counts all its NaNs
        # — HSET's new-field total can't attribute WHICH fields were
        # new, and the mixed case needs a mid-batch crash to occur.)
        nan_n = sum(1 for v in mapping.values() if v == "NaN")
        if nan_n and n_new:
            self._records_total.inc(nan_n, outcome="failed",
                                    **self._labels)
        self.batch_timer.record(t_end - t0)
        if self.trace_exporter is not None:
            # forced sampling (ISSUE 17): failed and SLO-violating
            # requests always ship their spans — head sampling decides
            # the happy path, never the requests worth debugging
            if self.slo is not None \
                    and self.slo.objectives.latency_ms is not None \
                    and (t_end - t0) * 1e3 > self.slo.objectives.latency_ms:
                self.trace_exporter.force(list(mapping))
            elif nan_n:
                self.trace_exporter.force(
                    [u for u, v in mapping.items() if v == "NaN"])
        return True

    def _buffer_writeback(self, entry):
        """Bounded: past `sink_buffer_batches` the OLDEST entry is shed
        and counted — its records were never acked, so the broker
        redelivers them after its pending window (duplicate work, never
        loss)."""
        self._wb_buffer.append(entry)
        while len(self._wb_buffer) > self.sink_buffer_batches:
            shed = self._wb_buffer.popleft()
            self._shed_records.inc(len(shed[0]))
            # shed records must be re-readable: release their ids so a
            # redelivery (this engine or a claiming peer) isn't filtered
            # out as already-in-flight
            self._release_inflight(
                shed[1], shed[5] if len(shed) > 5 else None)
            log.warning(
                "sink buffer overflow: shed a writeback of %d records "
                "(unacked; the broker will redeliver)", len(shed[0]))

    def _flush_writebacks(self):
        """Drain the buffered writebacks in order; stops at the first
        entry the broker still refuses (the breaker makes that a fast
        fail while the circuit is open)."""
        flushed = False
        while self._wb_buffer:
            if not self._write_entry(self._wb_buffer[0], own_retry=True):
                return
            self._wb_buffer.popleft()
            flushed = True
        if flushed and self._sink_down:
            self._sink_down = False
            self._reconnects.inc(role="sink")
            log.info("sink reconnected; buffered writebacks flushed")

    def _materialize(self, batch) -> List[str]:
        """Per-record encoded result strings for a batch; inference
        failure degrades the whole batch to "NaN" (the per-shape batch is
        the reference's failure unit, `ClusterServingInference.scala:71`)."""
        if batch.shed:
            # admission shed (ISSUE 11): an answered rejection — the
            # client sees "SHED" (degrades like NaN in the decoders but
            # is distinguishable on the wire), the ack keeps the broker
            # from redelivering work the engine chose not to do
            return ["SHED"] * len(batch.uris)
        if batch.nan:
            if batch.pending is not None:
                # a batch can be marked nan AFTER routing succeeded (a
                # dispatch-stage failure past predict_async): the routed
                # pending still holds a replica permit that only
                # result() releases — drain it or the replica is
                # permanently down a slot
                try:
                    batch.pending.result()
                except Exception:  # noqa: BLE001 — already degrading
                    pass
            return ["NaN"] * len(batch.uris)
        try:
            preds = batch.pending.result()
        except Exception as e:  # noqa: BLE001 — stream must survive
            log.error("inference failure for batch of %d: %s",
                      len(batch.uris), e)
            return ["NaN"] * len(batch.uris)
        values = []
        hops = None
        if self.trace_exporter is not None:
            # per-hop timing summary riding the writeback row (ISSUE
            # 17): engine-internal MONOTONIC durations only — a client
            # on another host can attribute its e2e latency without any
            # cross-clock arithmetic (e2e - engine_ms = wire + broker)
            now = time.perf_counter()
            t_disp = batch.t_dispatch if batch.t_dispatch is not None \
                else now
            hops = {"engine": self._labels.get("engine", self.consumer),
                    "engine_ms": round((now - batch.t0) * 1e3, 3),
                    "queue_ms": round((t_disp - batch.t0) * 1e3, 3),
                    "device_ms": round((now - t_disp) * 1e3, 3)}
        for pred in list(preds)[:len(batch.uris)]:
            try:
                if self.output_filter:
                    from analytics_zoo_tpu.serving.pre_post import \
                        apply_filter
                    values.append(apply_filter(np.asarray(pred),
                                               self.output_filter))
                else:
                    blob = encode_ndarray(np.asarray(pred))
                    if hops is not None:
                        blob["hops"] = hops
                    values.append(json.dumps(blob))
            except Exception as e:  # noqa: BLE001 — degrade per record
                log.warning("encode failure: %s", e)
                values.append("NaN")
        return values

    # -- legacy synchronous loop (pipelined=False, serve_once) -------------
    def run(self):
        while not self._stop.is_set():
            try:
                self.serve_once()
            except Exception as e:  # noqa: BLE001 — the Flink-restart role
                log.warning("serving cycle failed (%s: %s); retrying",
                            type(e).__name__, e)
                self._stop.wait(1.0)

    def serve_once(self) -> int:
        """One synchronous drain->batch->predict->sink cycle (the
        pre-pipeline behavior; also handy for tests and notebooks)."""
        records = self.broker.read_group(
            self.stream, GROUP, self.consumer, self.batch_size,
            block_ms=self.batch_timeout_ms)
        if not records:
            return 0
        with self._counter_lock:
            self.records_read += len(records)
        self._records_total.inc(len(records), outcome="read",
                                **self._labels)
        t0 = time.perf_counter()
        self._process(records)
        self.broker.ack(self.stream, GROUP, [rid for rid, _ in records])
        with self._counter_lock:
            self.records_served += len(records)
        self._records_total.inc(len(records), outcome="served",
                                **self._labels)
        t_end = time.perf_counter()
        self.batch_timer.record(t_end - t0)
        if self.tracer is not None:
            # the sync loop is one fused stage: a single span per cycle
            self.tracer.add_span("serve_once", t0, t_end,
                                 trace_ids=_record_uris(records))
        return len(records)

    def _process(self, records):
        # per-record decode failure -> NaN without killing the batch; one
        # forward per shape-homogeneous sub-batch
        if self.zero_copy_decode:
            batches, failed = self._decode_records(records)
        else:
            by_shape, failed = self._decode_records_legacy(records)
            batches = [([rid for rid, _, _ in items],
                        [uri for _, uri, _ in items],
                        np.stack([a for _, _, a in items]), len(items))
                       for items in by_shape.values()]
        for _rid, uri in failed:
            self.broker.hset(self.result_key, uri, "NaN")
        if failed:
            self._records_total.inc(len(failed), outcome="failed",
                                    **self._labels)
        for _ids, uris, buf, n in batches:
            try:
                preds = self.model.predict(buf[:n])
                for uri, pred in zip(uris, preds):
                    if self.output_filter:
                        from analytics_zoo_tpu.serving.pre_post import \
                            apply_filter
                        value = apply_filter(np.asarray(pred),
                                             self.output_filter)
                    else:
                        value = json.dumps(encode_ndarray(np.asarray(pred)))
                    self.broker.hset(self.result_key, uri, value)
            except NoHealthyReplicaError:
                # transient whole-pool quarantine: park via redelivery
                # (serve_once never acks this read) — NaN-acking every
                # record through the outage would turn lost CAPACITY
                # into lost correctness, the opposite of the
                # quarantine contract
                raise
            except Exception as e:  # noqa: BLE001 — stream must survive
                log.error("inference failure for batch of %d (%s): %s",
                          n, tuple(buf.shape[1:]), e)
                for uri in uris:
                    self.broker.hset(self.result_key, uri, "NaN")
                self._records_total.inc(len(uris), outcome="failed",
                                        **self._labels)

    # -- metrics (`/metrics`, FrontEndApp.scala:241) -----------------------
    def metrics(self) -> dict:
        m = {
            "records_served": self.records_served,
            "records_read": self.records_read,
            "pipelined": self.pipelined,
            "serving_dtype": self.serving_dtype,
            "model_version": self.model_version,
            "batch": self.batch_timer.snapshot(),
            "predict": self.model.timer.snapshot(),
        }
        if self.engine_id is not None:
            m["engine_id"] = self.engine_id
            m["claimed_records"] = int(
                self._claimed_records.value(**self._labels))
        if self.lease_table is not None:
            m["partitions"] = {
                "total": self.partitions,
                "owned": self.lease_table.owned(),
            }
        if self.pipelined:
            m["stages"] = {
                "decode": self.decode_timer.snapshot(),
                "dispatch": self.dispatch_timer.snapshot(),
                "sink": self.sink_timer.snapshot(),
            }
            m["queue_depths"] = {
                "decode": self._decode_q.qsize(),
                "dispatch": self._dispatch_q.qsize(),
                "sink": self._sink_q.qsize(),
            }
        if self._multi_replica or getattr(self.model, "placement",
                                          "replicated") == "sharded":
            m["placement"] = self.model.placement_info()
            m["replicas"] = self.model.replica_stats()
        m["batching"] = {
            "policy": self.batcher.policy,
            "deadline_ms": self.batcher.deadline_ms,
            "bucket_cost_ms": {str(b): round(c, 3) for b, c in
                               self.batcher.cost.snapshot().items()},
            "backlog": self._backlog_cache,
        }
        if self.tier_table is not None:
            m["admission"] = {
                "tiers": list(self.tier_table.names),
                "shed_backlog": self.shed_backlog,
            }
        ft = {"sink_buffered_batches": len(self._wb_buffer)}
        for role, br in (("reader", self.reader_broker),
                         ("sink", self.sink_broker)):
            breaker = getattr(br, "breaker", None)
            if breaker is not None:
                ft[f"breaker_{role}"] = breaker.state
        if self.supervisor is not None:
            ft["supervisor"] = self.supervisor.stats()
        m["fault_tolerance"] = ft
        if self.slo is not None:
            try:
                m["slo"] = self.slo.evaluate()
            except Exception:  # noqa: BLE001 — metrics must always answer
                m["slo"] = None
        size_fn = getattr(self.model, "compile_cache_size", None)
        if size_fn is not None:
            # per-(replica, bucket) executable count, plus persistent-
            # cache traffic when the model is cache-backed
            cc_info = {"executables": size_fn()}
            cache = getattr(self.model, "compile_cache", None)
            if cache is not None:
                s = cache.stats()
                cc_info.update(hits=s["hits"], misses=s["misses"],
                               bytes=s["bytes"], entries=s["entries"])
            src = getattr(self.model, "warmup_source", None)
            if src:
                cc_info["warmup_source"] = dict(src)
            m["compile_cache"] = cc_info
        if self.trace_exporter is not None:
            m["trace"] = self.trace_exporter.stats()
        return m
