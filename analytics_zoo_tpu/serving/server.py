"""ClusterServing — the serving loop.

Reference: Flink job `RedisSource -> inference map -> RedisSink`
(`ClusterServing.scala:55-68`), batching up to core count
(`ClusterServingInference.scala:152` batchInput), singleton model per task
manager (`FlinkInference.scala:41-52`), per-record failures degrade to "NaN"
(`:71-79`). TPU redesign: one host thread drains the broker stream, groups
records into a batch (up to `batch_size`, waiting at most `batch_timeout_ms`
for stragglers), pads to the InferenceModel's shape bucket, runs the jit'd
forward once, and writes per-record results back — dynamic batching under a
latency SLO instead of Flink operator parallelism."""

from __future__ import annotations

import json
import logging
import threading
from typing import Optional, Union

import numpy as np

from analytics_zoo_tpu.serving.broker import (Broker, connect_broker,
                                              decode_ndarray, encode_ndarray,
                                              new_consumer_name)
from analytics_zoo_tpu.serving.inference_model import InferenceModel
from analytics_zoo_tpu.serving.timer import Timer

log = logging.getLogger("analytics_zoo_tpu.serving")

GROUP = "serving_group"


class ClusterServing:
    def __init__(self, model: InferenceModel,
                 broker: Union[Broker, str, None] = None,
                 stream: str = "serving_stream",
                 batch_size: int = 32, batch_timeout_ms: int = 5,
                 output_filter: Optional[str] = None):
        self.model = model
        self.broker = broker if isinstance(broker, Broker) \
            else connect_broker(broker)
        self.stream = stream
        # e.g. "topN(5)" — the reference's PostProcessing filter grammar;
        # validated here so a bad spec fails at construction, not as
        # per-record NaNs mid-stream
        if output_filter is not None:
            from analytics_zoo_tpu.serving.pre_post import apply_filter
            apply_filter(np.zeros(2, np.float32), output_filter)
        self.output_filter = output_filter
        self.result_key = f"result:{stream}"
        self.batch_size = batch_size
        self.batch_timeout_ms = batch_timeout_ms
        self.consumer = new_consumer_name()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.batch_timer = Timer("batch")
        self.records_served = 0

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ClusterServing":
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)

    def run(self):
        while not self._stop.is_set():
            try:
                self.serve_once()
            except Exception as e:  # noqa: BLE001 — the Flink-restart role
                # transient broker failures (redis stall/restart) must not
                # kill the serving thread; brokers reconnect on next use
                log.warning("serving cycle failed (%s: %s); retrying",
                            type(e).__name__, e)
                self._stop.wait(1.0)

    # -- one drain->batch->predict->sink cycle -----------------------------
    def serve_once(self) -> int:
        records = self.broker.read_group(
            self.stream, GROUP, self.consumer, self.batch_size,
            block_ms=self.batch_timeout_ms)
        if not records:
            return 0
        with self.batch_timer.timing():
            self._process(records)
        self.broker.ack(self.stream, GROUP, [rid for rid, _ in records])
        self.records_served += len(records)
        return len(records)

    def _process(self, records):
        # decode; per-record decode failure -> NaN without killing the batch
        from analytics_zoo_tpu.serving.pre_post import decode_record_field
        decoded = []
        for rid, rec in records:
            try:
                data = rec["data"]
                # single-tensor fast path: field "t" or "image"
                field = "t" if "t" in data else ("image" if "image" in data
                                                 else next(iter(data)))
                decoded.append((rec["uri"],
                                decode_record_field(data[field])))
            except Exception as e:  # noqa: BLE001 — degrade per record
                log.warning("decode failure for %s: %s", rec.get("uri"), e)
                self.broker.hset(self.result_key, rec.get("uri", rid), "NaN")

        if not decoded:
            return
        # group by shape so one forward serves each homogeneous sub-batch
        by_shape = {}
        for uri, arr in decoded:
            by_shape.setdefault(arr.shape, []).append((uri, arr))
        for shape, items in by_shape.items():
            batch = np.stack([a for _, a in items])
            try:
                preds = self.model.predict(batch)
                for (uri, _), pred in zip(items, preds):
                    if self.output_filter:
                        from analytics_zoo_tpu.serving.pre_post import \
                            apply_filter
                        value = apply_filter(np.asarray(pred),
                                             self.output_filter)
                    else:
                        value = json.dumps(encode_ndarray(np.asarray(pred)))
                    self.broker.hset(self.result_key, uri, value)
            except Exception as e:  # noqa: BLE001 — stream must survive
                log.error("inference failure for batch %s: %s", shape, e)
                for uri, _ in items:
                    self.broker.hset(self.result_key, uri, "NaN")

    # -- metrics (`/metrics`, FrontEndApp.scala:241) -----------------------
    def metrics(self) -> dict:
        return {
            "records_served": self.records_served,
            "batch": self.batch_timer.snapshot(),
            "predict": self.model.timer.snapshot(),
        }
